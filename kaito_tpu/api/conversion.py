"""Legacy API version conversion (hub-and-spoke).

The analogue of the reference's conversion webhooks
(``api/v1alpha1/ragengine_conversion.go``, ``workspace_conversion.go``):
``kaito-tpu.io/v1`` is the hub; legacy ``v1alpha1`` wire objects
upgrade in place before decoding, so old manifests keep applying after
the API graduates.  Shape changes mirrored from the reference:

- RAGEngine storage: v1alpha1 FLAT ``{persistentVolumeClaim,
  mountPath}`` -> v1 nested ``storage.persistentVolume{...}``.
- RAGEngine inference service: v1alpha1 ``inferenceService.URL`` (the
  Go JSON tag capitalizes) -> v1 ``inferenceService.url``.
- Workspace tuning method casing: v1alpha1 ``qlora``/``lora`` ->
  v1 ``QLoRA``/``LoRA`` preset names pass through unchanged.

Unknown fields pass through untouched — conversion must never drop
fields it does not understand (round-trip safety, the property the
reference encodes in its conversion fuzz tests).
"""

from __future__ import annotations

import copy

LEGACY_VERSIONS = ("kaito-tpu.io/v1alpha1",)
HUB_VERSION = "kaito-tpu.io/v1"


def is_legacy(d: dict) -> bool:
    return d.get("apiVersion") in LEGACY_VERSIONS


def convert_to_hub(d: dict) -> dict:
    """Upgrade a legacy wire object to the hub version (no-op for hub
    or unknown versions; never mutates the input)."""
    if not is_legacy(d):
        return d
    out = copy.deepcopy(d)
    out["apiVersion"] = HUB_VERSION
    kind = out.get("kind")
    if kind == "RAGEngine":
        _convert_ragengine(out)
    elif kind == "Workspace":
        _convert_workspace(out)
    return out


def _convert_ragengine(out: dict) -> None:
    spec = out.get("spec") or {}
    storage = spec.get("storage")
    if isinstance(storage, dict):
        # only restructure when the nested form is absent — a
        # half-migrated manifest carrying both keeps BOTH (never drop
        # fields; the nested form wins at decode time)
        if "persistentVolume" not in storage and (
                storage.get("persistentVolumeClaim")
                or storage.get("mountPath")):
            storage["persistentVolume"] = {
                "persistentVolumeClaim": storage.pop(
                    "persistentVolumeClaim", ""),
                "mountPath": storage.pop("mountPath", "")}
    svc = spec.get("inferenceService")
    if isinstance(svc, dict):
        for legacy_key, hub_key in (("URL", "url"),
                                    ("AccessSecret", "accessSecret")):
            if legacy_key in svc and hub_key not in svc:
                svc[hub_key] = svc.pop(legacy_key)


def _convert_workspace(out: dict) -> None:
    tuning = out.get("tuning")
    if isinstance(tuning, dict):
        method = tuning.get("method")
        aliases = {"qlora": "QLoRA", "lora": "LoRA"}
        if method in aliases:
            tuning["method"] = aliases[method]


def convert_from_hub(d: dict, desired: str) -> dict:
    """Downgrade a hub object to a served legacy version (the spoke
    direction: clients reading/applying at v1alpha1 must see the
    legacy SHAPE, not a relabeled hub object — otherwise kubectl apply
    of flat legacy manifests diffs forever against the nested live
    form)."""
    if desired not in LEGACY_VERSIONS or d.get("apiVersion") == desired:
        return d
    out = copy.deepcopy(d)
    out["apiVersion"] = desired
    kind = out.get("kind")
    if kind == "RAGEngine":
        spec = out.get("spec") or {}
        storage = spec.get("storage")
        if isinstance(storage, dict):
            pv = storage.pop("persistentVolume", None)
            if isinstance(pv, dict):
                storage.setdefault("persistentVolumeClaim",
                                   pv.get("persistentVolumeClaim", ""))
                storage.setdefault("mountPath", pv.get("mountPath", ""))
        svc = spec.get("inferenceService")
        if isinstance(svc, dict):
            for hub_key, legacy_key in (("url", "URL"),
                                        ("accessSecret", "AccessSecret")):
                if hub_key in svc and legacy_key not in svc:
                    svc[legacy_key] = svc.pop(hub_key)
    elif kind == "Workspace":
        tuning = out.get("tuning")
        if isinstance(tuning, dict):
            aliases = {"QLoRA": "qlora", "LoRA": "lora"}
            if tuning.get("method") in aliases:
                tuning["method"] = aliases[tuning["method"]]
    return out


def convert(d: dict, desired: str) -> dict:
    """Convert to the requested version, either direction."""
    if desired == HUB_VERSION:
        return convert_to_hub(d)
    return convert_from_hub(convert_to_hub(d), desired)
