"""InferenceSet: replicated Workspaces with autoscale surface.

Parity with ``api/v1beta1/inferenceset_types.go:39-165``: replicas +
workspace template + selector for the HPA/KEDA scale subresource,
nodeCountLimit guard, rolling update strategy, auto-upgrade maintenance
window (cron).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.api.meta import Condition, KaitoObject, ObjectMeta
from kaito_tpu.api.workspace import InferenceSpec, ResourceSpec


@dataclass
class MaintenanceWindow:
    cron: str = ""              # 5-field cron in UTC
    duration_minutes: int = 60


@dataclass
class AutoUpgradePolicy:
    enabled: bool = False
    maintenance_window: MaintenanceWindow = field(default_factory=MaintenanceWindow)


@dataclass
class WorkspaceTemplate:
    resource: ResourceSpec = field(default_factory=ResourceSpec)
    inference: InferenceSpec = field(default_factory=InferenceSpec)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)


@dataclass
class InferenceSetSpec:
    replicas: int = 1
    template: WorkspaceTemplate = field(default_factory=WorkspaceTemplate)
    node_count_limit: int = 0           # 0 = unlimited
    update_strategy: str = "RollingUpdate"
    auto_upgrade: AutoUpgradePolicy = field(default_factory=AutoUpgradePolicy)


@dataclass
class InferenceSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    selector: str = ""                  # scale-subresource label selector
    conditions: list[Condition] = field(default_factory=list)
    aggregated_peak_tokens_per_minute: float = 0.0
    # fleet telemetry plane (runtime/fleet.py): rolling scaling signal
    # + replica hint.  Read-side only — nothing actuates on these yet.
    scaling_signal: str = ""            # idle|nominal|pressure|saturated
    recommended_replicas: int = 0


class InferenceSet(KaitoObject):
    kind = "InferenceSet"

    def __init__(self, meta: ObjectMeta, spec: Optional[InferenceSetSpec] = None):
        super().__init__(meta)
        self.spec = spec or InferenceSetSpec()
        self.status = InferenceSetStatus()

    def default(self) -> None:
        if self.spec.replicas < 0:
            self.spec.replicas = 0
        if not self.spec.update_strategy:
            self.spec.update_strategy = "RollingUpdate"

    def validate(self) -> list[str]:
        errs = []
        if self.spec.replicas < 0:
            errs.append("spec.replicas must be >= 0")
        if self.spec.update_strategy not in ("RollingUpdate", "OnDelete"):
            errs.append(f"spec.updateStrategy {self.spec.update_strategy!r} invalid")
        if self.spec.node_count_limit < 0:
            errs.append("spec.nodeCountLimit must be >= 0")
        if self.spec.auto_upgrade.enabled and not self.spec.auto_upgrade.maintenance_window.cron:
            errs.append("autoUpgrade.maintenanceWindow.cron required when enabled")
        if not self.spec.template.inference.preset and self.spec.template.inference.template is None:
            errs.append("template.inference.preset or template is required")
        return errs
