"""InferenceSet: replicated Workspaces with autoscale surface.

Parity with ``api/v1beta1/inferenceset_types.go:39-165``: replicas +
workspace template + selector for the HPA/KEDA scale subresource,
nodeCountLimit guard, rolling update strategy, auto-upgrade maintenance
window (cron).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.api.meta import Condition, KaitoObject, ObjectMeta
from kaito_tpu.api.workspace import InferenceSpec, ResourceSpec


@dataclass
class MaintenanceWindow:
    cron: str = ""              # 5-field cron in UTC
    duration_minutes: int = 60


@dataclass
class AutoUpgradePolicy:
    enabled: bool = False
    maintenance_window: MaintenanceWindow = field(default_factory=MaintenanceWindow)


@dataclass
class WorkspaceTemplate:
    resource: ResourceSpec = field(default_factory=ResourceSpec)
    inference: InferenceSpec = field(default_factory=InferenceSpec)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)


@dataclass
class AutoscalePolicy:
    """First-class autoscale surface consumed by the closed-loop
    actuator (``controllers/autoscaler.py``).  The fleet telemetry
    plane's hints (``SignalPolicy.scale_to_zero_hint`` /
    ``max_replicas_hint``) are derived from the SAME fields so
    ``status.recommended_replicas`` and actuation never disagree."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 0               # 0 = bounded only by nodeCountLimit
    scale_to_zero: bool = False         # sustained idle may park the set at 0
    idle_grace_s: float = 600.0         # extra idle dwell before scale-down
    scale_up_stabilization_s: float = 30.0
    scale_down_stabilization_s: float = 300.0
    scale_up_cooldown_s: float = 60.0
    scale_down_cooldown_s: float = 300.0
    drain_grace_s: float = 30.0         # EPP drain window before delete
    warm_pool: int = 1                  # replicas provisioned ahead on pressure
    warm_pool_gc_s: float = 600.0       # sustained non-pressure before warm GC

    def default(self) -> None:
        if self.min_replicas < 0:
            self.min_replicas = 0
        if self.max_replicas < 0:
            self.max_replicas = 0
        if self.warm_pool < 0:
            self.warm_pool = 0
        for f in ("idle_grace_s", "scale_up_stabilization_s",
                  "scale_down_stabilization_s", "scale_up_cooldown_s",
                  "scale_down_cooldown_s", "drain_grace_s",
                  "warm_pool_gc_s"):
            if getattr(self, f) < 0:
                setattr(self, f, 0.0)

    def validate(self) -> list[str]:
        errs = []
        if not self.enabled:
            return errs
        if self.min_replicas == 0 and not self.scale_to_zero:
            errs.append("autoscale.minReplicas 0 requires "
                        "autoscale.scaleToZero")
        if self.max_replicas and self.max_replicas < max(1, self.min_replicas):
            errs.append("autoscale.maxReplicas must be >= minReplicas")
        return errs

    def floor(self) -> int:
        """Lowest replica count sustained idle may park the set at:
        0 when scale-to-zero is on, else minReplicas (>= 1)."""
        return 0 if self.scale_to_zero else max(1, self.min_replicas)


@dataclass
class InferenceSetSpec:
    replicas: int = 1
    template: WorkspaceTemplate = field(default_factory=WorkspaceTemplate)
    node_count_limit: int = 0           # 0 = unlimited
    update_strategy: str = "RollingUpdate"
    auto_upgrade: AutoUpgradePolicy = field(default_factory=AutoUpgradePolicy)
    autoscale: AutoscalePolicy = field(default_factory=AutoscalePolicy)


@dataclass
class InferenceSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    selector: str = ""                  # scale-subresource label selector
    conditions: list[Condition] = field(default_factory=list)
    aggregated_peak_tokens_per_minute: float = 0.0
    # fleet telemetry plane (runtime/fleet.py): rolling scaling signal
    # + replica hint.  Read-side only — nothing actuates on these yet.
    scaling_signal: str = ""            # idle|nominal|pressure|saturated
    recommended_replicas: int = 0


class InferenceSet(KaitoObject):
    kind = "InferenceSet"

    def __init__(self, meta: ObjectMeta, spec: Optional[InferenceSetSpec] = None):
        super().__init__(meta)
        self.spec = spec or InferenceSetSpec()
        self.status = InferenceSetStatus()

    def default(self) -> None:
        if self.spec.replicas < 0:
            self.spec.replicas = 0
        if not self.spec.update_strategy:
            self.spec.update_strategy = "RollingUpdate"
        self.spec.autoscale.default()

    def validate(self) -> list[str]:
        errs = []
        if self.spec.replicas < 0:
            errs.append("spec.replicas must be >= 0")
        if self.spec.update_strategy not in ("RollingUpdate", "OnDelete"):
            errs.append(f"spec.updateStrategy {self.spec.update_strategy!r} invalid")
        if self.spec.node_count_limit < 0:
            errs.append("spec.nodeCountLimit must be >= 0")
        if self.spec.auto_upgrade.enabled and not self.spec.auto_upgrade.maintenance_window.cron:
            errs.append("autoUpgrade.maintenanceWindow.cron required when enabled")
        if not self.spec.template.inference.preset and self.spec.template.inference.template is None:
            errs.append("template.inference.preset or template is required")
        errs.extend(self.spec.autoscale.validate())
        return errs
