"""MultiRoleInference: prefill/decode disaggregation.

Parity: ``api/v1alpha1/multiroleinference_types.go:74-130`` — a model +
per-role scaling (prefill/decode) with role-specific instance types and
runtime config, plus the endpoint-picker plugin config that makes the
gateway route prefill→decode pairs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.api.meta import Condition, KaitoObject, ObjectMeta

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


@dataclass
class RoleSpec:
    type: str = ROLE_DECODE             # prefill | decode
    replicas: int = 1
    instance_type: str = "ct5lp-hightpu-4t"
    tpu_topology: str = ""
    runtime_config: dict = field(default_factory=dict)


@dataclass
class MRIModelSpec:
    name: str = ""
    model_access_secret: str = ""


@dataclass
class MultiRoleInferenceSpec:
    model: MRIModelSpec = field(default_factory=MRIModelSpec)
    roles: list[RoleSpec] = field(default_factory=list)
    epp_plugins_config: dict = field(default_factory=dict)


@dataclass
class MultiRoleInferenceStatus:
    conditions: list[Condition] = field(default_factory=list)
    role_ready: dict[str, bool] = field(default_factory=dict)


class MultiRoleInference(KaitoObject):
    kind = "MultiRoleInference"

    def __init__(self, meta: ObjectMeta,
                 spec: Optional[MultiRoleInferenceSpec] = None):
        super().__init__(meta)
        self.spec = spec or MultiRoleInferenceSpec()
        self.status = MultiRoleInferenceStatus()

    def default(self) -> None:
        for r in self.spec.roles:
            if r.replicas < 0:
                r.replicas = 0

    def validate(self) -> list[str]:
        errs = []
        if not self.spec.model.name:
            errs.append("model.name required")
        types = [r.type for r in self.spec.roles]
        if sorted(set(types)) != [ROLE_DECODE, ROLE_PREFILL]:
            errs.append("roles must contain exactly one prefill and one decode role")
        if len(types) != len(set(types)):
            errs.append("duplicate role types")
        return errs
