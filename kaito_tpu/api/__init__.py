from kaito_tpu.api.meta import Condition, ObjectMeta, now_iso  # noqa: F401
from kaito_tpu.api.workspace import (  # noqa: F401
    InferenceSpec,
    ResourceSpec,
    TuningSpec,
    Workspace,
    WorkspaceStatus,
)
from kaito_tpu.api.inferenceset import InferenceSet, InferenceSetSpec  # noqa: F401
from kaito_tpu.api.ragengine import RAGEngine, RAGEngineSpec  # noqa: F401
from kaito_tpu.api.multiroleinference import (  # noqa: F401
    MultiRoleInference,
    RoleSpec,
)
from kaito_tpu.api.modelmirror import ModelMirror, ModelMirrorSpec  # noqa: F401
