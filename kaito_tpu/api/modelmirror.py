"""ModelMirror: cluster-scoped model weight cache.

Parity: ``api/v1alpha1/modelmirror_types.go:29-127`` — managed mode
downloads weights into shared storage (on GKE: a GCS bucket or Filestore
RWX volume instead of Azure Blob CSI); static mode trusts pre-seeded
storage.  Phases Pending → Downloading → Ready.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.api.meta import Condition, KaitoObject, ObjectMeta

PHASE_PENDING = "Pending"
PHASE_DOWNLOADING = "Downloading"
PHASE_READY = "Ready"
PHASE_FAILED = "Failed"


@dataclass
class MirrorSource:
    registry: str = "huggingface"
    model_id: str = ""
    access_secret: str = ""


@dataclass
class MirrorStorage:
    size: str = "100Gi"
    storage_class_name: str = ""
    bucket: str = ""                     # GCS bucket alternative to PVC


@dataclass
class ModelMirrorSpec:
    mode: str = "managed"                # managed | static
    source: MirrorSource = field(default_factory=MirrorSource)
    storage: MirrorStorage = field(default_factory=MirrorStorage)


@dataclass
class ModelMirrorStatus:
    phase: str = PHASE_PENDING
    conditions: list[Condition] = field(default_factory=list)
    downloaded_bytes: int = 0


class ModelMirror(KaitoObject):
    kind = "ModelMirror"

    def __init__(self, meta: ObjectMeta, spec: Optional[ModelMirrorSpec] = None):
        super().__init__(meta)
        self.spec = spec or ModelMirrorSpec()
        self.status = ModelMirrorStatus()

    def default(self) -> None:
        if not self.spec.mode:
            self.spec.mode = "managed"
        if (self.spec.mode == "managed" and not self.spec.storage.bucket
                and not self.spec.storage.storage_class_name):
            self.spec.storage.storage_class_name = "filestore-rwx"

    def validate(self) -> list[str]:
        errs = []
        if self.spec.mode not in ("managed", "static"):
            errs.append(f"mode {self.spec.mode!r} must be managed|static")
        if self.spec.mode == "managed" and not self.spec.source.model_id:
            errs.append("source.modelID required in managed mode")
        if not (self.spec.storage.bucket or self.spec.storage.storage_class_name
                or self.spec.mode == "static"):
            errs.append("storage.bucket or storage.storageClassName required")
        return errs
