"""Typed-object machinery shared by all our API kinds.

The in-process analogue of k8s apimachinery for the CRD surface the
reference defines in ``api/v1beta1`` — metadata, conditions, and a
generation/resourceVersion model rich enough for controller-runtime
style reconciliation and ControllerRevision histories.  Objects
serialize to/from plain dicts (YAML-shaped), so real cluster backends
can adapt them 1:1.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Optional


def now_iso() -> str:
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    owner_references: list[dict] = field(default_factory=list)
    uid: str = ""
    generation: int = 1
    resource_version: int = 0
    creation_timestamp: str = field(default_factory=now_iso)
    deletion_timestamp: Optional[str] = None

    @property
    def key(self) -> tuple[str, str]:
        return (self.namespace, self.name)


@dataclass
class Condition:
    """status.conditions entry (mirrors metav1.Condition semantics)."""

    type: str
    status: str = "Unknown"          # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: str = field(default_factory=now_iso)
    observed_generation: int = 0


def set_condition(conditions: list[Condition], new: Condition) -> None:
    """Upsert keeping last_transition_time stable when status unchanged
    (the semantics the reference relies on via meta.SetStatusCondition)."""
    for i, c in enumerate(conditions):
        if c.type == new.type:
            if c.status == new.status:
                new.last_transition_time = c.last_transition_time
            conditions[i] = new
            return
    conditions.append(new)


def get_condition(conditions: list[Condition], type_: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == type_:
            return c
    return None


def condition_true(conditions: list[Condition], type_: str) -> bool:
    c = get_condition(conditions, type_)
    return c is not None and c.status == "True"


class KaitoObject:
    """Base for API kinds: metadata + deep-copyable spec/status."""

    kind: str = ""

    def __init__(self, meta: ObjectMeta):
        self.metadata = meta

    def deepcopy(self):
        return copy.deepcopy(self)
