"""Workspace: the primary user-facing kind.

TPU-native re-design of the reference's Workspace CRD
(``api/v1beta1/workspace_types.go:286-302``): ``resource`` asks for TPU
capacity (instance type is a TPU machine type; ``tpu_topology`` replaces
the MIG ``partition``), ``inference`` selects a preset/template plus
config and adapters, ``tuning`` describes a fine-tune job.  Validation
and defaulting follow ``workspace_validation.go``/``workspace_default.go``
semantics re-expressed for slices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from kaito_tpu.api.meta import Condition, KaitoObject, ObjectMeta
from kaito_tpu.models.registry import is_valid_preset
from kaito_tpu.sku.catalog import MACHINE_TYPES, parse_topology

# condition types (parity with the reference's condition model,
# workspace_controller.go:694-1107)
COND_RESOURCE_READY = "ResourceReady"
COND_NODE_CLAIM_READY = "NodeClaimReady"
COND_INFERENCE_READY = "InferenceReady"
COND_TUNING_STARTED = "TuningJobStarted"
COND_WORKSPACE_SUCCEEDED = "WorkspaceSucceeded"
COND_BENCHMARK_COMPLETE = "BenchmarkComplete"
# folded from the benchmark probe's /debug/slo verdict (runtime/slo.py)
COND_SLO_HEALTHY = "SLOHealthy"
# fleet telemetry verdict (runtime/fleet.py): True when a scaling
# action is signalled (pressure/saturated/idle), False when nominal
COND_SCALING_SIGNAL = "ScalingSignal"

# annotations / labels (our namespace, same roles as kaito.sh/*)
ANNOTATION_DISABLE_BENCHMARK = "kaito-tpu.io/disable-benchmark"
ANNOTATION_UPGRADE_TO = "kaito-tpu.io/upgrade-to-version"
# scale-down victim mark (controllers/autoscaler.py): the EPP renders
# this replica's backend as draining (picker stops scoring it,
# in-flight requests finish) before the Workspace is deleted
ANNOTATION_DRAINING = "kaito-tpu.io/draining"
LABEL_WORKSPACE_NAME = "kaito-tpu.io/workspace"
LABEL_CREATED_BY_INFERENCESET = "kaito-tpu.io/workspace-created-by-inferenceset"

MAX_SLICES_PER_WORKSPACE = 4   # pipeline-over-DCN cap (the reference caps
                               # PP at 3 nodes for a vLLM Ray bug; ours is
                               # a planner policy, not a bug workaround)


@dataclass
class ResourceSpec:
    """TPU capacity request."""

    instance_type: str = "ct5lp-hightpu-4t"
    count: int = 1                       # slices (node pools), not VMs
    tpu_topology: str = ""               # e.g. "4x4"; "" = planner decides
    label_selector: dict[str, str] = field(default_factory=dict)
    preferred_nodes: list[str] = field(default_factory=list)


@dataclass
class AdapterSpec:
    name: str = ""
    source_image: str = ""
    strength: float = 1.0


@dataclass
class InferenceSpec:
    preset: str = ""                     # preset name or HF id
    template: Optional[dict] = None      # raw pod template escape hatch
    config: str = ""                     # name of config map with engine YAML
    adapters: list[AdapterSpec] = field(default_factory=list)


@dataclass
class TuningInput:
    urls: list[str] = field(default_factory=list)
    image: str = ""
    volume: Optional[dict] = None


@dataclass
class TuningOutput:
    image: str = ""
    image_push_secret: str = ""
    volume: Optional[dict] = None


@dataclass
class TuningSpec:
    preset: str = ""
    method: str = "lora"                 # lora | qlora | full
    config: str = ""
    input: TuningInput = field(default_factory=TuningInput)
    output: TuningOutput = field(default_factory=TuningOutput)


@dataclass
class PerformanceStatus:
    metrics: dict[str, float] = field(default_factory=dict)
    config: dict[str, str] = field(default_factory=dict)


@dataclass
class WorkspaceStatus:
    conditions: list[Condition] = field(default_factory=list)
    target_node_count: int = 0
    worker_nodes: list[str] = field(default_factory=list)
    performance: PerformanceStatus = field(default_factory=PerformanceStatus)
    observed_generation: int = 0


class Workspace(KaitoObject):
    kind = "Workspace"

    def __init__(self, meta: ObjectMeta,
                 resource: Optional[ResourceSpec] = None,
                 inference: Optional[InferenceSpec] = None,
                 tuning: Optional[TuningSpec] = None):
        super().__init__(meta)
        self.resource = resource or ResourceSpec()
        self.inference = inference
        self.tuning = tuning
        self.status = WorkspaceStatus()

    # -- defaulting (reference: workspace_default.go) -------------------

    def default(self) -> None:
        if self.resource.count < 1:
            self.resource.count = 1
        if self.inference and self.inference.preset:
            self.inference.preset = self.inference.preset.strip()
        if self.tuning and not self.tuning.method:
            self.tuning.method = "lora"

    # -- validation (reference: workspace_validation.go:66) -------------

    def validate(self) -> list[str]:
        errs: list[str] = []
        if not self.metadata.name:
            errs.append("metadata.name is required")
        if self.inference is None and self.tuning is None:
            errs.append("one of inference or tuning must be set")
        if self.inference is not None and self.tuning is not None:
            errs.append("inference and tuning are mutually exclusive")

        r = self.resource
        if r.instance_type and r.instance_type not in MACHINE_TYPES and not r.label_selector:
            errs.append(
                f"resource.instanceType {r.instance_type!r} is not a known TPU "
                f"machine type and no labelSelector is set (BYO requires a selector)")
        if r.tpu_topology:
            try:
                parse_topology(r.tpu_topology)
            except ValueError as e:
                errs.append(f"resource.tpuTopology: {e}")
        if r.count < 1 or r.count > MAX_SLICES_PER_WORKSPACE:
            errs.append(
                f"resource.count must be in [1, {MAX_SLICES_PER_WORKSPACE}]")

        if self.inference is not None:
            i = self.inference
            if not i.preset and i.template is None:
                errs.append("inference.preset or inference.template is required")
            if i.preset and "/" not in i.preset and not is_valid_preset(i.preset):
                errs.append(f"inference.preset {i.preset!r} is not a known preset "
                            f"(HF ids must be org/name)")
            seen = set()
            for a in i.adapters:
                if not a.name or not a.source_image:
                    errs.append("inference.adapters entries need name and source")
                if a.name in seen:
                    errs.append(f"duplicate adapter name {a.name!r}")
                seen.add(a.name)
                if not (0.0 < a.strength <= 1.0):
                    errs.append(f"adapter {a.name!r} strength must be in (0, 1]")

        if self.tuning is not None:
            t = self.tuning
            if not t.preset:
                errs.append("tuning.preset is required")
            elif "/" not in t.preset and not is_valid_preset(t.preset):
                errs.append(f"tuning.preset {t.preset!r} is not a known preset")
            if t.method not in ("lora", "qlora", "full"):
                errs.append(f"tuning.method {t.method!r} must be lora|qlora|full")
            if not (t.input.urls or t.input.image or t.input.volume):
                errs.append("tuning.input needs one of urls, image, volume")
            if not (t.output.image or t.output.volume):
                errs.append("tuning.output needs image or volume")
        return errs

    # -- helpers --------------------------------------------------------

    @property
    def preset_name(self) -> str:
        if self.inference is not None:
            return self.inference.preset
        if self.tuning is not None:
            return self.tuning.preset
        return ""

    def revision_payload(self) -> dict:
        """The spec hash input for ControllerRevision tracking
        (reference: workspace_controller.go:384-494 hashes
        resource/inference/tuning)."""
        from dataclasses import asdict

        return {
            "resource": asdict(self.resource),
            "inference": asdict(self.inference) if self.inference else None,
            "tuning": asdict(self.tuning) if self.tuning else None,
        }
