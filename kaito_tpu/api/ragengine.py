"""RAGEngine kind (parity: ``api/v1beta1/ragengine_types.go:135-190``)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.api.meta import Condition, KaitoObject, ObjectMeta
from kaito_tpu.api.workspace import ResourceSpec

COND_RAG_RESOURCE_READY = "ResourceReady"
COND_RAG_SERVICE_READY = "RAGEngineServiceReady"


@dataclass
class VectorDBSpec:
    engine: str = "faiss"              # faiss | qdrant | native
    url: str = ""
    access_secret: str = ""


@dataclass
class StorageSpec:
    persistent_volume: Optional[dict] = None
    vector_db: VectorDBSpec = field(default_factory=VectorDBSpec)


@dataclass
class LocalEmbedding:
    model_id: str = ""
    model_access_secret: str = ""


@dataclass
class RemoteEmbedding:
    url: str = ""
    access_secret: str = ""


@dataclass
class EmbeddingSpec:
    local: Optional[LocalEmbedding] = None
    remote: Optional[RemoteEmbedding] = None


@dataclass
class InferenceServiceSpec:
    url: str = ""
    access_secret: str = ""
    context_window_size: int = 0


@dataclass
class GuardrailsSpec:
    enabled: bool = False
    config_map_ref: str = ""


@dataclass
class RAGEngineSpec:
    compute: ResourceSpec = field(default_factory=ResourceSpec)
    storage: StorageSpec = field(default_factory=StorageSpec)
    embedding: EmbeddingSpec = field(default_factory=EmbeddingSpec)
    inference_service: InferenceServiceSpec = field(default_factory=InferenceServiceSpec)
    guardrails: GuardrailsSpec = field(default_factory=GuardrailsSpec)


@dataclass
class RAGEngineStatus:
    conditions: list[Condition] = field(default_factory=list)
    worker_nodes: list[str] = field(default_factory=list)


class RAGEngine(KaitoObject):
    kind = "RAGEngine"

    def __init__(self, meta: ObjectMeta, spec: Optional[RAGEngineSpec] = None):
        super().__init__(meta)
        self.spec = spec or RAGEngineSpec()
        self.status = RAGEngineStatus()

    def default(self) -> None:
        if not self.spec.storage.vector_db.engine:
            self.spec.storage.vector_db.engine = "faiss"

    def validate(self) -> list[str]:
        errs = []
        e = self.spec.embedding
        if (e.local is None) == (e.remote is None):
            errs.append("exactly one of embedding.local or embedding.remote required")
        if e.local is not None and not e.local.model_id:
            errs.append("embedding.local.modelID required")
        if e.remote is not None and not e.remote.url:
            errs.append("embedding.remote.url required")
        if not self.spec.inference_service.url:
            errs.append("inferenceService.url required")
        db = self.spec.storage.vector_db
        if db.engine not in ("faiss", "qdrant", "native"):
            errs.append(f"vectorDB.engine {db.engine!r} must be faiss|qdrant|native")
        if db.engine == "qdrant" and not db.url:
            errs.append("vectorDB.url required for qdrant")
        return errs
