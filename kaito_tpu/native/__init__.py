"""ctypes bindings for the native runtime components.

Builds ``libkaito_native.so`` on first import when a compiler is
available (make -C kaito_tpu/native); every consumer has a pure-Python
fallback, so absence of a toolchain degrades gracefully.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libkaito_native.so")
_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def load_native() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _build_attempted:
            # always invoke make: dependency-driven, a no-op when fresh,
            # and it rebuilds a stale .so missing newer symbols
            _build_attempted = True
            try:
                subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                               capture_output=True, timeout=120)
            except Exception as e:
                logger.warning("native build failed (%s); using python fallbacks", e)
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
        except (OSError, AttributeError) as e:
            logger.warning("cannot load %s: %s", _LIB_PATH, e)
            return None
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.kprefix_new.restype = c.c_void_p
    lib.kprefix_new.argtypes = [c.c_int32, c.c_int32]
    lib.kprefix_free.argtypes = [c.c_void_p]
    lib.kprefix_acquire.restype = c.c_int32
    lib.kprefix_acquire.argtypes = [
        c.c_void_p, c.POINTER(c.c_int32), c.c_int32, c.c_int32,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32)]
    lib.kprefix_release.argtypes = [
        c.c_void_p, c.POINTER(c.c_int32), c.c_int32,
        c.POINTER(c.c_int32), c.c_int32]
    lib.kprefix_alloc_raw.restype = c.c_int32
    lib.kprefix_alloc_raw.argtypes = [
        c.c_void_p, c.c_int32, c.POINTER(c.c_int32)]
    lib.kprefix_release_uncommitted.argtypes = [
        c.c_void_p, c.POINTER(c.c_int32), c.c_int32,
        c.POINTER(c.c_int32), c.c_int32]
    lib.kprefix_available.restype = c.c_int32
    lib.kprefix_available.argtypes = [c.c_void_p]
    lib.kprefix_stats.argtypes = [c.c_void_p] + [c.POINTER(c.c_int64)] * 4

    lib.kvec_new.restype = c.c_void_p
    lib.kvec_new.argtypes = [c.c_int32]
    lib.kvec_free.argtypes = [c.c_void_p]
    lib.kvec_size.restype = c.c_int64
    lib.kvec_size.argtypes = [c.c_void_p]
    lib.kvec_add.argtypes = [c.c_void_p, c.c_int64, c.POINTER(c.c_float)]
    lib.kvec_remove.restype = c.c_int32
    lib.kvec_remove.argtypes = [c.c_void_p, c.c_int64]
    lib.kvec_search.restype = c.c_int32
    lib.kvec_search.argtypes = [
        c.c_void_p, c.POINTER(c.c_float), c.c_int32,
        c.POINTER(c.c_int64), c.POINTER(c.c_float)]
    lib.kvec_export.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.c_float)]


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativePrefixCache:
    """Prefix-caching page allocator (radix tree over token chunks)."""

    def __init__(self, num_pages: int, page_size: int):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.kprefix_new(num_pages, page_size)
        if not self._h:
            raise ValueError("bad prefix cache parameters")
        self.num_pages = num_pages
        self.page_size = page_size

    def acquire(self, tokens: list[int], max_total_tokens: int
                ) -> Optional[tuple[list[int], int]]:
        """Returns (pages, cached_tokens) or None on OOM."""
        toks = np.asarray(tokens, np.int32)
        max_pages = -(-max_total_tokens // self.page_size)
        out = np.zeros(max_pages + 1, np.int32)
        cached = ctypes.c_int32(0)
        n = self._lib.kprefix_acquire(
            self._h, _i32ptr(toks), len(toks), max_total_tokens,
            _i32ptr(out), ctypes.byref(cached))
        if n < 0:
            return None
        return list(out[:n]), int(cached.value)

    def release(self, tokens: list[int], pages: list[int]) -> None:
        toks = np.asarray(tokens, np.int32)
        pg = np.asarray(pages, np.int32)
        self._lib.kprefix_release(self._h, _i32ptr(toks), len(toks),
                                  _i32ptr(pg), len(pg))

    def alloc_raw(self, n: int) -> Optional[list[int]]:
        """Plain page allocation for on-demand sequence growth; the pages
        return through release()/release_uncommitted() with the rest."""
        out = np.zeros(max(n, 1), np.int32)
        got = self._lib.kprefix_alloc_raw(self._h, n, _i32ptr(out))
        if got < 0:
            return None
        return list(out[:got])

    def release_uncommitted(self, tokens: list[int], pages: list[int]) -> None:
        """Return shared refs and free exclusive pages WITHOUT committing
        anything into the radix tree (failure / unvalidated-KV paths)."""
        toks = np.asarray(tokens, np.int32)
        pg = np.asarray(pages, np.int32)
        self._lib.kprefix_release_uncommitted(
            self._h, _i32ptr(toks), len(toks), _i32ptr(pg), len(pg))

    @property
    def available(self) -> int:
        return int(self._lib.kprefix_available(self._h))

    def stats(self) -> dict:
        vals = [ctypes.c_int64(0) for _ in range(4)]
        self._lib.kprefix_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {"hits": vals[0].value, "misses": vals[1].value,
                "evictions": vals[2].value, "cached_pages": vals[3].value}

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.kprefix_free(self._h)
            self._h = None


class NativeFlatIndex:
    """Flat inner-product index backed by the C++ implementation;
    interface-compatible with rag.vector_store.FlatDenseIndex."""

    def __init__(self, dim: int):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.kvec_new(dim)
        self.dim = dim
        self._key_to_int: dict[str, int] = {}
        self._int_to_key: dict[int, str] = {}
        self._next = 1

    def _intern(self, doc_id: str) -> int:
        i = self._key_to_int.get(doc_id)
        if i is None:
            i = self._next
            self._next += 1
            self._key_to_int[doc_id] = i
            self._int_to_key[i] = doc_id
        return i

    def add(self, doc_id: str, vec: np.ndarray) -> None:
        v = np.ascontiguousarray(vec, np.float32)
        self._lib.kvec_add(self._h, self._intern(doc_id),
                           v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))

    def remove(self, doc_id: str) -> None:
        i = self._key_to_int.pop(doc_id, None)
        if i is not None:
            self._int_to_key.pop(i, None)
            self._lib.kvec_remove(self._h, i)

    def search(self, query_vec: np.ndarray, top_k: int) -> list[tuple[str, float]]:
        q = np.ascontiguousarray(query_vec, np.float32)
        ids = np.zeros(top_k, np.int64)
        scores = np.zeros(top_k, np.float32)
        n = self._lib.kvec_search(
            self._h, q.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), top_k,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            scores.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return [(self._int_to_key[int(ids[i])], float(scores[i]))
                for i in range(n) if int(ids[i]) in self._int_to_key]

    def state(self) -> dict:
        n = int(self._lib.kvec_size(self._h))
        ids = np.zeros(n, np.int64)
        vecs = np.zeros((n, self.dim), np.float32)
        if n:
            self._lib.kvec_export(
                self._h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                vecs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return {"ids": [self._int_to_key.get(int(i), str(i)) for i in ids],
                "vecs": vecs}

    def load_state(self, state: dict) -> None:
        for doc_id, vec in zip(state["ids"], np.asarray(state["vecs"])):
            self.add(str(doc_id), vec)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.kvec_free(self._h)
            self._h = None
