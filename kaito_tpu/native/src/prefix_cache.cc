// Prefix-caching KV page allocator.
//
// The native runtime piece under the serving engine's KV pool: a
// ref-counted page allocator with a radix tree over page-sized token
// chunks, so sequences sharing a prompt prefix share pages
// (vLLM-style automatic prefix caching, which the reference inherits
// from its vendored engine; here it is first-party).  Exposed through a
// C ABI consumed via ctypes (kaito_tpu/native/__init__.py).
//
// Concurrency: one global mutex per cache handle — the Python engine
// calls from its scheduler thread; contention is nil.

#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

using u64 = uint64_t;
using i64 = int64_t;

constexpr int32_t kNullPage = 0;

u64 hash_chunk(const int32_t* tokens, int n, u64 seed) {
  // FNV-1a over the chunk, chained with the parent hash so equal chunks
  // under different prefixes map to different nodes.
  u64 h = seed ^ 1469598103934665603ULL;
  for (int i = 0; i < n; i++) {
    h ^= static_cast<u64>(tokens[i]) + 0x9e3779b97f4a7c15ULL;
    h *= 1099511628211ULL;
  }
  return h;
}

struct Node {
  int32_t page = kNullPage;
  int32_t refcount = 0;   // sequences currently holding this page
  u64 key = 0;            // chained hash identifying this node
  u64 parent = 0;
  u64 lru = 0;            // last release tick
  bool cached = true;     // false while only allocated, true once committed
};

struct PrefixCache {
  std::mutex mu;
  int32_t num_pages;
  int32_t page_size;
  u64 tick = 0;
  std::vector<int32_t> free_pages;            // stack of free page ids
  std::unordered_map<u64, Node> nodes;        // key -> node (committed tree)
  std::unordered_map<int32_t, u64> page_owner;  // page -> node key
  // stats
  u64 hits = 0, misses = 0, evictions = 0;

  explicit PrefixCache(int32_t pages, int32_t psize)
      : num_pages(pages), page_size(psize) {
    for (int32_t p = pages - 1; p >= 1; p--) free_pages.push_back(p);
  }

  bool evict_one() {
    // evict the LRU committed node with refcount 0
    u64 best_key = 0;
    u64 best_lru = ~0ULL;
    for (auto& [key, node] : nodes) {
      if (node.refcount == 0 && node.lru < best_lru) {
        best_lru = node.lru;
        best_key = key;
      }
    }
    if (best_key == 0) return false;
    Node& n = nodes[best_key];
    free_pages.push_back(n.page);
    page_owner.erase(n.page);
    nodes.erase(best_key);
    evictions++;
    return true;
  }

  int32_t take_page() {
    if (free_pages.empty() && !evict_one()) return -1;
    int32_t p = free_pages.back();
    free_pages.pop_back();
    return p;
  }
};

}  // namespace

extern "C" {

void* kprefix_new(int32_t num_pages, int32_t page_size) {
  if (num_pages < 2 || page_size < 1) return nullptr;
  return new PrefixCache(num_pages, page_size);
}

void kprefix_free(void* handle) { delete static_cast<PrefixCache*>(handle); }

// Acquire pages for a sequence of n_tokens (page-aligned coverage for
// max_tokens total).  Full pages whose chunk matches a committed node
// are shared (ref++); the rest come from the free list.  Returns the
// number of pages written to out_pages, and sets *out_cached_tokens to
// the shared-prefix length in tokens.  Returns -1 on OOM (nothing is
// held in that case).
int32_t kprefix_acquire(void* handle, const int32_t* tokens, int32_t n_tokens,
                        int32_t max_total_tokens, int32_t* out_pages,
                        int32_t* out_cached_tokens) {
  auto* c = static_cast<PrefixCache*>(handle);
  std::lock_guard<std::mutex> lock(c->mu);
  const int32_t ps = c->page_size;
  const int32_t total_pages = (max_total_tokens + ps - 1) / ps;
  const int32_t full_prompt_pages = n_tokens / ps;  // only full pages cacheable

  std::vector<int32_t> pages;
  std::vector<u64> shared_keys;
  pages.reserve(total_pages);
  int32_t cached_tokens = 0;
  u64 parent = 0;
  bool matching = true;

  for (int32_t i = 0; i < total_pages; i++) {
    if (matching && i < full_prompt_pages) {
      u64 key = hash_chunk(tokens + i * ps, ps, parent);
      auto it = c->nodes.find(key);
      if (it != c->nodes.end()) {
        it->second.refcount++;
        pages.push_back(it->second.page);
        shared_keys.push_back(key);
        cached_tokens += ps;
        parent = key;
        c->hits++;
        continue;
      }
      matching = false;
      c->misses++;
    }
    int32_t p = c->take_page();
    if (p < 0) {
      // roll back shared refs and taken pages
      for (u64 k : shared_keys) c->nodes[k].refcount--;
      for (size_t j = shared_keys.size(); j < pages.size(); j++)
        c->free_pages.push_back(pages[j]);
      return -1;
    }
    pages.push_back(p);
  }
  std::memcpy(out_pages, pages.data(), pages.size() * sizeof(int32_t));
  *out_cached_tokens = cached_tokens;
  return static_cast<int32_t>(pages.size());
}

// Release a finished sequence: commit full prompt+output pages into the
// radix tree for future reuse, decrement shared refs.  `tokens` is the
// FULL final token sequence (prompt + generated), n_tokens its length;
// pages are the page ids returned by acquire (n_pages of them).
void kprefix_release(void* handle, const int32_t* tokens, int32_t n_tokens,
                     const int32_t* pages, int32_t n_pages) {
  auto* c = static_cast<PrefixCache*>(handle);
  std::lock_guard<std::mutex> lock(c->mu);
  const int32_t ps = c->page_size;
  const int32_t full_pages =
      std::min(n_tokens / ps, n_pages);  // only complete pages are reusable
  c->tick++;
  u64 parent = 0;
  for (int32_t i = 0; i < n_pages; i++) {
    int32_t page = pages[i];
    if (i < full_pages) {
      u64 key = hash_chunk(tokens + i * ps, ps, parent);
      auto it = c->nodes.find(key);
      if (it != c->nodes.end() && it->second.page == page) {
        // we held a shared ref on this committed node
        it->second.refcount--;
        it->second.lru = c->tick;
      } else if (it != c->nodes.end()) {
        // same content already committed under a different page: drop ours
        c->free_pages.push_back(page);
      } else {
        auto owner = c->page_owner.find(page);
        if (owner == c->page_owner.end()) {
          Node n;
          n.page = page;
          n.refcount = 0;
          n.key = key;
          n.parent = parent;
          n.lru = c->tick;
          c->nodes.emplace(key, n);
          c->page_owner.emplace(page, key);
        }
      }
      parent = key;
    } else {
      // tail pages (partial or generated-beyond-full): not cacheable
      auto owner = c->page_owner.find(page);
      if (owner == c->page_owner.end()) c->free_pages.push_back(page);
    }
  }
}

// Raw page allocation (no radix-tree interaction): used by the engine
// for reserve-on-demand growth of a running sequence's page list.  The
// pages are later returned through kprefix_release(_uncommitted) along
// with the sequence's acquire()d pages.  Returns n on success (ids in
// out_pages), -1 when the pool (after eviction) cannot supply n pages.
int32_t kprefix_alloc_raw(void* handle, int32_t n, int32_t* out_pages) {
  auto* c = static_cast<PrefixCache*>(handle);
  std::lock_guard<std::mutex> lock(c->mu);
  std::vector<int32_t> taken;
  taken.reserve(n);
  for (int32_t i = 0; i < n; i++) {
    int32_t p = c->take_page();
    if (p < 0) {
      for (int32_t q : taken) c->free_pages.push_back(q);
      return -1;
    }
    taken.push_back(p);
  }
  std::memcpy(out_pages, taken.data(), taken.size() * sizeof(int32_t));
  return n;
}

// Release WITHOUT committing: return shared refs (the contiguous prefix
// of pages that matched committed nodes at acquire time) and free the
// rest, entering nothing new into the tree.  Used for failure paths
// where the pages' KV content was never fully written/validated, so
// committing them would poison future prefix hits.
void kprefix_release_uncommitted(void* handle, const int32_t* tokens,
                                 int32_t n_tokens, const int32_t* pages,
                                 int32_t n_pages) {
  auto* c = static_cast<PrefixCache*>(handle);
  std::lock_guard<std::mutex> lock(c->mu);
  const int32_t ps = c->page_size;
  const int32_t full_pages = std::min(n_tokens / ps, n_pages);
  c->tick++;
  u64 parent = 0;
  bool matching = true;
  for (int32_t i = 0; i < n_pages; i++) {
    int32_t page = pages[i];
    if (matching && i < full_pages) {
      u64 key = hash_chunk(tokens + i * ps, ps, parent);
      auto it = c->nodes.find(key);
      if (it != c->nodes.end() && it->second.page == page) {
        it->second.refcount--;
        it->second.lru = c->tick;
        parent = key;
        continue;
      }
      matching = false;
    }
    if (c->page_owner.find(page) == c->page_owner.end())
      c->free_pages.push_back(page);
  }
}

int32_t kprefix_available(void* handle) {
  auto* c = static_cast<PrefixCache*>(handle);
  std::lock_guard<std::mutex> lock(c->mu);
  int32_t evictable = 0;
  for (auto& [k, n] : c->nodes)
    if (n.refcount == 0) evictable++;
  return static_cast<int32_t>(c->free_pages.size()) + evictable;
}

void kprefix_stats(void* handle, i64* out_hits, i64* out_misses,
                   i64* out_evictions, i64* out_cached_pages) {
  auto* c = static_cast<PrefixCache*>(handle);
  std::lock_guard<std::mutex> lock(c->mu);
  *out_hits = static_cast<i64>(c->hits);
  *out_misses = static_cast<i64>(c->misses);
  *out_evictions = static_cast<i64>(c->evictions);
  *out_cached_pages = static_cast<i64>(c->nodes.size());
}

}  // extern "C"
