// Flat dense vector index: contiguous row storage, vectorizable inner
// products, partial-sort top-k.  The native backend for the RAG vector
// store (the reference leans on FAISS; this is the first-party
// equivalent for the flat/IP case, with the same swap-remove id
// bookkeeping as the Python fallback).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

struct VecIndex {
  std::mutex mu;
  int32_t dim;
  std::vector<float> data;          // n * dim, row-major
  std::vector<int64_t> ids;
  std::unordered_map<int64_t, int64_t> pos;  // id -> row

  explicit VecIndex(int32_t d) : dim(d) {}
};

}  // namespace

extern "C" {

void* kvec_new(int32_t dim) {
  if (dim <= 0) return nullptr;
  return new VecIndex(dim);
}

void kvec_free(void* handle) { delete static_cast<VecIndex*>(handle); }

int64_t kvec_size(void* handle) {
  auto* ix = static_cast<VecIndex*>(handle);
  std::lock_guard<std::mutex> lock(ix->mu);
  return static_cast<int64_t>(ix->ids.size());
}

void kvec_add(void* handle, int64_t id, const float* vec) {
  auto* ix = static_cast<VecIndex*>(handle);
  std::lock_guard<std::mutex> lock(ix->mu);
  auto it = ix->pos.find(id);
  if (it != ix->pos.end()) {
    std::memcpy(ix->data.data() + it->second * ix->dim, vec,
                sizeof(float) * ix->dim);
    return;
  }
  ix->pos.emplace(id, static_cast<int64_t>(ix->ids.size()));
  ix->ids.push_back(id);
  ix->data.insert(ix->data.end(), vec, vec + ix->dim);
}

int32_t kvec_remove(void* handle, int64_t id) {
  auto* ix = static_cast<VecIndex*>(handle);
  std::lock_guard<std::mutex> lock(ix->mu);
  auto it = ix->pos.find(id);
  if (it == ix->pos.end()) return 0;
  int64_t row = it->second;
  int64_t last = static_cast<int64_t>(ix->ids.size()) - 1;
  if (row != last) {
    std::memcpy(ix->data.data() + row * ix->dim,
                ix->data.data() + last * ix->dim, sizeof(float) * ix->dim);
    int64_t moved = ix->ids[last];
    ix->ids[row] = moved;
    ix->pos[moved] = row;
  }
  ix->ids.pop_back();
  ix->data.resize(ix->ids.size() * ix->dim);
  ix->pos.erase(it);
  return 1;
}

// Export all rows (for persistence). Buffers must hold kvec_size rows.
void kvec_export(void* handle, int64_t* out_ids, float* out_vecs) {
  auto* ix = static_cast<VecIndex*>(handle);
  std::lock_guard<std::mutex> lock(ix->mu);
  std::memcpy(out_ids, ix->ids.data(), ix->ids.size() * sizeof(int64_t));
  std::memcpy(out_vecs, ix->data.data(), ix->data.size() * sizeof(float));
}

// Top-k by inner product. Returns number of results written.
int32_t kvec_search(void* handle, const float* query, int32_t k,
                    int64_t* out_ids, float* out_scores) {
  auto* ix = static_cast<VecIndex*>(handle);
  std::lock_guard<std::mutex> lock(ix->mu);
  const int64_t n = static_cast<int64_t>(ix->ids.size());
  if (n == 0 || k <= 0) return 0;
  const int32_t d = ix->dim;
  std::vector<std::pair<float, int64_t>> scored(n);
  const float* base = ix->data.data();
  for (int64_t i = 0; i < n; i++) {
    const float* row = base + i * d;
    float s = 0.f;
    for (int32_t j = 0; j < d; j++) s += row[j] * query[j];
    scored[i] = {s, ix->ids[i]};
  }
  const int64_t kk = std::min<int64_t>(k, n);
  std::partial_sort(scored.begin(), scored.begin() + kk, scored.end(),
                    [](auto& a, auto& b) { return a.first > b.first; });
  for (int64_t i = 0; i < kk; i++) {
    out_scores[i] = scored[i].first;
    out_ids[i] = scored[i].second;
  }
  return static_cast<int32_t>(kk);
}

}  // extern "C"
