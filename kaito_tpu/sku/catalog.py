"""TPU chip & slice catalog — the TPU-native replacement for the
reference's GPU SKU layer (``pkg/sku/cloud_sku_handler.go:25``,
``pkg/sku/azure_sku_handler.go:21``).

Where the reference maps *cloud VM instance types* to
``{GPUCount, GPUMemGB, GPUModel}``, we map *TPU machine types and slice
topologies* to chip generation specs: HBM per chip, bf16 peak FLOPs,
HBM bandwidth, ICI link characteristics, chips per host (VM), and the
set of valid slice topologies.  The estimator and the sharding planner
consume these to size slices and lay out device meshes.

Public (documented) hardware characteristics only; see Google's TPU
system architecture docs for the v4/v5e/v5p/v6e numbers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

GiB = 2**30

# GKE node labels for TPU slices (the analogue of the reference reading
# nvidia.com/* node labels in pkg/sku/helpers.go:75).
LABEL_TPU_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
LABEL_TPU_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
LABEL_TPU_MACHINE = "node.kubernetes.io/instance-type"


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse a topology string like ``2x4`` or ``4x4x8`` into dims."""
    try:
        dims = tuple(int(p) for p in topology.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"invalid TPU topology {topology!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"invalid TPU topology {topology!r}")
    return dims


def topology_chips(topology: str) -> int:
    """Total chip count of a topology string."""
    n = 1
    for d in parse_topology(topology):
        n *= d
    return n


@dataclass(frozen=True)
class TPUChipSpec:
    """Per-chip hardware characteristics of one TPU generation."""

    generation: str              # "v4" | "v5e" | "v5p" | "v6e"
    hbm_bytes: int               # HBM capacity per chip
    bf16_tflops: float           # peak dense bf16 TFLOP/s per chip
    int8_tops: float             # peak int8 TOP/s per chip
    hbm_gbps: float              # HBM bandwidth GB/s per chip
    ici_axes: int                # torus dimensionality (2D / 3D)
    ici_gbps_per_link: float     # one-direction ICI bandwidth per link, GB/s
    chips_per_host: int          # chips attached to one VM/host at full density
    accelerator_label: str       # value of cloud.google.com/gke-tpu-accelerator
    valid_topologies: Sequence[str]  # slice topologies GKE accepts
    max_chips: int               # largest slice (pod) size

    def topology_for_chips(self, chips: int) -> Optional[str]:
        """Smallest valid topology with at least ``chips`` chips."""
        best = None
        best_n = None
        for t in self.valid_topologies:
            n = topology_chips(t)
            if n >= chips and (best_n is None or n < best_n):
                best, best_n = t, n
        return best

    def hosts_for_topology(self, topology: str) -> int:
        chips = topology_chips(topology)
        return max(1, -(-chips // self.chips_per_host))


# Catalog of chip generations.  Topology lists follow GKE's accepted
# `gke-tpu-topology` values for each machine family.
CHIP_CATALOG: Mapping[str, TPUChipSpec] = {
    "v4": TPUChipSpec(
        generation="v4",
        hbm_bytes=32 * GiB,
        bf16_tflops=275.0,
        int8_tops=275.0,
        hbm_gbps=1228.0,
        ici_axes=3,
        ici_gbps_per_link=100.0,
        chips_per_host=4,
        accelerator_label="tpu-v4-podslice",
        valid_topologies=(
            "2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
            "4x8x8", "8x8x8", "8x8x16", "8x16x16", "16x16x16",
        ),
        max_chips=4096,
    ),
    "v5e": TPUChipSpec(
        generation="v5e",
        hbm_bytes=16 * GiB,
        bf16_tflops=197.0,
        int8_tops=394.0,
        hbm_gbps=819.0,
        ici_axes=2,
        ici_gbps_per_link=50.0,
        chips_per_host=8,
        accelerator_label="tpu-v5-lite-podslice",
        valid_topologies=(
            "1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16",
        ),
        max_chips=256,
    ),
    "v5p": TPUChipSpec(
        generation="v5p",
        hbm_bytes=95 * GiB,
        bf16_tflops=459.0,
        int8_tops=918.0,
        hbm_gbps=2765.0,
        ici_axes=3,
        ici_gbps_per_link=200.0,
        chips_per_host=4,
        accelerator_label="tpu-v5p-slice",
        valid_topologies=(
            "2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
            "4x8x8", "8x8x8", "8x8x16", "8x16x16", "16x16x16",
        ),
        max_chips=8960,
    ),
    "v6e": TPUChipSpec(
        generation="v6e",
        hbm_bytes=32 * GiB,
        bf16_tflops=918.0,
        int8_tops=1836.0,
        hbm_gbps=1640.0,
        ici_axes=2,
        ici_gbps_per_link=100.0,
        chips_per_host=8,
        accelerator_label="tpu-v6e-slice",
        valid_topologies=(
            "1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16",
        ),
        max_chips=256,
    ),
}

# GKE TPU machine types → (generation, chips per VM).  The analogue of
# the per-cloud instance-type tables in pkg/sku/{azure,aws}_sku_handler.go.
MACHINE_TYPES: Mapping[str, tuple[str, int]] = {
    # v4
    "ct4p-hightpu-4t": ("v4", 4),
    # v5e
    "ct5lp-hightpu-1t": ("v5e", 1),
    "ct5lp-hightpu-4t": ("v5e", 4),
    "ct5lp-hightpu-8t": ("v5e", 8),
    "ct5l-hightpu-1t": ("v5e", 1),
    "ct5l-hightpu-4t": ("v5e", 4),
    "ct5l-hightpu-8t": ("v5e", 8),
    # v5p
    "ct5p-hightpu-4t": ("v5p", 4),
    # v6e
    "ct6e-standard-1t": ("v6e", 1),
    "ct6e-standard-4t": ("v6e", 4),
    "ct6e-standard-8t": ("v6e", 8),
}

_ACCELERATOR_TO_GEN = {spec.accelerator_label: gen for gen, spec in CHIP_CATALOG.items()}


@dataclass(frozen=True)
class TPUSliceSpec:
    """A concrete provisionable slice: generation + topology."""

    chip: TPUChipSpec
    topology: str
    machine_type: str = ""

    @property
    def num_chips(self) -> int:
        return topology_chips(self.topology)

    @property
    def num_hosts(self) -> int:
        return self.chip.hosts_for_topology(self.topology)

    @property
    def total_hbm_bytes(self) -> int:
        return self.num_chips * self.chip.hbm_bytes

    @property
    def dims(self) -> tuple[int, ...]:
        return parse_topology(self.topology)

    def node_selector(self) -> dict[str, str]:
        """GKE node labels selecting this slice shape."""
        sel = {
            LABEL_TPU_ACCELERATOR: self.chip.accelerator_label,
            LABEL_TPU_TOPOLOGY: self.topology,
        }
        if self.machine_type:
            sel[LABEL_TPU_MACHINE] = self.machine_type
        return sel


class TPUSKUHandler:
    """Catalog lookups, interface-compatible with the reference's
    ``CloudSKUHandler`` (``pkg/sku/cloud_sku_handler.go:25-28``) but in
    terms of TPU machine types / generations."""

    def get_supported_generations(self) -> list[str]:
        raise NotImplementedError

    def get_chip_config(self, generation: str) -> Optional[TPUChipSpec]:
        raise NotImplementedError

    def get_chip_config_by_machine_type(self, machine_type: str) -> Optional[tuple[TPUChipSpec, int]]:
        raise NotImplementedError


class GKETPUSKUHandler(TPUSKUHandler):
    def get_supported_generations(self) -> list[str]:
        return sorted(CHIP_CATALOG)

    def get_chip_config(self, generation: str) -> Optional[TPUChipSpec]:
        return CHIP_CATALOG.get(generation)

    def get_chip_config_by_machine_type(self, machine_type: str) -> Optional[tuple[TPUChipSpec, int]]:
        entry = MACHINE_TYPES.get(machine_type)
        if entry is None:
            return None
        gen, chips_per_vm = entry
        return CHIP_CATALOG[gen], chips_per_vm

    def default_machine_type(self, generation: str, topology: str) -> str:
        """Pick the GKE machine type serving a topology of this generation."""
        chips = topology_chips(topology)
        candidates = [
            (mt, per_vm)
            for mt, (gen, per_vm) in MACHINE_TYPES.items()
            if gen == generation
        ]
        if not candidates:
            raise ValueError(f"unknown TPU generation {generation!r}")
        # Multi-host slices use the full-density machine type; single-host
        # slices use the machine type that exactly fits the chip count.
        exact = [mt for mt, per_vm in candidates if per_vm == chips]
        if exact:
            return exact[0]
        return max(candidates, key=lambda c: c[1])[0]


_HANDLERS = {"gke": GKETPUSKUHandler}


def get_sku_handler(cloud: str = "gke") -> TPUSKUHandler:
    """Pick the SKU handler for a cloud (reference: ``GetSKUHandler``
    selected by the ``CLOUD_PROVIDER`` env, ``cmd/workspace/main.go:157``)."""
    try:
        return _HANDLERS[cloud.lower()]()
    except KeyError:
        raise ValueError(f"unsupported cloud provider for TPU: {cloud!r}")


def get_tpu_config_from_node_labels(labels: Mapping[str, str]) -> Optional[TPUSliceSpec]:
    """Derive a slice spec from node labels — the BYO-node path
    (reference: ``sku.GetGPUConfigFromNodeLabels``, ``pkg/sku/helpers.go:75``)."""
    acc = labels.get(LABEL_TPU_ACCELERATOR)
    topo = labels.get(LABEL_TPU_TOPOLOGY)
    if not acc or not topo:
        return None
    gen = _ACCELERATOR_TO_GEN.get(acc)
    if gen is None:
        return None
    return TPUSliceSpec(
        chip=CHIP_CATALOG[gen],
        topology=topo,
        machine_type=labels.get(LABEL_TPU_MACHINE, ""),
    )
