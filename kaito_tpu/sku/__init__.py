from kaito_tpu.sku.catalog import (  # noqa: F401
    TPUChipSpec,
    TPUSliceSpec,
    TPUSKUHandler,
    GKETPUSKUHandler,
    get_sku_handler,
    parse_topology,
    topology_chips,
    get_tpu_config_from_node_labels,
    CHIP_CATALOG,
)
