"""kaito-tpu: a TPU-native AI toolchain operator.

A from-scratch, TPU-first framework with the capabilities of KAITO
(the Kubernetes AI Toolchain Operator): declarative APIs for LLM
inference, fine-tuning and RAG that plan JAX device meshes over TPU
slices, provision capacity, and serve models through a JAX/XLA/Pallas
engine with continuous batching and paged attention.

Layering (mirrors SURVEY.md §1, re-designed TPU-first):

- ``kaito_tpu.api``        -- typed Workspace/InferenceSet/RAGEngine/... objects
- ``kaito_tpu.sku``        -- TPU chip & slice catalog (v4/v5e/v5p/v6e)
- ``kaito_tpu.models``     -- model metadata registry + presets + HF autogen
- ``kaito_tpu.estimator``  -- HBM fit & slice-size estimation
- ``kaito_tpu.parallel``   -- sharding planner: mesh + partition specs
- ``kaito_tpu.engine``     -- JAX/Pallas serving engine (continuous batching)
- ``kaito_tpu.tuning``     -- LoRA/QLoRA fine-tuning on TPU
- ``kaito_tpu.rag``        -- RAG service (vector store, hybrid retrieval)
- ``kaito_tpu.controllers``-- reconcilers (workspace, inferenceset, ...)
- ``kaito_tpu.provision``  -- node provisioning backends (karpenter/byo/fake)
- ``kaito_tpu.manifests``  -- k8s object rendering
- ``kaito_tpu.runtime``    -- in-pod bootstrap: distributed init, probes
- ``kaito_tpu.native``     -- C++ runtime components (allocators, indexes)
"""

__version__ = "0.1.0"
