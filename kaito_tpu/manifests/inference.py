"""Inference workload generation: ParallelPlan -> StatefulSet + Services.

The TPU-native re-design of ``pkg/workspace/inference/
preset_inferences.go:158`` (GeneratePresetInference) and the command
builder ``pkg/model/interface.go:340-560``: instead of rendering vLLM
flags + a Ray bootstrap script, we render the engine server command
with the planner's mesh baked into env/flags, and rely on GKE's
TPU_WORKER_ID / TPU_WORKER_HOSTNAMES injection plus the headless
service for the JAX coordinator.
"""

from __future__ import annotations

import json
import shlex
from typing import Optional

from kaito_tpu.api.workspace import LABEL_WORKSPACE_NAME, Workspace
from kaito_tpu.manifests.core import (
    generate_headless_service,
    generate_service,
    generate_statefulset,
)
from kaito_tpu.models.metadata import ModelMetadata
from kaito_tpu.parallel.plan import ParallelPlan

DEFAULT_IMAGE = "ghcr.io/kaito-tpu/engine:latest"
PORT = 5000

ANNOTATION_ADAPTERS = "kaito-tpu.io/adapters"

# dynamic-adapter source schemes _resolve_adapter_source accepts; a
# plan-time check here beats a 400 at the first hot-load request
_ADAPTER_SOURCE_SCHEMES = ("hub://", "oras://")


def parse_adapters_annotation(text: str) -> Optional[dict]:
    """Parse the ``kaito-tpu.io/adapters`` Workspace annotation into
    the dynamic multi-LoRA cache config (docs/multi-lora.md).  Empty
    input returns None — the whole adapter plane stays off.  Raises
    ValueError on a malformed document; the workspace controller calls
    this at plan time so a bad annotation becomes a PlanFailed
    condition instead of a crash-looping pod (the qos precedent).
    jax-free on purpose: the controller imports it.

    .. code-block:: json

        {"slots": 4, "rmax": 16, "host_bytes": 268435456,
         "allow_base_mismatch": false,
         "allowlist": ["oras://ghcr.io/acme/"]}
    """
    text = (text or "").strip()
    if not text:
        return None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"adapters config is not valid JSON: {e}") \
            from None
    if not isinstance(doc, dict):
        raise ValueError("adapters config must be a JSON object")
    unknown = set(doc) - {"slots", "rmax", "host_bytes",
                          "allow_base_mismatch", "allowlist"}
    if unknown:
        raise ValueError(f"adapters config has unknown field(s): "
                         f"{sorted(unknown)}")
    try:
        slots = int(doc.get("slots", 0))
        rmax = int(doc.get("rmax", 16))
        host_bytes = int(doc.get("host_bytes", 256 << 20))
    except (TypeError, ValueError) as e:
        raise ValueError(f"adapters config: {e}") from None
    if slots < 1:
        raise ValueError("adapters config needs 'slots' >= 1 (the HBM "
                         "slot-table capacity)")
    if rmax < 1:
        raise ValueError("adapters config: rmax must be >= 1")
    if host_bytes < 0:
        raise ValueError("adapters config: host_bytes must be >= 0")
    allow_mismatch = doc.get("allow_base_mismatch", False)
    if not isinstance(allow_mismatch, bool):
        raise ValueError("adapters config: allow_base_mismatch must be "
                         "a boolean")
    allowlist = doc.get("allowlist", [])
    if not isinstance(allowlist, list):
        raise ValueError("adapters config: allowlist must be a list of "
                         "source-prefix strings")
    for pref in allowlist:
        if not isinstance(pref, str) or not pref.startswith(
                _ADAPTER_SOURCE_SCHEMES):
            raise ValueError(
                f"adapters config: allowlist entry {pref!r} must start "
                f"with one of {list(_ADAPTER_SOURCE_SCHEMES)}")
        if "," in pref:
            raise ValueError(
                f"adapters config: allowlist entry {pref!r} must not "
                f"contain ',' (the flag joins entries with commas)")
    return {"slots": slots, "rmax": rmax, "host_bytes": host_bytes,
            "allow_base_mismatch": allow_mismatch,
            "allowlist": [str(p) for p in allowlist]}


def parse_structured_output_annotation(text: str) -> Optional[dict]:
    """Parse the ``kaito-tpu.io/structured-output`` Workspace
    annotation (docs/structured-output.md).  Empty input returns None —
    the server keeps its defaults (structured output ON).  Accepts a
    bare boolean string (``"false"`` turns the surface off fleet-wide)
    or a JSON object sizing the grammar compile cache:

    .. code-block:: json

        {"enabled": true, "cache_entries": 128, "max_states": 1024}

    Raises ValueError on a malformed document; the workspace controller
    calls this at plan time so a bad annotation becomes a PlanFailed
    condition instead of a crash-looping pod (the adapters-annotation
    precedent).  jax-free on purpose: the controller imports it."""
    text = (text or "").strip()
    if not text:
        return None
    lowered = text.lower()
    if lowered in ("true", "1", "on", "enabled"):
        return {"enabled": True, "cache_entries": None, "max_states": None}
    if lowered in ("false", "0", "off", "disabled"):
        return {"enabled": False, "cache_entries": None, "max_states": None}
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"structured-output config is not valid JSON: {e}") from None
    if not isinstance(doc, dict):
        raise ValueError("structured-output config must be a boolean "
                         "string or a JSON object")
    unknown = set(doc) - {"enabled", "cache_entries", "max_states"}
    if unknown:
        raise ValueError(f"structured-output config has unknown "
                         f"field(s): {sorted(unknown)}")
    enabled = doc.get("enabled", True)
    if not isinstance(enabled, bool):
        raise ValueError("structured-output config: enabled must be a "
                         "boolean")
    out = {"enabled": enabled, "cache_entries": None, "max_states": None}
    for field, lo in (("cache_entries", 1), ("max_states", 2)):
        if field not in doc:
            continue
        v = doc[field]
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(
                f"structured-output config: {field} must be an integer")
        if v < lo:
            raise ValueError(
                f"structured-output config: {field} must be >= {lo}")
        out[field] = v
    return out


def parse_prefill_pack_annotation(text: str) -> Optional[int]:
    """Parse the ``kaito-tpu.io/prefill-pack`` Workspace annotation
    (docs/prefill.md).  Empty input returns None — the server keeps its
    default (auto packing up to max-num-seqs).  Accepts a non-negative
    integer: 0 = auto, 1 = serial legacy scheduler, N > 1 caps the pack
    size.  Raises ValueError on anything else; the workspace controller
    calls this at plan time so a bad annotation becomes a PlanFailed
    condition instead of a crash-looping pod.  jax-free on purpose:
    the controller imports it."""
    text = (text or "").strip()
    if not text:
        return None
    try:
        pack = int(text)
    except ValueError:
        raise ValueError(
            f"prefill-pack annotation must be a non-negative integer, "
            f"got {text!r}") from None
    if pack < 0:
        raise ValueError("prefill-pack annotation must be >= 0 "
                         "(0 = auto, 1 = serial scheduler)")
    return pack


def parse_devprof_annotation(text: str) -> Optional[float]:
    """Parse the ``kaito-tpu.io/devprof`` Workspace annotation
    (docs/observability.md): the device-profiler sampling interval in
    seconds.  Empty input returns None — the server keeps its default
    (off), so an absent annotation leaves the pod command and metrics
    exposition byte-identical.  Accepts a positive number (seconds
    between sampled profile windows); ``0``/``off``/``false`` return
    None too, an explicit way to say "keep it off".  Raises ValueError
    otherwise; the workspace controller calls this at plan time so a
    bad annotation becomes a PlanFailed condition instead of a
    crash-looping pod.  jax-free on purpose: the controller imports
    it."""
    text = (text or "").strip()
    if not text:
        return None
    if text.lower() in ("off", "false", "0", "0.0"):
        return None
    try:
        interval = float(text)
    except ValueError:
        raise ValueError(
            f"devprof annotation must be a sampling interval in "
            f"seconds (or 'off'), got {text!r}") from None
    if interval != interval or interval <= 0.0:  # NaN or non-positive
        raise ValueError(
            "devprof annotation must be a positive number of seconds")
    if interval < 1.0:
        raise ValueError(
            "devprof annotation must be >= 1.0 seconds — each sample "
            "captures a full profiler window, so sub-second cadence "
            "would perturb the workload it measures")
    return interval


def parse_kv_pool_disk_annotation(disk_text: str,
                                  kv_pool_text: str = "") -> Optional[int]:
    """Parse the ``kaito-tpu.io/kv-pool-disk`` Workspace annotation
    (docs/kv-pool.md "Tier 3: SSD"): the byte budget for the pool's
    disk spill tier.  Empty input returns None — the server keeps its
    default (no disk tier), so an absent annotation leaves the pod
    command, spill behavior, and metrics exposition byte-identical.
    Accepts a Kubernetes resource quantity (``20Gi``, ``500M``) or
    plain bytes; ``0``/``off``/``false`` return None too, an explicit
    way to keep the tier off.  The tier holds spill from the cluster
    pool's host store, so naming a budget without
    ``kaito-tpu.io/kv-pool`` enabled is an error.  Raises ValueError
    on anything else; the workspace controller calls this at plan time
    so a bad annotation becomes a PlanFailed condition instead of a
    crash-looping pod.  jax-free on purpose: the controller imports
    it."""
    text = (disk_text or "").strip()
    if not text or text.lower() in ("off", "false", "0"):
        return None
    from kaito_tpu.utils.quantity import parse_quantity
    try:
        nbytes = parse_quantity(text)
    except ValueError:
        raise ValueError(
            f"kv-pool-disk annotation must be a byte quantity "
            f"(e.g. '20Gi') or 'off', got {text!r}") from None
    if nbytes <= 0:
        return None
    if (kv_pool_text or "").strip().lower() not in ("true", "1", "on",
                                                    "enabled"):
        raise ValueError(
            "kv-pool-disk requires kaito-tpu.io/kv-pool enabled — the "
            "SSD tier spills the cluster pool's host store and is "
            "inert without it")
    return nbytes


def parse_comm_overlap_annotation(text: str) -> Optional[bool]:
    """Parse the ``kaito-tpu.io/comm-overlap`` Workspace annotation
    (docs/multichip.md): the collective-compute overlap gate for TP
    decode.  Empty input returns None — the server keeps its default
    (off), so an absent annotation leaves the pod command, dispatch and
    metrics exposition byte-identical.  Accepts the usual boolean
    spellings (true/1/on/enabled, false/0/off/disabled).  Raises
    ValueError otherwise; the workspace controller calls this at plan
    time so a bad annotation becomes a PlanFailed condition instead of
    a crash-looping pod.  jax-free on purpose: the controller imports
    it."""
    text = (text or "").strip().lower()
    if not text:
        return None
    if text in ("true", "1", "on", "enabled"):
        return True
    if text in ("false", "0", "off", "disabled"):
        return False
    raise ValueError(
        f"comm-overlap annotation must be a boolean "
        f"(true/1/on/enabled or false/0/off/disabled), got {text!r}")


def parse_itl_annotation(text: str) -> Optional[bool]:
    """Parse the ``kaito-tpu.io/itl`` Workspace annotation
    (docs/observability.md): the true per-token inter-token-latency
    gate.  Empty input returns None — the server keeps its default
    (off), so an absent annotation leaves the pod command and metrics
    exposition byte-identical.  Accepts the usual boolean spellings.
    Raises ValueError otherwise; the workspace controller calls this at
    plan time so a bad annotation becomes a PlanFailed condition
    instead of a crash-looping pod.  jax-free on purpose: the
    controller imports it."""
    text = (text or "").strip().lower()
    if not text:
        return None
    if text in ("true", "1", "on", "enabled"):
        return True
    if text in ("false", "0", "off", "disabled"):
        return False
    raise ValueError(
        f"itl annotation must be a boolean "
        f"(true/1/on/enabled or false/0/off/disabled), got {text!r}")


def parse_flight_annotation(dir_text: str,
                            max_text: str = "") -> Optional[dict]:
    """Parse the ``kaito-tpu.io/flight-dir`` (+ optional
    ``kaito-tpu.io/flight-max-bundles``) Workspace annotations
    (docs/observability.md): the incident flight recorder.  An empty
    dir returns None — the server keeps its default (off), so an
    absent annotation leaves the pod command byte-identical and
    ``/debug/flight`` answers 403.  The dir must be an absolute path
    (it names a pod-local volume mount); max-bundles must be a
    positive integer.  Raises ValueError otherwise; the workspace
    controller calls this at plan time so a bad annotation becomes a
    PlanFailed condition instead of a crash-looping pod.  jax-free on
    purpose: the controller imports it."""
    dir_text = (dir_text or "").strip()
    if not dir_text or dir_text.lower() in ("off", "false", "0"):
        return None
    if not dir_text.startswith("/"):
        raise ValueError(
            f"flight-dir annotation must be an absolute path "
            f"(a pod-local volume mount), got {dir_text!r}")
    out = {"dir": dir_text, "max_bundles": None}
    max_text = (max_text or "").strip()
    if max_text:
        try:
            n = int(max_text)
        except ValueError:
            raise ValueError(
                f"flight-max-bundles annotation must be a positive "
                f"integer, got {max_text!r}") from None
        if n <= 0:
            raise ValueError(
                "flight-max-bundles annotation must be >= 1")
        out["max_bundles"] = n
    return out


def coordinator_address(workspace_name: str, namespace: str) -> str:
    """Pod-0 DNS via the headless service — same convention the
    reference uses for the Ray leader (``pkg/utils/common.go:229``),
    reused as the JAX distributed coordinator."""
    return (f"{workspace_name}-0.{workspace_name}-headless."
            f"{namespace}.svc.cluster.local:8476")


def build_engine_command(
    ws: Workspace,
    md: ModelMetadata,
    plan: ParallelPlan,
    *,
    config_file: str = "",
    adapters_dir: str = "",
) -> list[str]:
    """The pod command (analogue of buildVLLMInferenceCommand
    ``pkg/model/interface.go:374`` + configureParallelism ``:500``).

    Long-tail presets (``runtime: transformers``) render the HF
    fallback runtime instead — the reference's vLLM-vs-text-generation
    runtime split (RuntimeName, interface.go)."""
    mesh = plan.mesh
    if getattr(md, "runtime", "engine") == "transformers":
        return [
            "python", "-m", "kaito_tpu.runtime.hf_fallback",
            "--model", md.hf_id,
            "--port", str(PORT),
            "--max-model-len", str(plan.max_model_len),
            "--served-model-name", md.name or md.hf_id,
        ]
    args = [
        "python", "-m", "kaito_tpu.engine.server",
        "--model", md.name if md.name else md.hf_id,
        "--port", str(PORT),
        "--max-model-len", str(plan.max_model_len),
    ]
    kv_dtype = ws.metadata.annotations.get(
        "kaito-tpu.io/kv-cache-dtype", "")
    if kv_dtype:
        args += ["--kv-cache-dtype", kv_dtype]
    # weight-only quantization (docs/quantization.md): the controller
    # validated the scheme at plan time (PlanFailed on unknown values),
    # and the planner already sized node counts with the smaller
    # weight bytes — the flag must render or the pods would serve
    # bf16 on capacity planned for int8/int4
    quant = ws.metadata.annotations.get("kaito-tpu.io/quantization", "")
    if quant:
        args += ["--quantization", quant]
    qos = ws.metadata.annotations.get("kaito-tpu.io/qos", "")
    if qos:
        args += ["--qos-config", qos]
    # packed prefill (docs/prefill.md): auto is the server default, so
    # only an explicit annotation renders — absent keeps the pod
    # command byte-identical
    pack = parse_prefill_pack_annotation(
        ws.metadata.annotations.get("kaito-tpu.io/prefill-pack", ""))
    if pack is not None:
        args += ["--prefill-pack", str(pack)]
    # cluster KV pool (docs/kv-pool.md): opt-in per workspace; the
    # controller mirrors the same annotation onto the EPP deployment so
    # holder adverts and fetch hints switch on together
    kv_pool = ws.metadata.annotations.get("kaito-tpu.io/kv-pool", "")
    if kv_pool.lower() in ("true", "1", "on", "enabled"):
        args += ["--kv-pool"]
        pool_bytes = ws.metadata.annotations.get(
            "kaito-tpu.io/kv-pool-bytes", "")
        if pool_bytes:
            args += ["--kv-pool-bytes", pool_bytes]
        # tier-3 SSD spill (docs/kv-pool.md "Tier 3: SSD"): renders
        # only inside the kv-pool branch — the validated parse below
        # already rejects a disk budget without the pool
        disk = parse_kv_pool_disk_annotation(
            ws.metadata.annotations.get("kaito-tpu.io/kv-pool-disk", ""),
            kv_pool)
        if disk is not None:
            args += ["--kv-pool-disk-bytes", str(disk)]
    spec_draft = ws.metadata.annotations.get(
        "kaito-tpu.io/speculative-draft", "")
    if spec_draft:
        # "auto" resolves to the preset's curated pairing here (the
        # controller already validated it) so the pod command names a
        # concrete catalog preset
        from kaito_tpu.models.registry import resolve_speculative_draft
        resolved = resolve_speculative_draft(md, spec_draft)
        if resolved:
            args += ["--speculative-draft", resolved]
    # dynamic multi-LoRA cache (docs/multi-lora.md): the controller
    # validated the document at plan time; rendering turns it into the
    # server's slot-table flags.  The EPP deployment mirrors the same
    # annotation as --adapter-affinity so residency adverts are scraped
    # exactly when the replicas serve them.
    lora = parse_adapters_annotation(
        ws.metadata.annotations.get(ANNOTATION_ADAPTERS, ""))
    if lora:
        args += ["--adapter-slots", str(lora["slots"]),
                 "--adapter-rmax", str(lora["rmax"]),
                 "--adapter-host-bytes", str(lora["host_bytes"])]
        if lora["allow_base_mismatch"]:
            args += ["--adapter-allow-base-mismatch"]
        if lora["allowlist"]:
            args += ["--adapter-source-allowlist",
                     ",".join(lora["allowlist"])]
    # structured output (docs/structured-output.md): the controller
    # validated the document at plan time (PlanFailed on malformed);
    # rendering turns it into the grammar-cache flags.  Enabled is the
    # server default, so only the off switch and explicit sizes render
    # — an absent annotation keeps the pod command byte-identical.
    so = parse_structured_output_annotation(
        ws.metadata.annotations.get("kaito-tpu.io/structured-output", ""))
    if so is not None:
        if not so["enabled"]:
            args += ["--no-structured-output"]
        if so["cache_entries"] is not None:
            args += ["--grammar-cache-entries", str(so["cache_entries"])]
        if so["max_states"] is not None:
            args += ["--grammar-max-states", str(so["max_states"])]
    # sampled device-time attribution (docs/observability.md): off is
    # the server default (sampling costs device time), so only an
    # explicit annotation renders — absent keeps the pod command and
    # the /metrics exposition byte-identical
    devprof = parse_devprof_annotation(
        ws.metadata.annotations.get("kaito-tpu.io/devprof", ""))
    if devprof is not None:
        args += ["--devprof-interval-s", str(devprof)]
    # collective-compute overlap (docs/multichip.md): off is the server
    # default, so only an explicit opt-in renders — absent (or an
    # explicit off) keeps the pod command byte-identical.  The server
    # ignores the flag off a TP>=2 mesh, so rendering it on a plan
    # without a tensor axis is harmless, not a failure.
    overlap = parse_comm_overlap_annotation(
        ws.metadata.annotations.get("kaito-tpu.io/comm-overlap", ""))
    if overlap:
        args += ["--comm-overlap"]
    # true per-token ITL (docs/observability.md): off is the server
    # default, so only an explicit opt-in renders — absent (or an
    # explicit off) keeps the pod command and exposition byte-identical
    itl = parse_itl_annotation(
        ws.metadata.annotations.get("kaito-tpu.io/itl", ""))
    if itl:
        args += ["--itl"]
    # incident flight recorder (docs/observability.md): only an
    # explicit dir renders — absent keeps the pod command
    # byte-identical and /debug/flight answers 403
    flight = parse_flight_annotation(
        ws.metadata.annotations.get("kaito-tpu.io/flight-dir", ""),
        ws.metadata.annotations.get("kaito-tpu.io/flight-max-bundles", ""))
    if flight is not None:
        args += ["--flight-dir", flight["dir"]]
        if flight["max_bundles"] is not None:
            args += ["--flight-max-bundles", str(flight["max_bundles"])]
    if config_file:
        args += ["--kaito-config-file", config_file]
    if adapters_dir:
        args += ["--kaito-adapters-dir", adapters_dir]
    return args


def engine_env(ws: Workspace, md: ModelMetadata, plan: ParallelPlan) -> list[dict]:
    """Mesh + rendezvous env for the engine pod (replaces the Ray
    leader/worker shell logic of buildMultiNodeRayCommand)."""
    mesh = plan.mesh
    env = [
        {"name": "KAITO_MESH_SPEC", "value": str(mesh)},
        {"name": "KAITO_TENSOR_PARALLEL", "value": str(mesh.size("tensor"))},
        {"name": "KAITO_DATA_PARALLEL", "value": str(mesh.size("data"))},
        {"name": "KAITO_PIPELINE_PARALLEL", "value": str(mesh.size("pipeline"))},
        {"name": "KAITO_SEQUENCE_PARALLEL", "value": str(mesh.size("sequence"))},
        {"name": "KAITO_COORDINATOR",
         "value": coordinator_address(ws.metadata.name, ws.metadata.namespace)},
        {"name": "KAITO_TPU_TOPOLOGY", "value": plan.topology},
    ]
    role = ws.metadata.annotations.get("kaito-tpu.io/inference-role", "")
    if role:
        # P/D roles enable the KV side-channel, restricted to in-cluster
        # peers of this MRI (reference: NIXL env + routing sidecar,
        # preset_inferences.go:909-985).  The role also keys the SLO
        # watchdog's burn attribution (ROADMAP item 1): prefill pools
        # page on TTFT burn, decode pools on ITL burn.
        env.append({"name": "KAITO_INFERENCE_ROLE", "value": role})
        env.append({"name": "KAITO_PD_ENABLED", "value": "true"})
        env.append({"name": "KAITO_PD_ALLOWLIST",
                    "value": f"http://{ws.metadata.labels.get('kaito-tpu.io/multirole-inference', ws.metadata.name)}-"})
    if md.download_auth_required:
        env.append({"name": "HF_TOKEN", "valueFrom": {"secretKeyRef": {
            "name": f"{ws.metadata.name}-hf-token", "key": "token",
            "optional": True}}})
    return env


def _probes(num_hosts: int, benchmark: bool) -> dict:
    """Probe set (reference: preset_inferences.go:316-441): startup probe
    doubles as the self-benchmark on the leader; distributed pods use
    the coordinator-health exec probe instead of HTTP."""
    probes: dict = {
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 30, "failureThreshold": 6,
        },
    }
    if benchmark:
        probes["startupProbe"] = {
            "exec": {"command": [
                "python", "-m", "kaito_tpu.runtime.benchmark_probe"]},
            "failureThreshold": 60, "periodSeconds": 30,
            "timeoutSeconds": 600,
        }
    else:
        probes["startupProbe"] = {
            "httpGet": {"path": "/health", "port": PORT},
            "failureThreshold": 120, "periodSeconds": 10,
        }
    if num_hosts > 1:
        # workers have no HTTP server; health == coordinator liveness
        probes["livenessProbe"] = {
            "exec": {"command": [
                "python", "-m", "kaito_tpu.runtime.health",
                "--role", "auto"]},
            "periodSeconds": 30, "failureThreshold": 6,
        }
    return probes


def generate_inference_workload(
    ws: Workspace,
    md: ModelMetadata,
    plan: ParallelPlan,
    node_selector: dict,
    *,
    image: str = DEFAULT_IMAGE,
    benchmark: bool = True,
) -> list:
    """Render Service + headless Service + StatefulSet for a workspace."""
    name = ws.metadata.name
    ns = ws.metadata.namespace
    labels = {LABEL_WORKSPACE_NAME: name}
    num_hosts = plan.num_hosts

    cmd = build_engine_command(
        ws, md, plan,
        config_file=(f"/mnt/config/inference_config.yaml"
                     if ws.inference and ws.inference.config else ""),
        adapters_dir="/mnt/adapters" if ws.inference and ws.inference.adapters else "")

    volumes: list[dict] = [{"name": "shm", "emptyDir": {"medium": "Memory"}}]
    mounts = [{"name": "shm", "mountPath": "/dev/shm"}]
    if ws.inference and ws.inference.config:
        volumes.append({"name": "config", "configMap": {"name": ws.inference.config}})
        mounts.append({"name": "config", "mountPath": "/mnt/config"})

    init_containers = []
    if ws.inference:
        for a in ws.inference.adapters:
            # adapter puller (reference: pkg/workspace/image/puller.go via ORAS)
            volumes.append({"name": f"adapter-{a.name}", "emptyDir": {}})
            mounts.append({"name": f"adapter-{a.name}",
                           "mountPath": f"/mnt/adapters/{a.name}"})
            init_containers.append({
                "name": f"pull-adapter-{a.name}",
                "image": a.source_image,
                "command": ["sh", "-c",
                            f"cp -r /data/* /mnt/adapters/{a.name}/ 2>/dev/null || "
                            f"oras pull {shlex.quote(a.source_image)} "
                            f"-o /mnt/adapters/{a.name}"],
                "volumeMounts": [{"name": f"adapter-{a.name}",
                                  "mountPath": f"/mnt/adapters/{a.name}"}],
            })

    fallback = getattr(md, "runtime", "engine") == "transformers"
    if fallback:
        # CPU torch runtime: no TPU chips to pin, and the engine
        # self-benchmark probe would 400 on small-context long-tail
        # models (input_len 2048 > n_positions) — plain HTTP probes
        resources = {"requests": {"cpu": "4", "memory": "16Gi"}}
        benchmark = False
    else:
        resources = {
            "requests": {"google.com/tpu": str(plan.chip.chips_per_host)},
            "limits": {"google.com/tpu": str(plan.chip.chips_per_host)},
        }
    container = {
        "name": "engine",
        "image": image,
        "command": cmd,
        "env": engine_env(ws, md, plan),
        "ports": [{"containerPort": PORT}],
        "resources": resources,
        "volumeMounts": mounts,
        **_probes(num_hosts, benchmark),
    }

    svc = generate_service(name, ns, labels, labels=labels)
    headless = generate_headless_service(name, ns, labels, labels=labels)
    ss = generate_statefulset(
        name, ns, replicas=num_hosts, labels=labels,
        node_selector=node_selector, containers=[container],
        init_containers=init_containers or None, volumes=volumes)
    return [svc, headless, ss]
