"""Endpoint-picker (EPP) workload rendering.

Both controllers render an InferencePool whose ``extensionRef`` names
``<cr>-epp``; these helpers render the Deployment + Service that make
the ref resolve (docs/routing.md).  The picker is the in-repo
``kaito_tpu.runtime.epp`` service: the backend set is passed as
``--backend url[=role[/group]]`` args, recomputed by the owning
reconciler whenever replicas come and go (the in-miniature analogue of
the GAIE EPP watching pods behind the pool selector).
"""

from __future__ import annotations

import json
from typing import Optional

from kaito_tpu.api.meta import ObjectMeta
from kaito_tpu.controllers.objects import Unstructured
from kaito_tpu.manifests.core import generate_service
from kaito_tpu.manifests.inference import DEFAULT_IMAGE

EPP_PORT = 5000
LABEL_EPP = "kaito-tpu.io/epp"


def build_epp_command(backends: list[str], *,
                      plugins_config: Optional[dict] = None,
                      block_chars: int = 0,
                      draining: Optional[list[str]] = None,
                      kv_pool: bool = False,
                      adapter_affinity: bool = False) -> list[str]:
    """The container command: one ``--backend`` per replica spec
    (``url[=role[/group]]``), the plugin chain inline as JSON, and one
    ``--drain-backend`` per replica the autoscaler is retiring (the
    picker keeps relaying its in-flight work but stops scoring it).
    ``kv_pool`` mirrors the engines' ``kaito-tpu.io/kv-pool``
    annotation: the picker scrapes holder adverts and emits fetch
    hints only when the replicas actually publish (docs/kv-pool.md).
    ``adapter_affinity`` mirrors ``kaito-tpu.io/adapters`` the same
    way: resident-adapter adverts are only worth scraping when the
    replicas run the adapter cache (docs/multi-lora.md)."""
    cmd = ["python", "-m", "kaito_tpu.runtime.epp",
           "--port", str(EPP_PORT)]
    for spec in backends:
        cmd += ["--backend", spec]
    for url in draining or []:
        cmd += ["--drain-backend", url]
    if plugins_config:
        cmd += ["--plugins-config",
                json.dumps(plugins_config, sort_keys=True)]
    if block_chars:
        cmd += ["--block-chars", str(block_chars)]
    if kv_pool:
        cmd += ["--kv-pool"]
    if adapter_affinity:
        cmd += ["--adapter-affinity"]
    return cmd


def generate_epp_workload(name: str, namespace: str, *,
                          backends: list[str],
                          owner: Optional[dict] = None,
                          plugins_config: Optional[dict] = None,
                          draining: Optional[list[str]] = None,
                          kv_pool: bool = False,
                          adapter_affinity: bool = False,
                          image: str = DEFAULT_IMAGE) -> list:
    """Render the ``<name>`` (conventionally ``<cr>-epp``) Deployment +
    Service the InferencePool's extensionRef resolves to."""
    labels = {LABEL_EPP: name}
    owners = [owner] if owner else []
    deploy = Unstructured(
        "Deployment",
        ObjectMeta(name=name, namespace=namespace, labels=dict(labels),
                   owner_references=list(owners)),
        spec={
            "replicas": 1,
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "containers": [{
                        "name": "epp",
                        "image": image,
                        "command": build_epp_command(
                            backends, plugins_config=plugins_config,
                            draining=draining, kv_pool=kv_pool,
                            adapter_affinity=adapter_affinity),
                        "ports": [{"containerPort": EPP_PORT}],
                        "readinessProbe": {
                            "httpGet": {"path": "/router/stats",
                                        "port": EPP_PORT},
                            "periodSeconds": 5,
                        },
                        "resources": {
                            "requests": {"cpu": "500m", "memory": "1Gi"},
                        },
                    }],
                },
            },
        })
    svc = generate_service(name, namespace, labels, port=EPP_PORT,
                           labels=labels)
    svc.metadata.owner_references = list(owners)
    return [deploy, svc]
