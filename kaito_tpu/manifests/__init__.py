from kaito_tpu.manifests.inference import (  # noqa: F401
    build_engine_command,
    generate_inference_workload,
)
from kaito_tpu.manifests.core import (  # noqa: F401
    generate_service,
    generate_headless_service,
    generate_statefulset,
)
