"""Tuning Job rendering.

Parity: ``pkg/workspace/tuning/preset_tuning.go:145`` CreatePresetTuning
— data-downloader init container (URL/image/volume sources), the
trainer command (our JAX LoRA trainer instead of accelerate+HF), a
results volume, and an ORAS pusher sidecar when output.image is set.
"""

from __future__ import annotations

import shlex

from kaito_tpu.api.meta import ObjectMeta
from kaito_tpu.api.workspace import LABEL_WORKSPACE_NAME, Workspace
from kaito_tpu.controllers.objects import Unstructured
from kaito_tpu.manifests.inference import DEFAULT_IMAGE
from kaito_tpu.parallel.plan import ParallelPlan

RESULTS_DIR = "/mnt/results"
DATA_DIR = "/mnt/data"
SENTINEL = "fine_tuning_completed.txt"


def build_tuning_command(ws: Workspace, md, plan: ParallelPlan) -> list[str]:
    t = ws.tuning
    return [
        "python", "-m", "kaito_tpu.tuning.cli",
        "--model", md.name,
        "--method", t.method,
        "--data-dir", DATA_DIR,
        "--output-dir", RESULTS_DIR,
        "--mesh", str(plan.mesh),
    ] + (["--config-file", "/mnt/config/tuning_config.yaml"] if t.config else [])


def generate_tuning_job(ws: Workspace, md, plan: ParallelPlan,
                        node_selector: dict,
                        image: str = DEFAULT_IMAGE) -> Unstructured:
    t = ws.tuning
    labels = {LABEL_WORKSPACE_NAME: ws.metadata.name}
    volumes = [{"name": "results", "emptyDir": {}},
               {"name": "data", "emptyDir": {}}]
    mounts = [{"name": "results", "mountPath": RESULTS_DIR},
              {"name": "data", "mountPath": DATA_DIR}]

    init_containers = []
    if t.input.urls:
        urls = " ".join(shlex.quote(u) for u in t.input.urls)
        init_containers.append({
            "name": "data-downloader",
            "image": "curlimages/curl:latest",
            "command": ["sh", "-c", f"cd {DATA_DIR} && for u in {urls}; do "
                        f"curl -sSLO \"$u\"; done"],
            "volumeMounts": [{"name": "data", "mountPath": DATA_DIR}],
        })
    elif t.input.image:
        init_containers.append({
            "name": "data-puller",
            "image": t.input.image,
            "command": ["sh", "-c", f"cp -r /data/* {DATA_DIR}/"],
            "volumeMounts": [{"name": "data", "mountPath": DATA_DIR}],
        })
    elif t.input.volume:
        volumes.append({"name": "input-volume", **t.input.volume})
        mounts.append({"name": "input-volume", "mountPath": DATA_DIR})

    containers = [{
        "name": "tuning",
        "image": image,
        "command": build_tuning_command(ws, md, plan),
        "volumeMounts": mounts,
        "resources": {
            "requests": {"google.com/tpu": str(plan.chip.chips_per_host)},
            "limits": {"google.com/tpu": str(plan.chip.chips_per_host)},
        },
    }, {
        # metrics sidecar (reference: metrics_server.py on :5000)
        "name": "metrics",
        "image": image,
        "command": ["python", "-m", "kaito_tpu.tuning.metrics_server",
                    "--port", "5000", "--results-dir", RESULTS_DIR],
        "ports": [{"containerPort": 5000}],
        "volumeMounts": [{"name": "results", "mountPath": RESULTS_DIR}],
    }]
    if t.output.image:
        # pusher waits for the sentinel then pushes results as an OCI
        # artifact (reference: pkg/workspace/image/pusher.go via ORAS)
        containers.append({
            "name": "pusher",
            "image": "ghcr.io/oras-project/oras:v1.2.0",
            "command": ["sh", "-c",
                        f"while [ ! -f {RESULTS_DIR}/{SENTINEL} ]; do sleep 5; done; "
                        f"cd {RESULTS_DIR} && oras push {shlex.quote(t.output.image)} ."],
            "volumeMounts": [{"name": "results", "mountPath": RESULTS_DIR}],
        })

    return Unstructured(
        "Job",
        ObjectMeta(name=f"{ws.metadata.name}", namespace=ws.metadata.namespace,
                   labels=labels),
        spec={
            "backoffLimit": 2,
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "nodeSelector": dict(node_selector),
                    "restartPolicy": "Never",
                    "initContainers": init_containers,
                    "containers": containers,
                    "volumes": volumes,
                    "tolerations": [{"key": "google.com/tpu",
                                     "operator": "Exists",
                                     "effect": "NoSchedule"}],
                },
            },
        })
