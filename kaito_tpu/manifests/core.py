"""Core k8s manifest rendering (dict-shaped; reference analogue is the
functional-options generator ``pkg/utils/generator/generator.go`` +
``pkg/workspace/manifests/manifests.go``)."""

from __future__ import annotations

from typing import Optional

from kaito_tpu.api.meta import ObjectMeta
from kaito_tpu.controllers.objects import Unstructured


def generate_service(name: str, namespace: str, selector: dict,
                     port: int = 5000, headless: bool = False,
                     labels: Optional[dict] = None) -> Unstructured:
    spec = {
        "selector": dict(selector),
        "ports": [{"name": "http", "port": port, "targetPort": port}],
    }
    if headless:
        spec["clusterIP"] = "None"
        spec["publishNotReadyAddresses"] = True
    return Unstructured(
        "Service",
        ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {})),
        spec=spec)


def generate_headless_service(name: str, namespace: str, selector: dict,
                              labels: Optional[dict] = None) -> Unstructured:
    """Pod-identity DNS for multi-host rendezvous (the reference uses a
    headless service for Ray leader discovery, manifests.go; ours feeds
    the JAX coordinator address <name>-0.<name>-headless...)."""
    return generate_service(f"{name}-headless", namespace, selector,
                            headless=True, labels=labels)


def generate_statefulset(
    name: str,
    namespace: str,
    *,
    replicas: int,
    labels: dict,
    node_selector: dict,
    containers: list[dict],
    init_containers: Optional[list[dict]] = None,
    volumes: Optional[list[dict]] = None,
    service_name: str = "",
    tolerations: Optional[list[dict]] = None,
) -> Unstructured:
    pod_spec = {
        "nodeSelector": dict(node_selector),
        "containers": containers,
        "tolerations": tolerations or [
            {"key": "google.com/tpu", "operator": "Exists",
             "effect": "NoSchedule"}],
    }
    if init_containers:
        pod_spec["initContainers"] = init_containers
    if volumes:
        pod_spec["volumes"] = volumes
    return Unstructured(
        "StatefulSet",
        ObjectMeta(name=name, namespace=namespace, labels=dict(labels)),
        spec={
            "replicas": replicas,
            "serviceName": service_name or f"{name}-headless",
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": pod_spec,
            },
        })
