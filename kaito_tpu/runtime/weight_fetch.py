"""Model weight fetch / streaming utility.

Parity with two reference mechanisms: the ModelMirror download job
(hf transfer into shared storage, ``pkg/modelmirror/download/job.go:33``)
and the model-streaming load path (vLLM runai_streamer from cloud blob,
``pkg/workspace/inference/modelstreaming/``).  On GKE the natural
substrate is GCS: managed mirrors download HF -> gs:// once; pods
stream safetensors straight from the bucket at startup.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys

logger = logging.getLogger(__name__)


def fetch_from_hub(model_id: str, dest: str, token: str = "") -> int:
    """Download safetensors + config via huggingface_hub (network
    permitting; in air-gapped test environments the local HF cache is
    the only source)."""
    from huggingface_hub import snapshot_download

    path = snapshot_download(
        model_id, token=token or None,
        allow_patterns=["*.safetensors", "*.json", "tokenizer*", "*.model"])
    os.makedirs(dest, exist_ok=True)
    for name in os.listdir(path):
        src = os.path.join(path, name)
        if os.path.isfile(src):
            shutil.copy2(src, os.path.join(dest, name))
    return 0


def copy_to_gcs(local: str, bucket_dest: str) -> int:
    """gs:// upload via gsutil (present on GKE node images)."""
    import subprocess

    return subprocess.call(["gsutil", "-m", "rsync", "-r", local, bucket_dest])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-id", required=True)
    ap.add_argument("--dest", required=True, help="local dir or gs:// URI")
    ap.add_argument("--hf-token", default=os.environ.get("HF_TOKEN", ""))
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    staging = args.dest
    to_gcs = args.dest.startswith("gs://")
    if to_gcs:
        staging = "/tmp/weight-staging"
    try:
        rc = fetch_from_hub(args.model_id, staging, args.hf_token)
    except Exception as e:
        logger.error("hub fetch failed: %s", e)
        return 1
    if rc == 0 and to_gcs:
        rc = copy_to_gcs(staging, args.dest)
    print(json.dumps({"model_id": args.model_id, "dest": args.dest,
                      "status": "ok" if rc == 0 else "failed"}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
