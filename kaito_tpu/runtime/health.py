"""Distributed health checks for multi-host slices.

Parity with the reference's ``multi-node-health-check.py`` (liveness =
Ray GCS state on the leader, readiness = leader vLLM /health): on TPU
the leader (pod ordinal 0) serves HTTP, workers are healthy iff the JAX
coordinator is reachable — the process would have crashed out of the
collective otherwise, so worker health is "coordinator TCP open AND my
engine process alive".
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import urllib.request


def pod_ordinal() -> int:
    """StatefulSet ordinal from the pod hostname suffix (or TPU_WORKER_ID)."""
    if "TPU_WORKER_ID" in os.environ:
        return int(os.environ["TPU_WORKER_ID"])
    host = os.environ.get("HOSTNAME", socket.gethostname())
    tail = host.rsplit("-", 1)[-1]
    return int(tail) if tail.isdigit() else 0


def leader_http_healthy(base: str, timeout: float = 5.0) -> bool:
    try:
        with urllib.request.urlopen(base + "/health", timeout=timeout) as r:
            return json.loads(r.read()).get("status") == "ok"
    except Exception:
        return False


def coordinator_reachable(addr: str, timeout: float = 5.0) -> bool:
    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection((host, int(port or 8476)), timeout=timeout):
            return True
    except OSError:
        return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", default="auto", choices=["auto", "leader", "worker"])
    ap.add_argument("--base-url", default="http://127.0.0.1:5000")
    ap.add_argument("--coordinator",
                    default=os.environ.get("KAITO_COORDINATOR", ""))
    args = ap.parse_args(argv)

    role = args.role
    if role == "auto":
        role = "leader" if pod_ordinal() == 0 else "worker"
    if role == "leader":
        ok = leader_http_healthy(args.base_url)
    else:
        ok = coordinator_reachable(args.coordinator) if args.coordinator else True
    print(json.dumps({"role": role, "healthy": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
