"""Data-parallel HTTP front: round-robin across N engine backends.

The in-miniature data plane of the repo's replica tier: in production,
InferenceSet replicas sit behind the rendered Service/InferencePool and
the GAIE EPP picks endpoints (``controllers/inferenceset.py``); the
reference's analogue is vLLM ``--data-parallel-size`` over Ray plus its
routing sidecar (``preset_inferences.go:909-985``).  This router is the
same contract as ONE process you can boot in tests, dryruns, and
single-node deployments: each backend is a fully independent engine
server (its own process, its own devices), and requests — including
SSE streams — relay byte-for-byte.

Scheduling is round-robin with health-aware skip: a backend that
refuses the connection is marked down and retried on a cool-down, so a
dead replica costs one skipped turn, not a failed request (behavior the
dp-over-2-procs test pins).
"""

from __future__ import annotations

import argparse
import http.client
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger(__name__)

DOWN_COOLDOWN_S = 5.0
HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding",
               "te", "trailer", "upgrade", "proxy-authorization"}


class _Backend:
    def __init__(self, url: str):
        url = url.rstrip("/")
        assert url.startswith("http://"), f"http backends only: {url}"
        self.url = url
        hostport = url[len("http://"):]
        self.host, _, port = hostport.partition(":")
        self.port = int(port or 80)
        self.down_until = 0.0
        self.served = 0

    @property
    def alive(self) -> bool:
        return time.monotonic() >= self.down_until

    def mark_down(self) -> None:
        self.down_until = time.monotonic() + DOWN_COOLDOWN_S


class DPRouter:
    """Round-robin chooser over backends, shared by handler threads."""

    def __init__(self, backends: list[str]):
        if not backends:
            raise ValueError("dp router needs at least one backend")
        self.backends = [_Backend(u) for u in backends]
        self._rr = 0
        self._lock = threading.Lock()

    def next_backend(self) -> Optional[_Backend]:
        """Next live backend (round robin), or the next one regardless
        if every backend is cooling down (better a refused retry than a
        guaranteed 503 when all marks are stale)."""
        with self._lock:
            n = len(self.backends)
            for offset in range(n):
                b = self.backends[(self._rr + offset) % n]
                if b.alive:
                    self._rr = (self._rr + offset + 1) % n
                    b.served += 1
                    return b
            b = self.backends[self._rr % n]
            self._rr = (self._rr + 1) % n
            b.served += 1
            return b

    def stats(self) -> dict:
        with self._lock:
            return {b.url: {"served": b.served, "alive": b.alive}
                    for b in self.backends}


def make_router_server(router: DPRouter, host: str = "0.0.0.0",
                       port: int = 0) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _relay(self, method: str):
            if self.path == "/router/stats":
                body = json.dumps(router.stats()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else None
            # failover is only safe BEFORE the first response byte: a
            # backend that dies mid-stream cannot be retried without
            # corrupting the client's half-written reply (and without
            # re-running the inference) — abort the connection instead
            tried = 0
            while tried < len(router.backends):
                b = router.next_backend()
                tried += 1
                try:
                    resp, conn = self._connect(b, method, body)
                except (ConnectionError, OSError) as e:
                    logger.warning("backend %s unreachable (%s); skipping",
                                   b.url, e)
                    b.mark_down()
                    continue
                self._stream_response(b, resp, conn)
                return
            self.send_response(503)
            msg = b'{"error": "no live backend"}'
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(msg)))
            self.end_headers()
            self.wfile.write(msg)

        def _connect(self, b: _Backend, method: str,
                     body: Optional[bytes]):
            """Send the request and read the response HEAD; raises are
            retryable (nothing has reached the client yet)."""
            conn = http.client.HTTPConnection(b.host, b.port, timeout=600)
            headers = {k: v for k, v in self.headers.items()
                       if k.lower() not in HOP_HEADERS}
            conn.request(method, self.path, body=body, headers=headers)
            return conn.getresponse(), conn

        def _stream_response(self, b: _Backend, resp, conn) -> None:
            """Relay an already-open backend response.  A BACKEND read
            failure marks it down and aborts the client connection (no
            retry — bytes are already out); a CLIENT write failure just
            ends the relay (the backend is healthy)."""
            try:
                self.send_response(resp.status)
                for k, v in resp.getheaders():
                    if k.lower() not in HOP_HEADERS:
                        self.send_header(k, v)
                has_len = resp.getheader("Content-Length") is not None
                if not has_len:
                    # stream of unknown length (SSE): relay chunked
                    self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                # relay bytes AS THEY ARRIVE so SSE tokens stream through
                while True:
                    try:
                        chunk = resp.read1(65536) if hasattr(resp, "read1") \
                            else resp.read(65536)
                    except (ConnectionError, OSError) as e:
                        logger.warning("backend %s died mid-stream (%s); "
                                       "aborting relay", b.url, e)
                        b.mark_down()
                        self.close_connection = True
                        return
                    if not chunk:
                        break
                    try:
                        if has_len:
                            self.wfile.write(chunk)
                        else:
                            self.wfile.write(
                                b"%x\r\n%s\r\n" % (len(chunk), chunk))
                        self.wfile.flush()
                    except (ConnectionError, OSError):
                        # client went away: backend stays healthy
                        self.close_connection = True
                        return
                if not has_len:
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                    except (ConnectionError, OSError):
                        self.close_connection = True
            finally:
                conn.close()

        def do_GET(self):
            self._relay("GET")

        def do_POST(self):
            self._relay("POST")

        def do_DELETE(self):
            self._relay("DELETE")

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kaito-tpu-dp-router")
    ap.add_argument("--backend", action="append", required=True,
                    help="backend base URL (repeat per replica)")
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--host", default="0.0.0.0")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    srv = make_router_server(DPRouter(args.backend), args.host, args.port)
    logger.info("dp router on :%d -> %s", srv.server_address[1],
                args.backend)
    srv.serve_forever()


if __name__ == "__main__":
    main()
