"""Data-parallel HTTP front: round-robin across N engine backends.

The in-miniature data plane of the repo's replica tier: in production,
InferenceSet replicas sit behind the rendered Service/InferencePool and
the GAIE EPP picks endpoints (``controllers/inferenceset.py``); the
reference's analogue is vLLM ``--data-parallel-size`` over Ray plus its
routing sidecar (``preset_inferences.go:909-985``).  This router is the
same contract as ONE process you can boot in tests, dryruns, and
single-node deployments: each backend is a fully independent engine
server (its own process, its own devices), and requests — including
SSE streams — relay byte-for-byte.

Failure-domain design (docs/failure-domains.md):

- Each backend carries a **circuit breaker**: consecutive connect
  failures open it with exponentially-backed-off cooldowns (capped);
  when the cooldown lapses the breaker is **half-open** — the next
  request probes it, and one success closes it again (``mark_up``).
- **Health probes**: an optional background thread GETs ``/health`` per
  backend, closing breakers as replicas recover without spending a
  client request on the probe.
- **Retry with jittered backoff**: idempotent requests (GET/DELETE and
  the stateless POST inference routes) retry against alternate replicas
  — across backends immediately, and across full cycles after a
  jittered sleep — as long as no response byte has reached the client.
- **Graceful drain**: SIGTERM stops accepting (503 + Retry-After),
  lets in-flight relays finish, then exits — the InferenceSet
  rolling-update contract.
"""

from __future__ import annotations

import argparse
import http.client
import json
import logging
import random
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kaito_tpu.engine.metrics import Counter, Gauge, Histogram, Registry
from kaito_tpu.utils.failpoints import FAILPOINTS, FailpointError
from kaito_tpu.utils.tracing import (make_request_id, parse_traceparent,
                                     sanitize_request_id)

logger = logging.getLogger(__name__)

DOWN_COOLDOWN_S = 5.0
DOWN_COOLDOWN_MAX_S = 60.0
BREAKER_THRESHOLD = 3          # consecutive failures that OPEN the breaker
RETRY_CYCLES = 2               # full passes over the backend list
RETRY_BACKOFF_S = 0.1          # jittered sleep between cycles
HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding",
               "te", "trailer", "upgrade", "proxy-authorization"}
# POST routes that are safe to replay against another replica before any
# response byte: stateless inference (any replica computes the same
# answer).  PD side-channel routes mutate per-replica staging state and
# must NOT fail over blindly.
IDEMPOTENT_POST_PREFIXES = ("/v1/completions", "/v1/chat/completions",
                            "/v1/embeddings", "/score", "/tokenize",
                            "/detokenize")


class _Backend:
    """One replica plus its circuit-breaker state.

    ``down_until`` stays THE open-until timestamp (tests poke it to
    heal a backend); ``failures`` counts CONSECUTIVE connect failures.
    State is derived, never stored:

    - ``open``      — cooling down (``down_until`` in the future)
    - ``half-open`` — cooldown lapsed but the breaker tripped and no
      success has closed it yet (the next request is the probe)
    - ``closed``    — healthy
    """

    def __init__(self, url: str):
        url = url.rstrip("/")
        assert url.startswith("http://"), f"http backends only: {url}"
        self.url = url
        hostport = url[len("http://"):]
        self.host, _, port = hostport.partition(":")
        self.port = int(port or 80)
        self.down_until = 0.0
        self.served = 0
        self.failures = 0

    @property
    def alive(self) -> bool:
        return time.monotonic() >= self.down_until

    @property
    def state(self) -> str:
        if not self.alive:
            return "open"
        if self.failures >= BREAKER_THRESHOLD:
            return "half-open"
        return "closed"

    def mark_down(self) -> None:
        """One more consecutive failure: cool down with exponential
        backoff (capped) so a dead replica is probed ever less often
        while it stays dead."""
        self.failures += 1
        backoff = min(DOWN_COOLDOWN_S * (2 ** max(0, self.failures
                                                  - BREAKER_THRESHOLD)),
                      DOWN_COOLDOWN_MAX_S)
        self.down_until = time.monotonic() + backoff

    def mark_up(self) -> None:
        """A success (request or health probe) closes the breaker."""
        self.failures = 0
        self.down_until = 0.0


_BREAKER_STATES = {"closed": 0, "half-open": 1, "open": 2}


class DPRouter:
    """Round-robin chooser over backends, shared by handler threads."""

    def __init__(self, backends: list[str]):
        if not backends:
            raise ValueError("dp router needs at least one backend")
        self.backends = [_Backend(u) for u in backends]
        self._rr = 0
        self._lock = threading.Lock()
        self.draining = False
        self._inflight = 0
        # router's OWN /metrics (docs/observability.md): the engine
        # replicas each expose theirs; these series cover the relay tier
        r = Registry()
        self.registry = r
        self.m_forwarded = Counter(
            "kaito:router_requests_forwarded_total",
            "Requests relayed to a backend (response head received)",
            r, labels=("backend",))
        self.m_retries = Counter(
            "kaito:router_retries_total",
            "Relay attempts beyond each request's first", r,
            labels=("backend",))
        self.m_failures = Counter(
            "kaito:router_backend_failures_total",
            "Connect/forward failures that skipped a backend", r,
            labels=("backend",))
        self.upstream_latency = Histogram(
            "kaito:router_upstream_latency_seconds",
            "Forward-to-response-head latency per backend", r,
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
            labels=("backend",))
        # breaker state is time-derived (down_until vs now), so the
        # family is computed at scrape time via the labelled-fn Gauge
        Gauge("kaito:router_backend_breaker_state",
              "Circuit breaker per backend (0=closed, 1=half-open, 2=open)",
              r, labels=("backend",),
              fn=lambda: {(b.url,): _BREAKER_STATES[b.state]
                          for b in self.backends})

    def next_backend(self) -> Optional[_Backend]:
        """Next live backend (round robin), or the next one regardless
        if every backend is cooling down (better a refused retry than a
        guaranteed 503 when all marks are stale)."""
        with self._lock:
            n = len(self.backends)
            for offset in range(n):
                b = self.backends[(self._rr + offset) % n]
                if b.alive:
                    self._rr = (self._rr + offset + 1) % n
                    b.served += 1
                    return b
            b = self.backends[self._rr % n]
            self._rr = (self._rr + 1) % n
            b.served += 1
            return b

    def stats(self) -> dict:
        with self._lock:
            return {b.url: {"served": b.served, "alive": b.alive,
                            "state": b.state, "failures": b.failures}
                    for b in self.backends}

    # -- drain bookkeeping -------------------------------------------------
    def begin_request(self) -> bool:
        """Admission gate: False while draining (caller answers 503)."""
        with self._lock:
            if self.draining:
                return False
            self._inflight += 1
            return True

    def end_request(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop accepting, wait for in-flight relays to finish.  Returns
        True when the router went quiet inside the timeout."""
        with self._lock:
            self.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.inflight == 0:
                return True
            time.sleep(0.05)
        return self.inflight == 0


class HealthProber(threading.Thread):
    """Background ``/health`` probe per backend: closes breakers as
    replicas recover, opens them when a live-looking backend refuses
    the probe — without spending client requests on discovery."""

    def __init__(self, router: DPRouter, interval_s: float = 2.0):
        super().__init__(daemon=True, name="dp-health-prober")
        self.router = router
        self.interval_s = interval_s
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            for b in self.router.backends:
                try:
                    conn = http.client.HTTPConnection(b.host, b.port,
                                                      timeout=5)
                    try:
                        conn.request("GET", "/health")
                        ok = conn.getresponse().status == 200
                    finally:
                        conn.close()
                except (ConnectionError, OSError):
                    ok = False
                if ok:
                    if b.failures:
                        logger.info("health probe: %s recovered", b.url)
                    b.mark_up()
                elif b.alive:
                    b.mark_down()


def _retryable(method: str, path: str) -> bool:
    """May this request be replayed against another replica (before any
    response byte)?  GET/DELETE always; POST only on the stateless
    inference routes."""
    if method in ("GET", "DELETE", "HEAD"):
        return True
    if method == "POST":
        return any(path.startswith(p) for p in IDEMPOTENT_POST_PREFIXES)
    return False


def make_router_server(router: DPRouter, host: str = "0.0.0.0",
                       port: int = 0,
                       probe_interval_s: float = 0.0) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send_json(self, code: int, obj: dict,
                       headers: Optional[dict] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            rid = getattr(self, "_rid", None)
            if rid:
                self.send_header("X-Request-Id", rid)
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)

        def _read_request_body(self) -> Optional[bytes]:
            """Read the client body whichever way it was framed.  A
            ``Transfer-Encoding: chunked`` body is DE-CHUNKED here and
            forwarded with Content-Length (http.client sets it), so a
            chunked client upload is no longer silently dropped."""
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                chunks = []
                while True:
                    size_line = self.rfile.readline(65536).strip()
                    size = int(size_line.split(b";")[0] or b"0", 16)
                    if size == 0:
                        # consume trailers until the blank line
                        while self.rfile.readline(65536).strip():
                            pass
                        break
                    chunks.append(self.rfile.read(size))
                    self.rfile.read(2)          # CRLF after each chunk
                return b"".join(chunks)
            length = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(length) if length else None

        def _relay(self, method: str):
            # end-to-end tracing: accept the caller's X-Request-Id (or
            # a W3C traceparent), mint one otherwise, and forward it so
            # router + engine logs/spans correlate on one id.
            self._rid = (sanitize_request_id(self.headers.get("X-Request-Id"))
                         or parse_traceparent(self.headers.get("traceparent"))
                         or make_request_id())
            if self.path == "/router/stats":
                self._send_json(200, router.stats())
                return
            if self.path == "/metrics" and method == "GET":
                # the router's OWN series, never forwarded: per-backend
                # forwards/retries/failures, breaker state, latency
                body = router.registry.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if not router.begin_request():
                self._send_json(503, {"error": "router draining"},
                                headers={"Retry-After": 1})
                return
            try:
                self._relay_inner(method)
            finally:
                router.end_request()

        def _relay_inner(self, method: str):
            try:
                body = self._read_request_body()
            except (ValueError, ConnectionError, OSError):
                self._send_json(400, {"error": "malformed request body"})
                return
            # failover is only safe BEFORE the first response byte: a
            # backend that dies mid-stream cannot be retried without
            # corrupting the client's half-written reply (and without
            # re-running the inference) — abort the connection instead.
            # Retryable requests get RETRY_CYCLES full passes over the
            # list with a jittered backoff between passes; one-shot
            # (non-idempotent) requests get a single pass.
            retryable = _retryable(method, self.path)
            cycles = RETRY_CYCLES if retryable else 1
            last_status: Optional[int] = None
            attempts = 0
            for cycle in range(cycles):
                if cycle:
                    time.sleep(RETRY_BACKOFF_S * (1 + random.random()))
                tried = 0
                while tried < len(router.backends):
                    b = router.next_backend()
                    tried += 1
                    attempts += 1
                    if attempts > 1:
                        router.m_retries.inc(backend=b.url)
                    t_fwd = time.monotonic()
                    try:
                        resp, conn = self._connect(b, method, body)
                    except (ConnectionError, OSError, FailpointError) as e:
                        logger.warning("backend %s unreachable (%s); "
                                       "skipping", b.url, e)
                        router.m_failures.inc(backend=b.url)
                        b.mark_down()
                        continue
                    router.upstream_latency.observe(
                        time.monotonic() - t_fwd, backend=b.url)
                    if retryable and resp.status in (502, 503) \
                            and (cycle + 1 < cycles
                                 or tried < len(router.backends)):
                        # the replica answered but cannot serve (loading
                        # stub, drain, overload): try elsewhere.  The
                        # breaker does NOT trip — the process is alive.
                        last_status = resp.status
                        conn.close()
                        continue
                    b.mark_up()
                    router.m_forwarded.inc(backend=b.url)
                    self._stream_response(b, method, resp, conn)
                    return
            self._send_json(503 if last_status is None else last_status,
                            {"error": "no live backend"},
                            headers={"Retry-After": 1})

        def _connect(self, b: _Backend, method: str,
                     body: Optional[bytes]):
            """Send the request and read the response HEAD; raises are
            retryable (nothing has reached the client yet)."""
            FAILPOINTS.fire("router.forward", backend=b.url)
            conn = http.client.HTTPConnection(b.host, b.port, timeout=600)
            headers = {k: v for k, v in self.headers.items()
                       if k.lower() not in HOP_HEADERS
                       and k.lower() not in ("content-length",
                                             "x-request-id")}
            headers["X-Request-Id"] = self._rid
            conn.request(method, self.path, body=body, headers=headers)
            return conn.getresponse(), conn

        def _stream_response(self, b: _Backend, method: str, resp,
                             conn) -> None:
            """Relay an already-open backend response.  A BACKEND read
            failure marks it down and aborts the client connection (no
            retry — bytes are already out); a CLIENT write failure just
            ends the relay (the backend is healthy)."""
            try:
                self.send_response(resp.status)
                for k, v in resp.getheaders():
                    if k.lower() not in HOP_HEADERS:
                        self.send_header(k, v)
                # 1xx/204/304 (and HEAD replies) carry NO body by spec:
                # chunked framing (or a terminator) after their headers
                # would corrupt the connection for the next request
                bodyless = (resp.status < 200 or resp.status in (204, 304)
                            or method == "HEAD")
                has_len = resp.getheader("Content-Length") is not None
                if not has_len and not bodyless:
                    # stream of unknown length (SSE): relay chunked
                    self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                if bodyless:
                    return
                # relay bytes AS THEY ARRIVE so SSE tokens stream through
                while True:
                    try:
                        chunk = resp.read1(65536) if hasattr(resp, "read1") \
                            else resp.read(65536)
                    except (ConnectionError, OSError) as e:
                        logger.warning("backend %s died mid-stream (%s); "
                                       "aborting relay", b.url, e)
                        b.mark_down()
                        self.close_connection = True
                        return
                    if not chunk:
                        break
                    try:
                        if has_len:
                            self.wfile.write(chunk)
                        else:
                            self.wfile.write(
                                b"%x\r\n%s\r\n" % (len(chunk), chunk))
                        self.wfile.flush()
                    except (ConnectionError, OSError):
                        # client went away: backend stays healthy
                        self.close_connection = True
                        return
                if not has_len:
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                    except (ConnectionError, OSError):
                        self.close_connection = True
            finally:
                conn.close()

        def do_GET(self):
            self._relay("GET")

        def do_POST(self):
            self._relay("POST")

        def do_DELETE(self):
            self._relay("DELETE")

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.router = router                      # type: ignore[attr-defined]
    if probe_interval_s > 0:
        prober = HealthProber(router, probe_interval_s)
        prober.start()
        srv.prober = prober                  # type: ignore[attr-defined]
    return srv


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kaito-tpu-dp-router")
    ap.add_argument("--backend", action="append", required=True,
                    help="backend base URL (repeat per replica)")
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--health-probe-interval-s", type=float, default=2.0,
                    help="per-backend /health probe cadence (0 = off)")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM grace: max seconds to finish in-flight "
                         "requests before exit")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    router = DPRouter(args.backend)
    srv = make_router_server(router, args.host, args.port,
                             probe_interval_s=args.health_probe_interval_s)

    def _term(signum, frame):
        # graceful drain: stop accepting, finish in-flight, exit — the
        # rolling-update contract (new requests get 503 + Retry-After,
        # the Gateway retries them on another replica)
        logger.info("SIGTERM: draining %d in-flight request(s)",
                    router.inflight)
        threading.Thread(target=lambda: (router.drain(args.drain_timeout_s),
                                         srv.shutdown()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    logger.info("dp router on :%d -> %s", srv.server_address[1],
                args.backend)
    srv.serve_forever()
    logger.info("dp router exited cleanly")


if __name__ == "__main__":
    main()
