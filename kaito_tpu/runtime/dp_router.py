"""Data-parallel HTTP front: round-robin across N engine backends.

This is now a THIN compatibility front over the shared routing data
path in ``kaito_tpu/runtime/routing.py`` (docs/routing.md): the circuit
breaker, ``/health`` prober, jittered idempotent retry, SSE byte relay,
chunked-body handling, X-Request-Id propagation, and SIGTERM drain all
live there, shared verbatim with the first-party endpoint picker
(``kaito_tpu/runtime/epp.py``) that the InferencePool's ``extensionRef``
resolves to.  What remains here is only the classic policy — blind
round robin — plus the historical module surface that tests, dryruns
and single-node deployments import.

The in-miniature data plane of the repo's replica tier: in production,
InferenceSet replicas sit behind the rendered Service/InferencePool and
the EPP picks endpoints (``controllers/inferenceset.py``); the
reference's analogue is vLLM ``--data-parallel-size`` over Ray plus its
routing sidecar (``preset_inferences.go:909-985``).  This router is the
same contract as ONE process you can boot in tests, dryruns, and
single-node deployments: each backend is a fully independent engine
server (its own process, its own devices), and requests — including
SSE streams — relay byte-for-byte.

Failure-domain design (docs/failure-domains.md):

- Each backend carries a **circuit breaker**: consecutive connect
  failures open it with exponentially-backed-off cooldowns (capped);
  when the cooldown lapses the breaker is **half-open** — the next
  request probes it, and one success closes it again (``mark_up``).
- **Health probes**: an optional background thread GETs ``/health`` per
  backend, closing breakers as replicas recover without spending a
  client request on the probe.
- **Retry with jittered backoff**: idempotent requests (GET/DELETE and
  the stateless POST inference routes) retry against alternate replicas
  — across backends immediately, and across full cycles after a
  jittered sleep — as long as no response byte has reached the client.
- **Graceful drain**: SIGTERM stops accepting (503 + Retry-After),
  lets in-flight relays finish, then exits — the InferenceSet
  rolling-update contract.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

# Re-exported so existing imports (tests, helpers, bench harnesses)
# keep working against the historical dp_router module surface.
from kaito_tpu.runtime.routing import (BREAKER_THRESHOLD,  # noqa: F401
                                       DOWN_COOLDOWN_MAX_S, DOWN_COOLDOWN_S,
                                       HOP_HEADERS, IDEMPOTENT_POST_PREFIXES,
                                       RETRY_BACKOFF_S, RETRY_CYCLES, Backend,
                                       HealthProber, RoutingCore, _retryable,
                                       make_routing_server)

logger = logging.getLogger(__name__)

# historical name: the backend class predates the shared routing lib
_Backend = Backend


class DPRouter(RoutingCore):
    """Round-robin chooser over backends, shared by handler threads.

    Pure policy: ``RoutingCore`` owns the breaker/drain/metrics state
    and its default ``candidates`` IS round robin, so this subclass
    only pins down the historical constructor (a list of URL strings).
    """

    def __init__(self, backends: list[str]):
        super().__init__(backends)


def make_router_server(router, host: str = "0.0.0.0", port: int = 0,
                       probe_interval_s: float = 0.0):
    """Historical entry point; the relay itself is the shared one."""
    return make_routing_server(router, host, port,
                               probe_interval_s=probe_interval_s)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kaito-tpu-dp-router")
    ap.add_argument("--backend", action="append", required=True,
                    help="backend base URL (repeat per replica)")
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--health-probe-interval-s", type=float, default=2.0,
                    help="per-backend /health probe cadence (0 = off)")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM grace: max seconds to finish in-flight "
                         "requests before exit")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    router = DPRouter(args.backend)
    srv = make_router_server(router, args.host, args.port,
                             probe_interval_s=args.health_probe_interval_s)

    def _term(signum, frame):
        # graceful drain: stop accepting, finish in-flight, exit — the
        # rolling-update contract (new requests get 503 + Retry-After,
        # the Gateway retries them on another replica)
        logger.info("SIGTERM: draining %d in-flight request(s)",
                    router.inflight)
        threading.Thread(target=lambda: (router.drain(args.drain_timeout_s),
                                         srv.shutdown()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    logger.info("dp router on :%d -> %s", srv.server_address[1],
                args.backend)
    srv.serve_forever()
    logger.info("dp router exited cleanly")


if __name__ == "__main__":
    main()
