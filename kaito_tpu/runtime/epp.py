"""First-party endpoint picker (EPP) behind the InferencePool.

Both controllers render an InferencePool whose ``extensionRef`` names
``<name>-epp`` (``controllers/inferenceset.py``,
``controllers/multiroleinference.py``); this module is that picker.
It rides the shared routing data path (``runtime/routing.py`` — same
breaker/retry/SSE relay/drain as the round-robin dp_router front) and
replaces only the candidate ORDER with a scored one (docs/routing.md):

1. **Prefix-hash affinity** — a bounded LRU of recent prompt-prefix
   block hashes per backend, block size aligned to the engine's
   prefix-cache page size, so repeated-prefix traffic lands on the
   replica whose radix tree already holds the KV (SGLang-style
   cache-aware routing).
2. **Live load** — ``kaito:batch_occupancy``, queue depth, and KV
   utilization scraped from each replica's ``/metrics``; hysteresis
   (enter-high/exit-low watermarks) keeps affinity from steering onto
   a saturated or breaker-open backend.
3. **PD plugin chain** — decode requests carrying a staged-KV
   ``kv_transfer`` handle steer to the prefill-owning replica (or its
   group), honoring the MultiRoleInference ``eppPluginsConfig`` chain
   (pd-filter / kv-locality-scorer / queue-depth-scorer).

The picker exports its own ``kaito:epp_*`` series next to the shared
``kaito:router_*`` transport families on ``/metrics``.
"""

from __future__ import annotations

import argparse
import collections
import http.client
import json
import logging
import signal
import threading
from typing import Iterable, Optional

from kaito_tpu.engine.metrics import Counter, Gauge, Registry
from kaito_tpu.engine.qos import priority_rank
from kaito_tpu.runtime.routing import (Backend, PrefixAffinityIndex,
                                       RoutingCore, _BackendPoller, _MASK64,
                                       _fnv1a, adapter_seed,
                                       extract_prompt_text,
                                       make_routing_server, prefix_blocks)

logger = logging.getLogger(__name__)

# With no tokenizer in the picker, block size is expressed in CHARS and
# aligned to the engine's KV page size (tokens) via a chars-per-token
# estimate: ~4 chars/token is the usual English/BPE rule of thumb, and
# over-estimating only makes affinity blocks COARSER than engine pages
# (a char-block hit still maps onto whole cached pages).
CHARS_PER_TOKEN = 4
DEFAULT_BLOCK_CHARS = 64       # engine default page_size=16 tokens * 4

# score weight that dominates load terms when most prefix blocks match
AFFINITY_WEIGHT = 3.0

# cluster KV-pool locality weight: below AFFINITY_WEIGHT (a radix-tree
# hit on the picked replica beats a cross-replica fetch) but above the
# load terms, so a healthy holder wins ties against equally-loaded peers
POOL_WEIGHT = 2.5

# adapter-residency weight (docs/multi-lora.md): below POOL_WEIGHT —
# faulting an adapter in from a replica's host tier (or hot-loading it)
# is cheaper than re-prefilling a long prefix — but above the load
# terms, so adapter-tagged traffic concentrates on replicas already
# serving that adapter instead of spreading slot-table churn fleet-wide
ADAPTER_WEIGHT = 2.0

# cap on adapter names folded in per advert — a hand-rolled replica
# can't balloon the index (real slot tables hold a few dozen at most)
_MAX_ADAPTERS_PER_ADVERT = 1024


class KVPoolIndex:
    """Cluster-wide prefix→holder lookup (docs/kv-pool.md).

    Built from the ``/debug/kv_pool`` adverts each replica serves:
    every advertised entry contributes one index row PER BLOCK HASH in
    its chain, so a request matching only the first half of a long
    published prefix still finds the holder.  Because the hashes are
    chained (block *i* folds every earlier block), equality at position
    *i* implies — up to hash collision, which the ENGINE's token-level
    trim makes harmless — that the whole *i+1*-block prefix matches.
    Rows are keyed by (block_chars, hash) so adverts from replicas
    configured with a different page size can never cross-match."""

    # retained rows per URL when a replica sends CAPPED adverts (the
    # merge path below never wholesale-replaces, so bound what a
    # long-lived replica can accumulate in the index)
    MAX_ENTRIES_PER_URL = 4096

    def __init__(self):
        self._lock = threading.Lock()
        # url -> {"block_chars": int, "entries": OrderedDict key->entry
        # (freshest LAST)}
        self._adverts: dict[str, dict] = {}
        # (block_chars, hash hex) -> url -> (entry key, n_pages, n_tokens)
        self._index: dict = {}
        self.updates = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def update(self, url: str, advert: Optional[dict]) -> None:
        """Fold one replica's advert (None/empty/disabled = forget
        it — a scrape failure or a rollout restart must not leave
        stale holders steering fetches at a replica without the KV;
        the fetch path degrades to recompute anyway, this just keeps
        the hint hit rate honest).

        A FULL advert wholesale-replaces the replica's rows.  A CAPPED
        advert (``"capped": true`` — the store listed only its
        freshest N entries) is authoritative only for the rows it
        lists: listed keys are refreshed/added, unlisted rows are
        retained (bounded by ``MAX_ENTRIES_PER_URL``) — an evicted
        retained row just degrades a later fetch to an ordinary
        miss."""
        with self._lock:
            if (isinstance(advert, dict) and advert.get("enabled")
                    and advert.get("entries")):
                bc = int(advert.get("block_chars") or 0)
                # the wire lists freshest FIRST; key the rows freshest
                # LAST so popitem(last=False) ages out the stalest
                fresh: "collections.OrderedDict[str, dict]" = \
                    collections.OrderedDict(
                        (str(e.get("key") or ""), e)
                        for e in reversed(advert["entries"])
                        if e.get("key"))
                prev = self._adverts.get(url)
                if (advert.get("capped") and prev is not None
                        and prev["block_chars"] == bc):
                    merged = prev["entries"]
                    for k, e in fresh.items():
                        merged.pop(k, None)
                        merged[k] = e
                    while len(merged) > self.MAX_ENTRIES_PER_URL:
                        merged.popitem(last=False)
                    entries = merged
                else:
                    entries = fresh
                self._adverts[url] = {"block_chars": bc,
                                      "entries": entries}
            else:
                self._adverts.pop(url, None)
            self._rebuild_locked()
            self.updates += 1

    def drop(self, url: str) -> None:
        self.update(url, None)

    def _rebuild_locked(self) -> None:
        idx: dict = {}
        for url, adv in self._adverts.items():
            bc = adv["block_chars"]
            for e in adv["entries"].values():
                blocks = e.get("blocks") or []
                key = str(e.get("key") or "")
                n_tokens = int(e.get("n_tokens") or 0)
                if not blocks or not key:
                    continue
                for i, h in enumerate(blocks):
                    holders = idx.setdefault((bc, str(h)), {})
                    cur = holders.get(url)
                    # same hash can appear in several entries (shared
                    # prefixes): keep the one serving the most pages
                    if cur is None or i + 1 > cur[1]:
                        holders[url] = (key, i + 1, n_tokens)
        self._index = idx

    def match(self, blocks: list[int],
              block_chars: int) -> dict[str, tuple]:
        """url -> (entry key, matched pages, entry tokens) for the
        LONGEST advertised prefix of ``blocks`` — scan from the tail so
        the first hit is the best one."""
        hexes = [f"{b & _MASK64:016x}" for b in blocks]
        with self._lock:
            for i in range(len(hexes) - 1, -1, -1):
                holders = self._index.get((block_chars, hexes[i]))
                if holders:
                    return dict(holders)
        return {}


class KVPoolScraper(_BackendPoller):
    """Background ``/debug/kv_pool`` advert scrape per backend: keeps
    the cluster prefix→holder index fresh without spending a request
    round trip.  A 403 (pool disabled), connect failure, or garbage
    body clears that replica's rows."""

    def __init__(self, picker: "EndpointPicker", interval_s: float = 2.0,
                 timeout_s: float = 2.0):
        super().__init__("epp-kv-pool-scraper", interval_s)
        self.picker = picker
        self.timeout_s = timeout_s

    def targets(self) -> Iterable[Backend]:
        return [b for b in self.picker.backends if b.alive]

    def poll_one(self, b: Backend) -> None:
        advert = None
        try:
            conn = http.client.HTTPConnection(b.host, b.port,
                                              timeout=self.timeout_s)
            try:
                conn.request("GET", "/debug/kv_pool")
                resp = conn.getresponse()
                if resp.status == 200:
                    advert = json.loads(resp.read().decode("utf-8",
                                                           "replace"))
            finally:
                conn.close()
        except (ConnectionError, OSError, ValueError):
            advert = None
        if self.picker.pool_index is not None:
            self.picker.pool_index.update(b.url, advert)


class AdapterIndex:
    """Fleet-wide adapter→holder lookup (docs/multi-lora.md).

    Built from the ``/v1/adapters`` snapshots each replica serves:
    per replica, which adapters sit in its HBM slot table (score 1.0 —
    requests dispatch against them immediately) and which are parked in
    its host tier (score 0.5 — a fault-back-in away).  The union of all
    advertised names doubles as the picker's answer to "is this
    request's ``model`` field an adapter?" — the EPP has no catalog of
    its own, so only names the fleet actually serves get the
    adapter-seeded hash chain (a not-yet-scraped adapter degrades to
    unseeded blocks: no affinity signal, no pool match, never a wrong
    route)."""

    def __init__(self):
        self._lock = threading.Lock()
        # url -> {adapter name -> residency score (1.0 HBM, 0.5 host)}
        self._by_url: dict[str, dict[str, float]] = {}
        self._names: set[str] = set()           # fleet-wide union
        self.updates = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._names)

    def update(self, url: str, snap: Optional[dict]) -> None:
        """Replace one replica's advert (None/disabled = forget it —
        a restart or scrape failure must not leave stale residency
        steering adapter traffic at a replica that dropped it)."""
        with self._lock:
            if isinstance(snap, dict) and snap.get("enabled"):
                scores: dict[str, float] = {}
                for e in (snap.get("resident") or
                          [])[:_MAX_ADAPTERS_PER_ADVERT]:
                    name = str((e or {}).get("name") or "")
                    if name:
                        scores[name] = 1.0
                for name in (snap.get("host_tier") or
                             [])[:_MAX_ADAPTERS_PER_ADVERT]:
                    scores.setdefault(str(name), 0.5)
                if scores:
                    self._by_url[url] = scores
                else:
                    self._by_url.pop(url, None)
            else:
                self._by_url.pop(url, None)
            self._names = set().union(*self._by_url.values()) \
                if self._by_url else set()
            self.updates += 1

    def drop(self, url: str) -> None:
        self.update(url, None)

    def known(self, name: str) -> bool:
        with self._lock:
            return name in self._names

    def residency(self, name: str) -> dict[str, float]:
        """url -> residency score for every replica serving ``name``."""
        with self._lock:
            return {url: scores[name]
                    for url, scores in self._by_url.items()
                    if name in scores}


class AdapterScraper(_BackendPoller):
    """Background ``/v1/adapters`` snapshot scrape per backend (the
    same poller family as the KV-pool advert scrape).  A 403 (cache
    disabled), connect failure, or garbage body clears that replica's
    residency rows."""

    def __init__(self, picker: "EndpointPicker", interval_s: float = 2.0,
                 timeout_s: float = 2.0):
        super().__init__("epp-adapter-scraper", interval_s)
        self.picker = picker
        self.timeout_s = timeout_s

    def targets(self) -> Iterable[Backend]:
        return [b for b in self.picker.backends if b.alive]

    def poll_one(self, b: Backend) -> None:
        snap = None
        try:
            conn = http.client.HTTPConnection(b.host, b.port,
                                              timeout=self.timeout_s)
            try:
                conn.request("GET", "/v1/adapters")
                resp = conn.getresponse()
                if resp.status == 200:
                    snap = json.loads(resp.read().decode("utf-8",
                                                         "replace"))
            finally:
                conn.close()
        except (ConnectionError, OSError, ValueError):
            snap = None
        if self.picker.adapter_index is not None:
            self.picker.adapter_index.update(b.url, snap)


def default_epp_plugins_config() -> dict:
    """Standalone (InferenceSet) chain: no roles to filter, so the
    pd-filter is a no-op and affinity + load do the work."""
    return {
        "plugins": [
            {"type": "pd-filter"},
            {"type": "prefix-affinity-scorer", "weight": AFFINITY_WEIGHT},
            {"type": "kv-locality-scorer", "weight": 2},
            {"type": "queue-depth-scorer", "weight": 1},
            {"type": "kv-load-scorer", "weight": 1},
            # QoS (docs/qos.md): both are inert (score 0) for requests
            # without an X-Kaito-Tenant / X-Kaito-Priority header
            {"type": "tenant-stickiness-scorer", "weight": 1},
            {"type": "priority-scorer", "weight": 1},
        ],
    }


class RequestCtx:
    """Everything scoring needs, parsed once per request."""

    __slots__ = ("blocks", "matched", "kv_source", "want_role", "steered",
                 "tenant", "priority", "pool_match", "adapter",
                 "adapter_residency", "session")

    def __init__(self):
        self.blocks: list[int] = []            # prompt prefix block hashes
        self.matched: dict[str, int] = {}      # url -> consecutive hits
        self.kv_source: str = ""               # kv_transfer.source_url
        self.want_role: str = ""               # "", "prefill", "decode"
        self.steered = False                   # PD locality won the pick
        self.tenant: str = ""                  # X-Kaito-Tenant (QoS)
        self.priority: str = ""                # X-Kaito-Priority class name
        self.session: str = ""                 # X-Kaito-Session conv id
        # cluster KV pool: url -> (entry key, matched pages, entry tokens)
        self.pool_match: dict[str, tuple] = {}
        self.adapter: str = ""                 # resolved LoRA adapter name
        # url -> residency score (1.0 HBM slot, 0.5 host tier)
        self.adapter_residency: dict[str, float] = {}


def _extract_prompt(body: Optional[bytes]) -> str:
    """Best-effort prompt text from an inference request body; any
    parse failure just means no affinity signal for this request."""
    if not body:
        return ""
    try:
        obj = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return ""
    # extraction shared with the engine-side KV-pool publisher so both
    # hash the SAME bytes (routing.extract_prompt_text)
    return extract_prompt_text(obj)


def _extract_kv_source(body: Optional[bytes]) -> str:
    if not body:
        return ""
    try:
        obj = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return ""
    if not isinstance(obj, dict):
        return ""
    kt = obj.get("kv_transfer")
    if isinstance(kt, dict):
        src = kt.get("source_url")
        if isinstance(src, str):
            return src.rstrip("/")
    return ""


class EndpointPicker(RoutingCore):
    """Scored candidate ordering over the shared routing transport."""

    def __init__(self, backends: list, *, block_chars: int = 0,
                 index_capacity: int = 65536,
                 plugins_config: Optional[dict] = None,
                 registry: Optional[Registry] = None,
                 draining: Optional[Iterable[str]] = None,
                 kv_pool: bool = False,
                 adapter_affinity: bool = False):
        # empty pools are legal here: a scaled-to-zero InferenceSet
        # keeps its EPP front alive so arrivals surface as
        # kaito:router_requests_received_total (the wake signal) while
        # clients get a retryable 503 instead of a dead DNS name
        super().__init__(backends, registry, allow_empty=True)
        for url in draining or ():
            self.set_draining(url)
        self._block_chars = block_chars        # 0 = auto from kv_page_size
        self.index = PrefixAffinityIndex(index_capacity)
        cfg = plugins_config or default_epp_plugins_config()
        self.plugins = [(p.get("type", ""), float(p.get("weight", 1)))
                        for p in cfg.get("plugins", [])
                        if isinstance(p, dict)]
        # cluster KV pool (docs/kv-pool.md): the index + scorer exist
        # only when enabled, so with the pool off the scoring math and
        # the /metrics exposition are byte-identical to before
        self.pool_index = KVPoolIndex() if kv_pool else None
        if kv_pool and not any(t == "kv-pool-scorer"
                               for t, _ in self.plugins):
            self.plugins.append(("kv-pool-scorer", POOL_WEIGHT))
        # multi-LoRA adapter affinity (docs/multi-lora.md): same
        # flag-gated discipline — with it off, no index, no scorer, no
        # metric families, byte-identical scoring and exposition
        self.adapter_index = AdapterIndex() if adapter_affinity else None
        if adapter_affinity and not any(t == "adapter-affinity-scorer"
                                        for t, _ in self.plugins):
            self.plugins.append(("adapter-affinity-scorer",
                                 ADAPTER_WEIGHT))
        r = self.registry
        self.m_picks = Counter(
            "kaito:epp_picks_total",
            "Requests the picker routed, per chosen backend", r,
            labels=("backend",))
        self.m_affinity_hits = Counter(
            "kaito:epp_affinity_hits_total",
            "Requests landed on a backend already holding prefix blocks",
            r)
        self.m_affinity_misses = Counter(
            "kaito:epp_affinity_misses_total",
            "Requests with prefix signal but no (usable) block owner", r)
        self.m_pd_steered = Counter(
            "kaito:epp_pd_steered_total",
            "Decode requests steered to the staged-KV owner or its group",
            r)
        Gauge("kaito:epp_backend_saturated",
              "Hysteresis saturation per backend (1 = affinity excluded)",
              r, labels=("backend",),
              fn=lambda: {(b.url,): float(b.saturated)
                          for b in self.backends})
        Gauge("kaito:epp_affinity_index_size",
              "Distinct prefix block hashes currently indexed", r,
              fn=lambda: float(len(self.index)))
        Gauge("kaito:epp_affinity_index_evictions",
              "Prefix block hashes evicted from the LRU index", r,
              fn=lambda: float(self.index.evictions))
        if kv_pool:
            self.m_pool_route = Counter(
                "kaito:epp_kv_pool_holder_routed_total",
                "Requests routed to a replica already holding the "
                "matched pool prefix", r)
            self.m_pool_fetch = Counter(
                "kaito:epp_kv_pool_fetch_hints_total",
                "Requests sent to a non-holder with an X-Kaito-KV-Fetch "
                "hint (cross-replica prefix fetch)", r)
            Gauge("kaito:epp_kv_pool_index_size",
                  "Distinct (block_chars, block hash) rows in the "
                  "cluster prefix->holder index", r,
                  fn=lambda: float(len(self.pool_index)))
            # session affinity (docs/routing.md "Session affinity"):
            # conversation-keyed pin so turn N lands on the replica
            # whose host/SSD KV tiers hold turn N-1's pages — gated
            # with the pool (no pool, no tiered KV worth pinning to)
            self.m_session_pin_routed = Counter(
                "kaito:epp_session_pin_routed_total",
                "Requests routed to their conversation's pinned holder "
                "(X-Kaito-Session)", r)
            self.m_session_pin_misses = Counter(
                "kaito:epp_session_pin_misses_total",
                "Session-tagged requests whose pinned holder was gone "
                "or unusable (fell back to prefix scoring)", r)
            Gauge("kaito:epp_session_pins",
                  "Conversations currently pinned to a holder", r,
                  fn=lambda: float(self.index.session_count()))
        if adapter_affinity:
            self.m_adapter_hits = Counter(
                "kaito:epp_adapter_affinity_hits_total",
                "Adapter requests routed to a replica already holding "
                "the adapter (HBM slot or host tier)", r)
            self.m_adapter_misses = Counter(
                "kaito:epp_adapter_affinity_misses_total",
                "Adapter requests with no resident replica (target must "
                "hot-load before serving)", r)
            Gauge("kaito:epp_adapter_index_size",
                  "Distinct adapter names advertised by the fleet", r,
                  fn=lambda: float(len(self.adapter_index)))

    # -- affinity block size ----------------------------------------------
    @property
    def block_chars(self) -> int:
        """Char-block size for prefix hashing: explicit override, else
        the engine's scraped ``kaito:kv_page_size`` (tokens) times the
        chars-per-token estimate, else the engine-default fallback —
        keeping affinity blocks aligned with what the radix tree can
        actually reuse."""
        if self._block_chars > 0:
            return self._block_chars
        pages = [b.load.page_size for b in self.backends
                 if b.load.page_size > 0]
        if pages:
            return int(max(pages)) * CHARS_PER_TOKEN
        return DEFAULT_BLOCK_CHARS

    # -- scoring -----------------------------------------------------------
    def make_ctx(self, method: str, path: str,
                 body: Optional[bytes], headers=None) -> RequestCtx:
        ctx = RequestCtx()
        if headers is not None:
            # the picker runs in its own pod with only the wire to go
            # on: headers are the QoS intake (body fields as fallback,
            # matching the engine server's contract)
            ctx.tenant = (headers.get("X-Kaito-Tenant") or "").strip()
            ctx.priority = (headers.get("X-Kaito-Priority") or "").strip()
            ctx.session = (headers.get("X-Kaito-Session") or "").strip()
        if method != "POST":
            return ctx
        if path.startswith("/pd/prefill"):
            ctx.want_role = "prefill"
        kv_source = _extract_kv_source(body)
        if kv_source:
            ctx.kv_source = kv_source
            ctx.want_role = ctx.want_role or "decode"
        if headers is not None:
            ctx.adapter = (headers.get("X-Kaito-Adapter") or "").strip()
        if not ctx.tenant or not ctx.priority or (
                not ctx.adapter and self.adapter_index is not None):
            try:
                obj = json.loads(body) if body else {}
            except (ValueError, UnicodeDecodeError):
                obj = {}
            if isinstance(obj, dict):
                ctx.tenant = ctx.tenant or str(obj.get("tenant") or "")
                ctx.priority = ctx.priority or str(obj.get("priority") or "")
                # the picker only trusts a "model" field as an adapter
                # selector when a scraped advert has named it: a scrape
                # race degrades to unseeded blocks (no affinity, no
                # pool match) — never a wrong route or a poisoned seed
                if not ctx.adapter and self.adapter_index is not None:
                    model = str(obj.get("model") or "")
                    if model and self.adapter_index.known(model):
                        ctx.adapter = model
        if ctx.adapter and self.adapter_index is not None:
            ctx.adapter_residency = self.adapter_index.residency(ctx.adapter)
        prompt = _extract_prompt(body)
        if prompt:
            # the adapter name seeds the hash chain exactly like the
            # engine's pool/prefix publishing does, so adapter traffic
            # never affinity-matches (or pool-fetches) base KV
            ctx.blocks = prefix_blocks(prompt, self.block_chars,
                                       seed=adapter_seed(ctx.adapter))
            if ctx.blocks:
                ctx.matched = self.index.match(ctx.blocks)
                if self.pool_index is not None:
                    ctx.pool_match = self.pool_index.match(
                        ctx.blocks, self.block_chars)
        return ctx

    def _filter_role(self, ctx: RequestCtx,
                     pool: list[Backend]) -> list[Backend]:
        """pd-filter: keep replicas whose role can serve this request.
        Unlabelled ("") and "both" backends always qualify; when no
        backend matches (homogeneous pool), the filter is a no-op."""
        if not ctx.want_role:
            return pool
        kept = [b for b in pool
                if b.role in ("", "both", ctx.want_role)]
        return kept or pool

    def _score(self, b: Backend, ctx: RequestCtx) -> float:
        """Weighted plugin-chain sum; each scorer yields [0, 1]."""
        total = 0.0
        for ptype, weight in self.plugins:
            if ptype == "prefix-affinity-scorer":
                # a saturated or breaker-tripped backend never earns
                # affinity — steering onto it would trade a cache hit
                # for queueing (or a connect failure)
                if ctx.blocks and not b.saturated and b.state == "closed":
                    total += weight * (ctx.matched.get(b.url, 0)
                                       / len(ctx.blocks))
            elif ptype == "kv-locality-scorer":
                if ctx.kv_source:
                    if b.url == ctx.kv_source:
                        # colocated decode: device-to-device handoff
                        total += weight
                    elif b.group and b.group == self._source_group(ctx):
                        total += weight * 0.5
            elif ptype == "kv-pool-scorer":
                # cluster-pool locality: a replica holding the matched
                # published prefix earns score proportional to how much
                # of the prompt it covers.  Saturated or breaker-open
                # holders earn nothing — they'd be routed to only for
                # the KV, trading a transfer for queueing; the non-
                # holder pick then gets a fetch hint instead
                # (request_headers), which is the route-vs-fetch split.
                if ctx.pool_match and not b.saturated \
                        and b.state == "closed":
                    info = ctx.pool_match.get(b.url)
                    if info is not None and ctx.blocks:
                        total += weight * min(1.0,
                                              info[1] / len(ctx.blocks))
            elif ptype == "adapter-affinity-scorer":
                # LoRA residency locality: a replica with the adapter in
                # an HBM slot scores 1.0 (instant dispatch), host tier
                # 0.5 (one fault-in away), elsewhere 0 (full hot-load).
                # Saturated/tripped replicas earn nothing, mirroring the
                # other affinity scorers.
                if ctx.adapter and ctx.adapter_residency \
                        and not b.saturated and b.state == "closed":
                    total += weight * ctx.adapter_residency.get(b.url, 0.0)
            elif ptype == "queue-depth-scorer":
                total += weight / (1.0 + b.load.waiting)
            elif ptype == "kv-load-scorer":
                total += weight * (1.0 - min(1.0, max(
                    b.load.kv_usage, b.load.occupancy)))
            elif ptype == "tenant-stickiness-scorer":
                # rendezvous hash of (tenant, backend): a tenant's
                # traffic concentrates on one healthy replica so its
                # prefix cache stays warm there — without a shared
                # index, and stable as the pool changes.  Saturated
                # replicas earn nothing (stickiness must not pile onto
                # a full backend).
                if ctx.tenant and not b.saturated and b.state == "closed":
                    h = _fnv1a(f"{ctx.tenant}|{b.url}".encode(), 0)
                    total += weight * (h / float(_MASK64))
            elif ptype == "priority-scorer":
                # high-priority traffic avoids loaded backends harder:
                # the rank scales the headroom term, so best-effort
                # ("" / rank 0) is indifferent while guaranteed traffic
                # strongly prefers the emptiest replica
                rank = priority_rank(ctx.priority)
                if rank > 0:
                    total += weight * rank * (1.0 - min(1.0, max(
                        b.load.occupancy, b.load.kv_usage)))
            # pd-filter participates as a filter, not a scorer;
            # unknown plugin types are ignored (forward compat)
        return total

    def request_headers(self, ctx, backend: Backend) -> dict:
        """Per-candidate steering (docs/kv-pool.md): when the picked
        replica is NOT a holder of the matched pool prefix, name the
        best live holder in ``X-Kaito-KV-Fetch`` so the replica can
        pull the prefix over the chunked PD wire instead of
        recomputing it.  The engine applies the final measured
        transfer-vs-recompute veto; the EPP only nominates — so a
        fresh scale-out replica (no measured rates yet) trusts the
        hint, which is exactly the cold-boot case the pool serves.
        Resolved per failover attempt: if the holder itself ends up
        picked, no hint is sent."""
        if not isinstance(ctx, RequestCtx) or not ctx.pool_match:
            return {}
        if backend.url in ctx.pool_match:
            return {}                  # routed to a holder: no fetch
        best_url, best = "", None
        for b in self.backends:
            info = ctx.pool_match.get(b.url)
            if info is None or not b.alive or b.state != "closed":
                continue               # dead holder: advert is stale
            if best is None or info[1] > best[1]:
                best_url, best = b.url, info
        if best is None:
            return {}
        return {"X-Kaito-KV-Fetch": best_url,
                "X-Kaito-KV-Fetch-Key": best[0]}

    def _source_group(self, ctx: RequestCtx) -> str:
        for b in self.backends:
            if b.url == ctx.kv_source:
                return b.group
        return ""

    def candidates(self, method: str, path: str,
                   ctx) -> Iterable[Backend]:
        """Alive candidates in descending score order, then draining
        backends (healthy but leaving the pool — 503-free last resort),
        then cooling-down backends (same never-0-candidates guarantee
        as the round-robin front)."""
        if not isinstance(ctx, RequestCtx):
            ctx = RequestCtx()
        pool = self._filter_role(ctx, list(self.backends))
        alive = [b for b in pool if b.alive and not b.draining]
        draining = [b for b in pool if b.alive and b.draining]
        dead = [b for b in pool if not b.alive]
        # stable sort: score ties fall back to least-loaded-first order;
        # replicas inside a 429 Retry-After window sort after every
        # non-demoted peer regardless of score (healthy but shedding)
        alive.sort(key=lambda b: (b.demoted, -self._score(b, ctx),
                                  b.load.waiting))
        # session pin (docs/routing.md "Session affinity"): a
        # conversation's turn N goes to the replica that served turn
        # N-1 — its HBM radix tree / host store / SSD tier hold the
        # history — ahead of prefix scoring.  A gone, saturated,
        # breaker-open, or shedding holder forfeits the pin and the
        # scored order stands (the holder's tiers are useless if the
        # request would just queue behind them).
        if ctx.session and self.pool_index is not None and alive:
            pinned = self.index.session_holder(ctx.session)
            if pinned:
                for i, b in enumerate(alive):
                    if (b.url == pinned and b.state == "closed"
                            and not b.saturated and not b.demoted):
                        alive.insert(0, alive.pop(i))
                        break
        for b in alive + draining + dead:
            with self._lock:
                b.served += 1
            yield b

    def note_response(self, backend: Backend, ctx,
                      status: int) -> None:
        """A response head arrived: account the pick and feed the
        affinity index (the chosen replica now holds this prefix)."""
        self.m_picks.inc(backend=backend.url)
        if not isinstance(ctx, RequestCtx):
            return
        if ctx.kv_source and not ctx.steered and (
                backend.url == ctx.kv_source
                or (backend.group
                    and backend.group == self._source_group(ctx))):
            ctx.steered = True         # count once per request
            self.m_pd_steered.inc()
        if ctx.pool_match and self.pool_index is not None:
            if backend.url in ctx.pool_match:
                self.m_pool_route.inc()
            elif self.request_headers(ctx, backend):
                self.m_pool_fetch.inc()
        if ctx.session and self.pool_index is not None:
            holder = self.index.session_holder(ctx.session)
            if holder == backend.url:
                self.m_session_pin_routed.inc()
            elif holder is not None:
                self.m_session_pin_misses.inc()
            # re-pin to whoever actually served the turn (first turn
            # creates the pin; a failover moves it) — never onto a
            # draining replica whose tiers are about to vanish
            if status < 500 and not backend.draining:
                self.index.record_session(ctx.session, backend.url)
        if ctx.adapter and self.adapter_index is not None:
            if ctx.adapter_residency.get(backend.url, 0.0) > 0:
                self.m_adapter_hits.inc()
            else:
                self.m_adapter_misses.inc()
        if ctx.blocks:
            if ctx.matched.get(backend.url, 0) > 0:
                self.m_affinity_hits.inc()
            else:
                self.m_affinity_misses.inc()
            # a draining replica's KV is about to be torn down: never
            # record fresh affinity that would steer prompts at a
            # backend scheduled for deletion
            if status < 500 and not backend.draining:
                self.index.record(ctx.blocks, backend.url)


def _parse_backend_arg(spec: str) -> Backend:
    """``url[=role[/group]]`` — e.g. ``http://p0:5000=prefill/g0``."""
    url, _, rolegroup = spec.partition("=")
    role, _, group = rolegroup.partition("/")
    return Backend(url, role=role, group=group)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kaito-tpu-epp")
    ap.add_argument("--backend", action="append", default=[],
                    help="backend spec url[=role[/group]] (repeat per "
                         "replica); role in {prefill,decode,both}; zero "
                         "backends = scaled-to-zero front (503 + wake "
                         "signal)")
    ap.add_argument("--drain-backend", action="append", default=[],
                    help="backend url currently draining for scale-down "
                         "(kept serving in-flight work, never scored for "
                         "new picks)")
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--block-chars", type=int, default=0,
                    help="affinity block size in chars (0 = derive from "
                         "the scraped engine kv_page_size)")
    ap.add_argument("--index-capacity", type=int, default=65536,
                    help="max distinct prefix block hashes kept (LRU)")
    ap.add_argument("--plugins-config", default="",
                    help="plugin-chain JSON (inline, or @path to a file "
                         "— the InferencePool's eppPluginsConfig)")
    ap.add_argument("--health-probe-interval-s", type=float, default=2.0,
                    help="per-backend /health probe cadence (0 = off)")
    ap.add_argument("--scrape-interval-s", type=float, default=1.0,
                    help="per-backend /metrics load scrape cadence "
                         "(0 = off)")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM grace: max seconds to finish in-flight "
                         "requests before exit")
    ap.add_argument("--kv-pool", action="store_true",
                    help="enable the cluster KV-pool index: scrape "
                         "/debug/kv_pool adverts, score holders, emit "
                         "X-Kaito-KV-Fetch hints (docs/kv-pool.md)")
    ap.add_argument("--kv-pool-scrape-interval-s", type=float, default=2.0,
                    help="per-backend /debug/kv_pool advert scrape "
                         "cadence (0 = off)")
    ap.add_argument("--adapter-affinity", action="store_true",
                    help="enable the multi-LoRA adapter-affinity index: "
                         "scrape /v1/adapters adverts, seed prefix "
                         "hashes per adapter, score resident replicas "
                         "(docs/multi-lora.md)")
    ap.add_argument("--adapter-scrape-interval-s", type=float, default=2.0,
                    help="per-backend /v1/adapters advert scrape "
                         "cadence (0 = off)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    plugins_config = None
    if args.plugins_config:
        raw = args.plugins_config
        if raw.startswith("@"):
            with open(raw[1:], encoding="utf-8") as f:
                raw = f.read()
        plugins_config = json.loads(raw)

    picker = EndpointPicker(
        [_parse_backend_arg(s) for s in args.backend],
        block_chars=args.block_chars,
        index_capacity=args.index_capacity,
        plugins_config=plugins_config,
        draining=args.drain_backend,
        kv_pool=args.kv_pool,
        adapter_affinity=args.adapter_affinity)
    srv = make_routing_server(picker, args.host, args.port,
                              probe_interval_s=args.health_probe_interval_s,
                              scrape_interval_s=args.scrape_interval_s)
    if args.kv_pool and args.kv_pool_scrape_interval_s > 0:
        pool_scraper = KVPoolScraper(picker, args.kv_pool_scrape_interval_s)
        pool_scraper.start()
        srv.pool_scraper = pool_scraper      # type: ignore[attr-defined]
    if args.adapter_affinity and args.adapter_scrape_interval_s > 0:
        a_scraper = AdapterScraper(picker, args.adapter_scrape_interval_s)
        a_scraper.start()
        srv.adapter_scraper = a_scraper      # type: ignore[attr-defined]

    def _term(signum, frame):
        logger.info("SIGTERM: draining %d in-flight request(s)",
                    picker.inflight)
        threading.Thread(target=lambda: (picker.drain(args.drain_timeout_s),
                                         srv.shutdown()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    logger.info("epp on :%d -> %s", srv.server_address[1],
                [b.url for b in picker.backends])
    srv.serve_forever()
    logger.info("epp exited cleanly")


if __name__ == "__main__":
    main()
