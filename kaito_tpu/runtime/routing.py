"""Shared routing data path: transport + endpoint scoring.

ONE relay implementation serves both fronts of the replica tier
(docs/routing.md):

- ``kaito_tpu.runtime.dp_router`` — the round-robin compatibility
  front (single-node DP deployments, tests, dryruns);
- ``kaito_tpu.runtime.epp`` — the first-party endpoint picker the
  InferencePool's ``extensionRef`` resolves to, scoring replicas by
  prefix-hash affinity, live load, and the PD plugin chain.

The transport guts here are what used to live inside dp_router: the
per-backend circuit breaker (open/half-open/closed with exponential
cooldown), the ``/health`` prober, jittered idempotent retry across
replicas and cycles, byte-for-byte SSE relay, chunked-body handling,
SIGTERM drain, and X-Request-Id propagation.  A front chooses ONLY the
candidate order (``RoutingCore.candidates``); everything about how a
request reaches a replica is shared.

Scoring building blocks (used by the EPP, unit-testable alone):

- ``prefix_blocks``       — chained FNV-1a hashes over fixed-size
  prompt blocks, the wire-level analogue of the engine's radix-tree
  page hashing (``native/src/prefix_cache.cc``); the block size is
  aligned to the engine's KV page size so an affinity hit lands where
  cached KV actually lives.
- ``PrefixAffinityIndex`` — bounded LRU of recent block hashes per
  backend (the hash ring the picker consults).
- ``BackendLoad`` scraping — ``kaito:batch_occupancy``, queue depth
  and KV utilization from each replica's ``/metrics``.
- ``update_saturation``   — hysteresis: a replica enters saturation at
  the high watermarks and only leaves below the low ones, so affinity
  never flaps onto a barely-recovered backend.
"""

from __future__ import annotations

import http.client
import json
import logging
import random
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Optional

from kaito_tpu.engine.metrics import Counter, Gauge, Histogram, Registry
from kaito_tpu.utils.failpoints import FAILPOINTS, FailpointError
from kaito_tpu.utils.tracing import (make_request_id, parse_traceparent,
                                     sanitize_request_id)

logger = logging.getLogger(__name__)

DOWN_COOLDOWN_S = 5.0
DOWN_COOLDOWN_MAX_S = 60.0
BREAKER_THRESHOLD = 3          # consecutive failures that OPEN the breaker
RETRY_CYCLES = 2               # full passes over the backend list
RETRY_BACKOFF_S = 0.1          # jittered sleep between cycles
HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding",
               "te", "trailer", "upgrade", "proxy-authorization"}
# POST routes that are safe to replay against another replica before any
# response byte: stateless inference (any replica computes the same
# answer).  PD side-channel routes mutate per-replica staging state and
# must NOT fail over blindly.
IDEMPOTENT_POST_PREFIXES = ("/v1/completions", "/v1/chat/completions",
                            "/v1/embeddings", "/score", "/tokenize",
                            "/detokenize")

_BREAKER_STATES = {"closed": 0, "half-open": 1, "open": 2}

# hysteresis watermarks: enter saturation at *_HI, leave below *_LO
SAT_OCCUPANCY_HI = 0.90
SAT_OCCUPANCY_LO = 0.70
SAT_KV_HI = 0.90
SAT_KV_LO = 0.75
SAT_QUEUE_HI = 8
SAT_QUEUE_LO = 2


class BackendLoad:
    """Last-scraped load snapshot for one replica (all floats so a
    missing series degrades to 0 rather than None-poisoning scores)."""

    __slots__ = ("occupancy", "waiting", "kv_usage", "page_size", "ts")

    def __init__(self):
        self.occupancy = 0.0       # kaito:batch_occupancy
        self.waiting = 0.0         # kaito:num_requests_waiting
        self.kv_usage = 0.0        # kaito:kv_cache_usage_perc
        self.page_size = 0.0       # kaito:kv_page_size (tokens)
        self.ts = 0.0              # monotonic scrape time (0 = never)


class Backend:
    """One replica plus its circuit-breaker state.

    ``down_until`` stays THE open-until timestamp (tests poke it to
    heal a backend); ``failures`` counts CONSECUTIVE connect failures.
    State is derived, never stored:

    - ``open``      — cooling down (``down_until`` in the future)
    - ``half-open`` — cooldown lapsed but the breaker tripped and no
      success has closed it yet (the next request is the probe)
    - ``closed``    — healthy
    """

    def __init__(self, url: str, role: str = "", group: str = ""):
        url = url.rstrip("/")
        assert url.startswith("http://"), f"http backends only: {url}"
        self.url = url
        hostport = url[len("http://"):]
        self.host, _, port = hostport.partition(":")
        self.port = int(port or 80)
        self.role = role           # "" | "prefill" | "decode" | "both"
        self.group = group         # replica group for PD KV locality
        self.down_until = 0.0
        # 429 advisory window (Retry-After): the replica is healthy but
        # FULL — no breaker trip, just deprioritized for new picks
        self.avoid_until = 0.0
        self.served = 0
        self.failures = 0
        self.load = BackendLoad()
        self.saturated = False     # hysteresis state (update_saturation)
        # scale-down drain (autoscaler): a draining backend keeps
        # serving its in-flight work but stops attracting new picks —
        # fronts order it after every healthy peer, never 503 it
        self.draining = False

    @property
    def alive(self) -> bool:
        return time.monotonic() >= self.down_until

    @property
    def demoted(self) -> bool:
        """Inside a 429 Retry-After advisory window: last-resort only."""
        return time.monotonic() < self.avoid_until

    def demote(self, seconds: float) -> None:
        """A 429 with Retry-After: honor the advisory window without
        touching the breaker (the replica is alive, just shedding)."""
        self.avoid_until = max(self.avoid_until,
                               time.monotonic() + max(0.0, seconds))

    @property
    def state(self) -> str:
        if not self.alive:
            return "open"
        if self.failures >= BREAKER_THRESHOLD:
            return "half-open"
        return "closed"

    def mark_down(self) -> None:
        """One more consecutive failure: cool down with exponential
        backoff (capped) so a dead replica is probed ever less often
        while it stays dead."""
        self.failures += 1
        backoff = min(DOWN_COOLDOWN_S * (2 ** max(0, self.failures
                                                  - BREAKER_THRESHOLD)),
                      DOWN_COOLDOWN_MAX_S)
        self.down_until = time.monotonic() + backoff

    def mark_up(self) -> None:
        """A success (request or health probe) closes the breaker."""
        self.failures = 0
        self.down_until = 0.0


def update_saturation(b: Backend,
                      occ_hi: float = SAT_OCCUPANCY_HI,
                      occ_lo: float = SAT_OCCUPANCY_LO,
                      kv_hi: float = SAT_KV_HI,
                      kv_lo: float = SAT_KV_LO,
                      q_hi: float = SAT_QUEUE_HI,
                      q_lo: float = SAT_QUEUE_LO) -> bool:
    """Hysteresis band around the saturation decision: a backend that
    crossed a high watermark keeps rejecting affinity steering until it
    falls below EVERY low watermark — without the band, a replica
    hovering at the threshold would flap in and out of eligibility on
    every scrape."""
    ld = b.load
    if b.saturated:
        if (ld.occupancy <= occ_lo and ld.kv_usage <= kv_lo
                and ld.waiting <= q_lo):
            b.saturated = False
    else:
        if (ld.occupancy >= occ_hi or ld.kv_usage >= kv_hi
                or ld.waiting >= q_hi):
            b.saturated = True
    return b.saturated


# ---------------------------------------------------------------------------
# prefix-hash affinity
# ---------------------------------------------------------------------------

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def _fnv1a(data: bytes, seed: int) -> int:
    h = (_FNV_OFFSET ^ seed) & _MASK64
    for byte in data:
        h = ((h ^ byte) * _FNV_PRIME) & _MASK64
    return h


def adapter_seed(adapter: str) -> int:
    """Chain seed folding a LoRA adapter name into the block hashes
    (docs/multi-lora.md): KV computed under adapter deltas must never
    hash-match base KV (or another adapter's) for the same text, so
    both hashing sides — the engine's pool publisher and the EPP —
    seed the chain with the adapter identity.  "" (base) keeps seed 0:
    every pre-adapter chain is byte-identical."""
    return _fnv1a(adapter.encode("utf-8", "replace"), 0) if adapter else 0


def prefix_blocks(text: str, block_chars: int, seed: int = 0) -> list[int]:
    """Chained block hashes of a prompt prefix: block i's hash folds in
    block i-1's, exactly the chaining the engine's radix tree uses for
    token pages (equal blocks at different depths hash differently).
    Trailing partial blocks are dropped — the engine can only reuse
    whole KV pages, so a partial block can never be a cache hit.
    ``seed`` (default 0 = unchanged chains) namespaces the whole chain,
    e.g. per LoRA adapter via ``adapter_seed``."""
    if block_chars <= 0:
        return []
    data = text.encode("utf-8", "replace")
    out: list[int] = []
    parent = seed & _MASK64
    for i in range(len(data) // block_chars):
        parent = _fnv1a(data[i * block_chars:(i + 1) * block_chars], parent)
        out.append(parent)
    return out


def extract_prompt_text(obj) -> str:
    """The prompt string the routing layer hashes, from a PARSED
    request body.  Shared by the EPP and the engine's KV-pool
    publisher: both sides must hash the SAME bytes or the cluster
    prefix index silently never matches (tests/test_kv_pool.py)."""
    if not isinstance(obj, dict):
        return ""
    prompt = obj.get("prompt")
    if isinstance(prompt, str):
        return prompt
    msgs = obj.get("messages")
    if isinstance(msgs, list):
        # role markers included so "same content, different role" maps
        # to different blocks (mirrors the chat-template expansion)
        parts = []
        for m in msgs:
            if isinstance(m, dict):
                parts.append(f"<{m.get('role', '')}>"
                             f"{m.get('content', '')}")
        return "".join(parts)
    return ""


class PrefixAffinityIndex:
    """Bounded LRU of recent prompt-prefix block hashes per backend.

    ``record`` notes that a backend just served (and therefore now
    holds KV for) a chain of blocks; ``match`` returns, per backend,
    how many LEADING blocks of a new prompt that backend has seen.
    Capacity bounds total distinct block hashes; eviction is LRU so a
    hot shared prefix never ages out while it keeps hitting."""

    def __init__(self, capacity: int = 65536,
                 session_capacity: int = 16384):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self.session_capacity = session_capacity
        self.evictions = 0
        self._lock = threading.Lock()
        # block hash -> {backend_url: last_touch} (insertion order = LRU)
        self._map: OrderedDict[int, dict[str, float]] = OrderedDict()
        # session id -> backend url (insertion order = LRU): the
        # conversation-keyed pin (docs/routing.md "Session affinity").
        # Turn N of a conversation routes to the replica that served
        # turn N-1 — whose host/SSD KV tiers hold the history — before
        # prefix scoring gets a say; a dead/removed holder falls back
        # to normal scoring via drop_backend.
        self._sessions: OrderedDict[str, str] = OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def record(self, blocks: Iterable[int], backend_url: str) -> None:
        now = time.monotonic()
        with self._lock:
            for h in blocks:
                owners = self._map.get(h)
                if owners is None:
                    owners = self._map[h] = {}
                else:
                    self._map.move_to_end(h)
                owners[backend_url] = now
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
                self.evictions += 1

    def match(self, blocks: list[int]) -> dict[str, int]:
        """backend url -> number of consecutive leading blocks it
        holds.  Only unbroken runs count: a backend missing block k
        cannot serve block k+1 from cache (the engine's radix tree
        stops at the first divergence)."""
        out: dict[str, int] = {}
        alive: Optional[set] = None
        with self._lock:
            for h in blocks:
                owners = self._map.get(h)
                if not owners:
                    break
                self._map.move_to_end(h)
                here = set(owners)
                alive = here if alive is None else (alive & here)
                if not alive:
                    break
                for url in alive:
                    out[url] = out.get(url, 0) + 1
        return out

    def record_session(self, session: str, backend_url: str) -> None:
        """Pin a conversation to the replica that just served it."""
        if not session:
            return
        with self._lock:
            self._sessions[session] = backend_url
            self._sessions.move_to_end(session)
            while len(self._sessions) > self.session_capacity:
                self._sessions.popitem(last=False)

    def session_holder(self, session: str) -> Optional[str]:
        """The pinned holder url for a conversation, or None."""
        if not session:
            return None
        with self._lock:
            url = self._sessions.get(session)
            if url is not None:
                self._sessions.move_to_end(session)
            return url

    def session_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def drop_backend(self, backend_url: str) -> None:
        """Forget a replica (removed from the pool / restarted — its
        KV cache is gone, affinity to it is stale)."""
        with self._lock:
            empty = []
            for h, owners in self._map.items():
                owners.pop(backend_url, None)
                if not owners:
                    empty.append(h)
            for h in empty:
                del self._map[h]
            stale = [s for s, url in self._sessions.items()
                     if url == backend_url]
            for s in stale:
                del self._sessions[s]


# ---------------------------------------------------------------------------
# /metrics scraping
# ---------------------------------------------------------------------------

_LOAD_SERIES = {
    "kaito:batch_occupancy": "occupancy",
    "kaito:num_requests_waiting": "waiting",
    "kaito:kv_cache_usage_perc": "kv_usage",
    "kaito:kv_page_size": "page_size",
}


def parse_load_metrics(text: str) -> dict[str, float]:
    """Pull the routing-relevant gauges out of an exposition payload.
    Labelled series of the same family (DP groups) are summed for
    counters-like values and averaged for the utilization gauges —
    close enough for scoring, and robust to either shape."""
    sums: dict[str, list[float]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        key = _LOAD_SERIES.get(name)
        if key is None:
            continue
        try:
            value = float(line.rsplit(" ", 1)[1])
        except (ValueError, IndexError):
            continue
        sums.setdefault(key, []).append(value)
    out: dict[str, float] = {}
    for key, vals in sums.items():
        if key == "waiting":
            out[key] = sum(vals)
        else:
            out[key] = sum(vals) / len(vals)
    return out


# fields the load parser may populate; anything else the parser ever
# returns is dropped instead of setattr-poked into the snapshot
_LOAD_FIELDS = frozenset(s for s in BackendLoad.__slots__ if s != "ts")
# a scrape missing any of these is PARTIAL (truncated payload, wrong
# process behind the port): keep the old snapshot and its stale ts
_LOAD_REQUIRED = ("occupancy", "waiting", "kv_usage")


def scrape_backend_load(b: Backend, timeout: float = 5.0) -> bool:
    """GET one replica's /metrics and fold the load gauges into
    ``b.load`` + its hysteresis state.  Returns False (and leaves the
    old snapshot in place, stale ts included) when the replica is
    unreachable or the payload is missing the core load series.

    The new snapshot is built aside and swapped in whole, so a
    concurrent scorer never reads a half-updated mix of old and new
    gauges stamped with a fresh ``ts``."""
    try:
        conn = http.client.HTTPConnection(b.host, b.port, timeout=timeout)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            if resp.status != 200:
                return False
            vals = parse_load_metrics(resp.read().decode("utf-8", "replace"))
        finally:
            conn.close()
    except (ConnectionError, OSError):
        return False
    if any(k not in vals for k in _LOAD_REQUIRED):
        return False
    fresh = BackendLoad()
    fresh.page_size = b.load.page_size      # optional series: carry over
    for key, v in vals.items():
        if key in _LOAD_FIELDS:
            setattr(fresh, key, v)
    fresh.ts = time.monotonic()
    b.load = fresh
    update_saturation(b)
    return True


class _BackendPoller(threading.Thread):
    """Shared loop shape for the background scraper/prober: the first
    pass runs IMMEDIATELY (not after the first interval sleep), every
    pass polls the backends CONCURRENTLY, and a per-backend in-flight
    guard skips a backend whose previous poll has not returned yet — so
    one hung-but-alive replica degrades only its own freshness, never
    the cadence of the others (the old serial loop let a single 5 s
    timeout starve every backend behind it)."""

    def __init__(self, name: str, interval_s: float):
        super().__init__(daemon=True, name=name)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._inflight: set[int] = set()
        self._guard = threading.Lock()

    def stop(self) -> None:
        self._stop.set()

    def targets(self) -> Iterable[Backend]:
        raise NotImplementedError

    def poll_one(self, b: Backend) -> None:
        raise NotImplementedError

    def poll_pass(self) -> None:
        for b in self.targets():
            with self._guard:
                if id(b) in self._inflight:
                    continue            # previous poll still hanging
                self._inflight.add(id(b))
            threading.Thread(target=self._poll_guarded, args=(b,),
                             daemon=True, name=f"{self.name}-worker").start()

    def _poll_guarded(self, b: Backend) -> None:
        try:
            self.poll_one(b)
        except Exception:
            logger.debug("%s: poll of %s failed", self.name, b.url,
                         exc_info=True)
        finally:
            with self._guard:
                self._inflight.discard(id(b))

    def run(self) -> None:
        self.poll_pass()                # first pass now, not at t+interval
        while not self._stop.wait(self.interval_s):
            self.poll_pass()


class MetricsScraper(_BackendPoller):
    """Background load scraper: keeps every backend's ``load`` snapshot
    fresh so scoring never blocks a request on a network round trip."""

    def __init__(self, core: "RoutingCore", interval_s: float = 1.0,
                 timeout_s: float = 2.0):
        super().__init__("routing-metrics-scraper", interval_s)
        self.core = core
        self.timeout_s = timeout_s

    def targets(self) -> Iterable[Backend]:
        return [b for b in self.core.backends if b.alive]

    def poll_one(self, b: Backend) -> None:
        scrape_backend_load(b, timeout=self.timeout_s)


class HealthProber(_BackendPoller):
    """Background ``/health`` probe per backend: closes breakers as
    replicas recover, opens them when a live-looking backend refuses
    the probe — without spending client requests on discovery."""

    def __init__(self, router: "RoutingCore", interval_s: float = 2.0,
                 timeout_s: float = 5.0):
        super().__init__("dp-health-prober", interval_s)
        self.router = router
        self.timeout_s = timeout_s

    def targets(self) -> Iterable[Backend]:
        return list(self.router.backends)

    def poll_one(self, b: Backend) -> None:
        try:
            conn = http.client.HTTPConnection(b.host, b.port,
                                              timeout=self.timeout_s)
            try:
                conn.request("GET", "/health")
                ok = conn.getresponse().status == 200
            finally:
                conn.close()
        except (ConnectionError, OSError):
            ok = False
        if ok:
            if b.failures:
                logger.info("health probe: %s recovered", b.url)
            b.mark_up()
        elif b.alive:
            b.mark_down()


def _retryable(method: str, path: str) -> bool:
    """May this request be replayed against another replica (before any
    response byte)?  GET/DELETE always; POST only on the stateless
    inference routes."""
    if method in ("GET", "DELETE", "HEAD"):
        return True
    if method == "POST":
        return any(path.startswith(p) for p in IDEMPOTENT_POST_PREFIXES)
    return False


# ---------------------------------------------------------------------------
# routing core: backends + breaker + drain + transport metrics
# ---------------------------------------------------------------------------

class RoutingCore:
    """Everything a routing front shares: the backend list, breaker
    bookkeeping, drain state, and the relay-tier metric families.
    Fronts override ``candidates`` (the ordering policy) and optionally
    ``make_ctx`` / ``note_response`` / ``handle_local``."""

    def __init__(self, backends: list, registry: Optional[Registry] = None,
                 allow_empty: bool = False):
        if not backends and not allow_empty:
            raise ValueError("router needs at least one backend")
        self.backends = [b if isinstance(b, Backend) else Backend(b)
                         for b in backends]
        self._rr = 0
        self._lock = threading.Lock()
        self.draining = False
        self._inflight = 0
        # the relay tier's OWN /metrics (docs/observability.md): the
        # engine replicas each expose theirs; these cover the transport
        r = registry if registry is not None else Registry()
        self.registry = r
        self.m_received = Counter(
            "kaito:router_requests_received_total",
            "Relayable requests accepted by this front (scale-to-zero "
            "wake signal: arrivals exist even with zero backends)", r)
        self.m_forwarded = Counter(
            "kaito:router_requests_forwarded_total",
            "Requests relayed to a backend (response head received)",
            r, labels=("backend",))
        self.m_retries = Counter(
            "kaito:router_retries_total",
            "Relay attempts beyond each request's first", r,
            labels=("backend",))
        self.m_failures = Counter(
            "kaito:router_backend_failures_total",
            "Connect/forward failures that skipped a backend", r,
            labels=("backend",))
        self.m_rate_limited = Counter(
            "kaito:router_backend_rate_limited_total",
            "429 responses that demoted a backend for its Retry-After "
            "window (request failed over, breaker untouched)", r,
            labels=("backend",))
        self.upstream_latency = Histogram(
            "kaito:router_upstream_latency_seconds",
            "Forward-to-response-head latency per backend", r,
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
            labels=("backend",))
        # breaker state is time-derived (down_until vs now), so the
        # family is computed at scrape time via the labelled-fn Gauge
        Gauge("kaito:router_backend_breaker_state",
              "Circuit breaker per backend (0=closed, 1=half-open, 2=open)",
              r, labels=("backend",),
              fn=lambda: {(b.url,): _BREAKER_STATES[b.state]
                          for b in self.backends})
        Gauge("kaito:router_backend_draining",
              "Scale-down drain state per backend (1 = not scored)",
              r, labels=("backend",),
              fn=lambda: {(b.url,): float(b.draining)
                          for b in self.backends})

    # -- selection policy --------------------------------------------------
    def next_backend(self) -> Optional[Backend]:
        """Next live non-draining non-demoted backend (round robin);
        replicas inside a 429 Retry-After window come next (they are
        healthy, just shedding), draining backends are last-resort only
        (they still serve correctly — better that than a 503 — but new
        work prefers survivors), and if every backend is cooling down,
        the next one regardless (better a refused retry than a
        guaranteed 503 when all marks are stale)."""
        with self._lock:
            n = len(self.backends)
            if n == 0:
                return None
            for offset in range(n):
                b = self.backends[(self._rr + offset) % n]
                if b.alive and not b.draining and not b.demoted:
                    self._rr = (self._rr + offset + 1) % n
                    b.served += 1
                    return b
            for offset in range(n):
                b = self.backends[(self._rr + offset) % n]
                if b.alive and not b.draining:
                    self._rr = (self._rr + offset + 1) % n
                    b.served += 1
                    return b
            for offset in range(n):
                b = self.backends[(self._rr + offset) % n]
                if b.alive:
                    self._rr = (self._rr + offset + 1) % n
                    b.served += 1
                    return b
            b = self.backends[self._rr % n]
            self._rr = (self._rr + 1) % n
            b.served += 1
            return b

    def set_draining(self, url: str, draining: bool = True) -> bool:
        """Flip one backend's drain state (autoscaler scale-down:
        mark, let in-flight finish, then remove).  Returns False when
        no backend matches the url."""
        url = url.rstrip("/")
        found = False
        for b in self.backends:
            if b.url == url:
                b.draining = draining
                found = True
        return found

    def make_ctx(self, method: str, path: str,
                 body: Optional[bytes], headers=None):
        """Parse whatever the front's scoring needs out of the request
        (``headers`` carries the QoS tenant/priority intake).  The base
        (round-robin) front needs nothing."""
        return None

    def request_headers(self, ctx, backend: "Backend") -> dict:
        """Extra headers to inject into the forwarded request, resolved
        per CANDIDATE backend (the EPP's KV-pool front steers a picked
        replica to fetch a prefix from its holder via
        ``X-Kaito-KV-Fetch``).  The base front injects nothing."""
        return {}

    def candidates(self, method: str, path: str, ctx) -> Iterable[Backend]:
        """One preference-ordered pass over the replicas for one retry
        cycle.  The default is the classic round robin."""
        for _ in range(len(self.backends)):
            b = self.next_backend()
            if b is not None:
                yield b

    def note_response(self, backend: Backend, ctx, status: int) -> None:
        """A response head arrived from ``backend`` (any status)."""

    def handle_local(self, path: str, method: str = "GET"):
        """Locally-answered routes (never forwarded).  Returns
        ``(status, content_type, body_bytes)`` or None to relay."""
        if path == "/router/stats":
            body = json.dumps(self.stats()).encode()
            return 200, "application/json", body
        if path == "/metrics" and method == "GET":
            # the front's OWN series, never forwarded: per-backend
            # forwards/retries/failures, breaker state, latency
            return (200, "text/plain; version=0.0.4",
                    self.registry.expose().encode())
        return None

    def stats(self) -> dict:
        with self._lock:
            return {b.url: {"served": b.served, "alive": b.alive,
                            "state": b.state, "failures": b.failures,
                            "draining": b.draining}
                    for b in self.backends}

    # -- drain bookkeeping -------------------------------------------------
    def begin_request(self) -> bool:
        """Admission gate: False while draining (caller answers 503)."""
        with self._lock:
            if self.draining:
                return False
            self._inflight += 1
            return True

    def end_request(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop accepting, wait for in-flight relays to finish.  Returns
        True when the router went quiet inside the timeout."""
        with self._lock:
            self.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.inflight == 0:
                return True
            time.sleep(0.05)
        return self.inflight == 0


# ---------------------------------------------------------------------------
# the relay server (shared verbatim by every front)
# ---------------------------------------------------------------------------

def make_routing_server(core: RoutingCore, host: str = "0.0.0.0",
                        port: int = 0, probe_interval_s: float = 0.0,
                        scrape_interval_s: float = 0.0
                        ) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send_json(self, code: int, obj: dict,
                       headers: Optional[dict] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            rid = getattr(self, "_rid", None)
            if rid:
                self.send_header("X-Request-Id", rid)
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)

        def _read_request_body(self) -> Optional[bytes]:
            """Read the client body whichever way it was framed.  A
            ``Transfer-Encoding: chunked`` body is DE-CHUNKED here and
            forwarded with Content-Length (http.client sets it), so a
            chunked client upload is no longer silently dropped."""
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                chunks = []
                while True:
                    size_line = self.rfile.readline(65536).strip()
                    size = int(size_line.split(b";")[0] or b"0", 16)
                    if size == 0:
                        # consume trailers until the blank line
                        while self.rfile.readline(65536).strip():
                            pass
                        break
                    chunks.append(self.rfile.read(size))
                    self.rfile.read(2)          # CRLF after each chunk
                return b"".join(chunks)
            length = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(length) if length else None

        def _relay(self, method: str):
            # end-to-end tracing: accept the caller's X-Request-Id (or
            # a W3C traceparent), mint one otherwise, and forward it so
            # router + engine logs/spans correlate on one id.
            self._rid = (sanitize_request_id(self.headers.get("X-Request-Id"))
                         or parse_traceparent(self.headers.get("traceparent"))
                         or make_request_id())
            local = core.handle_local(self.path, method)
            if local is not None:
                status, ctype, body = local
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if not core.begin_request():
                self._send_json(503, {"error": "router draining"},
                                headers={"Retry-After": 1})
                return
            core.m_received.inc()
            try:
                self._relay_inner(method)
            finally:
                core.end_request()

        def _relay_inner(self, method: str):
            try:
                body = self._read_request_body()
            except (ValueError, ConnectionError, OSError):
                self._send_json(400, {"error": "malformed request body"})
                return
            # failover is only safe BEFORE the first response byte: a
            # backend that dies mid-stream cannot be retried without
            # corrupting the client's half-written reply (and without
            # re-running the inference) — abort the connection instead.
            # Retryable requests get RETRY_CYCLES full passes over the
            # candidate order with a jittered backoff between passes;
            # one-shot (non-idempotent) requests get a single pass.
            ctx = core.make_ctx(method, self.path, body,
                                headers=self.headers)
            retryable = _retryable(method, self.path)
            cycles = RETRY_CYCLES if retryable else 1
            last_status: Optional[int] = None
            attempts = 0
            for cycle in range(cycles):
                if cycle:
                    time.sleep(RETRY_BACKOFF_S * (1 + random.random()))
                remaining = len(core.backends)
                for b in core.candidates(method, self.path, ctx):
                    remaining -= 1
                    attempts += 1
                    if attempts > 1:
                        core.m_retries.inc(backend=b.url)
                    t_fwd = time.monotonic()
                    try:
                        resp, conn = self._connect(b, method, body, ctx)
                    except (ConnectionError, OSError, FailpointError) as e:
                        logger.warning("backend %s unreachable (%s); "
                                       "skipping", b.url, e)
                        core.m_failures.inc(backend=b.url)
                        b.mark_down()
                        continue
                    core.upstream_latency.observe(
                        time.monotonic() - t_fwd, backend=b.url)
                    if retryable and resp.status in (502, 503) \
                            and (cycle + 1 < cycles or remaining > 0):
                        # the replica answered but cannot serve (loading
                        # stub, drain, overload): try elsewhere.  The
                        # breaker does NOT trip — the process is alive.
                        last_status = resp.status
                        conn.close()
                        continue
                    if retryable and resp.status == 429 \
                            and (cycle + 1 < cycles or remaining > 0):
                        # shedding replica: honor its Retry-After as a
                        # demotion window (healthy-but-full, no breaker
                        # trip) and fail over to the next candidate NOW
                        # — a shed request should move, not die
                        try:
                            ra = min(60.0, max(
                                1.0, float(resp.getheader("Retry-After")
                                           or 1)))
                        except (TypeError, ValueError):
                            ra = 1.0
                        b.demote(ra)
                        core.m_rate_limited.inc(backend=b.url)
                        last_status = resp.status
                        conn.close()
                        continue
                    b.mark_up()
                    core.m_forwarded.inc(backend=b.url)
                    core.note_response(b, ctx, resp.status)
                    self._stream_response(b, method, resp, conn)
                    return
            self._send_json(503 if last_status is None else last_status,
                            {"error": "no live backend"},
                            headers={"Retry-After": 1})

        def _connect(self, b: Backend, method: str,
                     body: Optional[bytes], ctx=None):
            """Send the request and read the response HEAD; raises are
            retryable (nothing has reached the client yet)."""
            FAILPOINTS.fire("router.forward", backend=b.url)
            conn = http.client.HTTPConnection(b.host, b.port, timeout=600)
            headers = {k: v for k, v in self.headers.items()
                       if k.lower() not in HOP_HEADERS
                       and k.lower() not in ("content-length",
                                             "x-request-id")}
            headers["X-Request-Id"] = self._rid
            # per-candidate steering headers from the front (e.g. the
            # EPP's KV-pool fetch hint) — resolved HERE because the
            # chosen backend differs per failover attempt
            headers.update(core.request_headers(ctx, b) or {})
            conn.request(method, self.path, body=body, headers=headers)
            return conn.getresponse(), conn

        def _stream_response(self, b: Backend, method: str, resp,
                             conn) -> None:
            """Relay an already-open backend response.  A BACKEND read
            failure marks it down and aborts the client connection (no
            retry — bytes are already out); a CLIENT write failure just
            ends the relay (the backend is healthy)."""
            try:
                self.send_response(resp.status)
                for k, v in resp.getheaders():
                    if k.lower() not in HOP_HEADERS:
                        self.send_header(k, v)
                # 1xx/204/304 (and HEAD replies) carry NO body by spec:
                # chunked framing (or a terminator) after their headers
                # would corrupt the connection for the next request
                bodyless = (resp.status < 200 or resp.status in (204, 304)
                            or method == "HEAD")
                has_len = resp.getheader("Content-Length") is not None
                if not has_len and not bodyless:
                    # stream of unknown length (SSE): relay chunked
                    self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                if bodyless:
                    return
                # relay bytes AS THEY ARRIVE so SSE tokens stream through
                while True:
                    try:
                        chunk = resp.read1(65536) if hasattr(resp, "read1") \
                            else resp.read(65536)
                    except (ConnectionError, OSError) as e:
                        logger.warning("backend %s died mid-stream (%s); "
                                       "aborting relay", b.url, e)
                        b.mark_down()
                        self.close_connection = True
                        return
                    if not chunk:
                        break
                    try:
                        if has_len:
                            self.wfile.write(chunk)
                        else:
                            self.wfile.write(
                                b"%x\r\n%s\r\n" % (len(chunk), chunk))
                        self.wfile.flush()
                    except (ConnectionError, OSError):
                        # client went away: backend stays healthy
                        self.close_connection = True
                        return
                if not has_len:
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                    except (ConnectionError, OSError):
                        self.close_connection = True
            finally:
                conn.close()

        def do_GET(self):
            self._relay("GET")

        def do_POST(self):
            self._relay("POST")

        def do_DELETE(self):
            self._relay("DELETE")

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.router = core                        # type: ignore[attr-defined]
    if probe_interval_s > 0:
        prober = HealthProber(core, probe_interval_s)
        prober.start()
        srv.prober = prober                  # type: ignore[attr-defined]
    if scrape_interval_s > 0:
        scraper = MetricsScraper(core, scrape_interval_s)
        scraper.start()
        srv.scraper = scraper                # type: ignore[attr-defined]
    return srv
