"""SLO watchdog: continuous north-star burn-rate tracking.

Turns the raw serving histograms into an operator-consumable health
signal: rolling multi-window SLIs (TTFT p50/p99, generated tokens per
second per chip, availability) evaluated against configurable targets
defaulting to the BASELINE north star (>= 2000 tok/s/chip, p50 TTFT
< 200 ms), with Google-SRE-style multi-window burn-rate alerting —
state per SLI is ``ok`` (budget intact), ``warn`` (the fast 5m window
is burning), or ``page`` (both the 5m and 1h windows are burning, so
the breach is sustained, not a blip).

Exported three ways:

- ``kaito:slo_*`` gauges on the engine's ``/metrics`` registry,
- a ``/debug/slo`` JSON endpoint on the engine server,
- the benchmark probe folds the verdict into ``KAITO_BENCHMARK_RESULT``
  so the workspace controller can set the ``SLOHealthy`` condition.

Burn-rate math: each SLI is a good/total ratio with an error budget
``1 - target_fraction``; burn = bad_fraction / budget.  Burn > 1 means
the budget is being spent faster than allowed.  The p50 TTFT target is
expressed as "50% of requests must see first token within the target",
so burn_rate > 1 is exactly "the observed p50 exceeds the target".

Everything takes an injectable clock so the unit tier can step time
deterministically.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

# multi-window pair (seconds): the fast window detects, the slow
# window confirms (classic 5m/1h page rule)
WINDOW_FAST_S = 300.0
WINDOW_SLOW_S = 3600.0

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_PAGE = "page"
_STATE_CODE = {STATE_OK: 0, STATE_WARN: 1, STATE_PAGE: 2}

_MAX_SAMPLES = 65536


@dataclass
class SLOTargets:
    """North-star defaults (BASELINE.json); every field has an env
    override so a deployment can tune without a code change."""

    ttft_p50_s: float = 0.200            # p50 TTFT < 200 ms
    ttft_p99_s: float = 1.0              # tail TTFT
    itl_p99_s: float = 0.250             # tail inter-token gap (per token)
    tokens_per_sec_per_chip: float = 2000.0
    availability: float = 0.999          # success / (success+fail+shed)
    # fraction of requests/tokens that must meet each latency bound
    ttft_p50_fraction: float = 0.50
    ttft_p99_fraction: float = 0.99
    itl_p99_fraction: float = 0.99

    @classmethod
    def from_env(cls, base: "Optional[SLOTargets]" = None) -> "SLOTargets":
        t = base or cls()

        def f(env: str, cur: float, scale: float = 1.0) -> float:
            raw = os.environ.get(env, "")
            try:
                return float(raw) * scale if raw else cur
            except ValueError:
                return cur

        return cls(
            ttft_p50_s=f("KAITO_SLO_TTFT_P50_MS", t.ttft_p50_s, 1e-3),
            ttft_p99_s=f("KAITO_SLO_TTFT_P99_MS", t.ttft_p99_s, 1e-3),
            itl_p99_s=f("KAITO_SLO_ITL_P99_MS", t.itl_p99_s, 1e-3),
            tokens_per_sec_per_chip=f("KAITO_SLO_TOKENS_PER_SEC_PER_CHIP",
                                      t.tokens_per_sec_per_chip),
            availability=f("KAITO_SLO_AVAILABILITY", t.availability),
            ttft_p50_fraction=t.ttft_p50_fraction,
            ttft_p99_fraction=t.ttft_p99_fraction,
            itl_p99_fraction=t.itl_p99_fraction,
        )

    def to_dict(self) -> dict:
        return {
            "ttft_p50_ms": round(self.ttft_p50_s * 1000, 3),
            "ttft_p99_ms": round(self.ttft_p99_s * 1000, 3),
            "itl_p99_ms": round(self.itl_p99_s * 1000, 3),
            "tokens_per_sec_per_chip": self.tokens_per_sec_per_chip,
            "availability": self.availability,
        }


class WindowSeries:
    """Timestamped samples pruned to the longest window (bounded).

    Shared with the fleet telemetry plane
    (``kaito_tpu/runtime/fleet.py``), which keeps one of these per
    InferenceSet per signal — the same multi-window rolling design,
    lifted from one process to the fleet."""

    def __init__(self, max_window_s: float, time_fn: Callable[[], float]):
        self.max_window_s = max_window_s
        self.time_fn = time_fn
        self._samples: "collections.deque[tuple[float, float]]" = \
            collections.deque(maxlen=_MAX_SAMPLES)
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        now = self.time_fn()
        with self._lock:
            self._samples.append((now, float(value)))
            self._prune(now)

    def _prune(self, now: float) -> None:
        cutoff = now - self.max_window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def values(self, window_s: float) -> list[float]:
        now = self.time_fn()
        with self._lock:
            self._prune(now)
            cutoff = now - window_s
            return [v for t, v in self._samples if t >= cutoff]

    def total(self, window_s: float) -> float:
        return sum(self.values(window_s))


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(q * len(xs))))
    return xs[idx]


def _ratio_burn(bad: float, total: float, budget: float) -> float:
    """bad_fraction / error_budget; 0 when there is no traffic."""
    if total <= 0:
        return 0.0
    return (bad / total) / max(budget, 1e-9)


def _alert_state(burn_fast: float, burn_slow: float) -> str:
    if burn_fast > 1.0 and burn_slow > 1.0:
        return STATE_PAGE
    if burn_fast > 1.0:
        return STATE_WARN
    return STATE_OK


class SLOWatchdog:
    """Feed it per-request observations; read back burn-rate states.

    All feed methods are cheap (deque append under a lock) and safe
    from handler threads.  ``chips`` is the serving slice's chip count
    so tok/s normalizes to the per-chip north star.
    """

    def __init__(self, targets: Optional[SLOTargets] = None, chips: int = 1,
                 windows: tuple[float, float] = (WINDOW_FAST_S,
                                                WINDOW_SLOW_S),
                 time_fn: Callable[[], float] = time.monotonic,
                 per_tenant: bool = False, itl_enabled: bool = False,
                 role: str = ""):
        self.targets = targets or SLOTargets()
        self.chips = max(1, int(chips))
        self.window_fast_s, self.window_slow_s = windows
        self.time_fn = time_fn
        self._t0 = time_fn()
        slow = self.window_slow_s
        self.ttft = WindowSeries(slow, time_fn)
        self.tokens = WindowSeries(slow, time_fn)     # per-request counts
        self.success = WindowSeries(slow, time_fn)
        self.failure = WindowSeries(slow, time_fn)
        self.shed = WindowSeries(slow, time_fn)
        # per-token inter-token gaps (--itl): the itl_p99 SLI and its
        # gauges only exist when the engine-side stamping is on, so the
        # ITL-off exposition stays byte-identical
        self.itl_enabled = bool(itl_enabled)
        self.itl = WindowSeries(slow, time_fn)
        # P/D role attribution (ROADMAP item 1): "prefill" / "decode" /
        # "unified"; a non-empty role adds the kaito:slo_role info gauge
        self._role_set = bool(role)
        self.role = role or "unified"
        # per-tenant QoS slices (docs/qos.md): only with a QoS config —
        # the gauges they feed must not exist in the QoS-off exposition
        self.per_tenant = per_tenant
        self._tenant_ttft: dict[str, WindowSeries] = {}
        self._tenant_shed: dict[str, WindowSeries] = {}
        self._tenant_itl: dict[str, WindowSeries] = {}

    # -- feeds ---------------------------------------------------------

    def _tenant_series(self, store: dict, tenant: str) -> WindowSeries:
        s = store.get(tenant)
        if s is None:
            s = store[tenant] = WindowSeries(self.window_slow_s,
                                             self.time_fn)
        return s

    def observe_ttft(self, seconds: float, tenant: str = "") -> None:
        self.ttft.add(seconds)
        if self.per_tenant and tenant:
            self._tenant_series(self._tenant_ttft, tenant).add(seconds)

    def observe_itl(self, seconds: float, tenant: str = "") -> None:
        """One inter-token gap (the engine's retire-path stamp)."""
        self.itl.add(seconds)
        if self.per_tenant and tenant:
            self._tenant_series(self._tenant_itl, tenant).add(seconds)

    def note_tokens(self, n: int) -> None:
        if n > 0:
            self.tokens.add(n)

    def note_shed(self, n: int = 1, tenant: str = "") -> None:
        self.shed.add(n)
        if self.per_tenant and tenant:
            self._tenant_series(self._tenant_shed, tenant).add(n)

    def observe_request(self, req) -> None:
        """Feed one finished engine Request (the server calls this next
        to EngineMetrics.observe_request)."""
        if getattr(req, "first_token_time", None):
            self.observe_ttft(req.first_token_time - req.submit_time,
                              tenant=getattr(req, "tenant", ""))
        self.note_tokens(len(getattr(req, "output_tokens", ()) or ()))
        if getattr(req, "finish_time", None) or \
                getattr(req, "finish_reason", None):
            ok = getattr(req, "finish_reason", None) not in \
                ("error", "deadline")
            (self.success if ok else self.failure).add(1)

    # -- per-tenant view (docs/qos.md) ---------------------------------

    def tenant_snapshot(self) -> dict:
        """Fast-window TTFT p50 and shed count per tenant — the
        degradation ladder's observable: a guaranteed tenant's p50
        holds while best-effort sheds climb."""
        out: dict = {}
        for t in sorted(set(self._tenant_ttft) | set(self._tenant_shed)
                        | set(self._tenant_itl)):
            ttfts = (self._tenant_ttft[t].values(self.window_fast_s)
                     if t in self._tenant_ttft else [])
            shed = (self._tenant_shed[t].total(self.window_fast_s)
                    if t in self._tenant_shed else 0.0)
            out[t] = {"ttft_p50_s": round(_percentile(ttfts, 0.50), 6),
                      "ttft_samples": len(ttfts),
                      "shed": int(shed)}
            if self.itl_enabled:
                itls = (self._tenant_itl[t].values(self.window_fast_s)
                        if t in self._tenant_itl else [])
                out[t]["itl_p99_s"] = round(_percentile(itls, 0.99), 6)
                out[t]["itl_samples"] = len(itls)
        return out

    # -- evaluation ----------------------------------------------------

    def _window_elapsed(self, window_s: float) -> float:
        """Effective rate denominator: a process younger than the
        window must not dilute tok/s by time it never served."""
        return max(1e-6, min(window_s, self.time_fn() - self._t0))

    def _eval_window(self, window_s: float) -> dict:
        t = self.targets
        ttfts = self.ttft.values(window_s)
        n = len(ttfts)
        bad_p50 = sum(1 for v in ttfts if v > t.ttft_p50_s)
        bad_p99 = sum(1 for v in ttfts if v > t.ttft_p99_s)
        ok = self.success.total(window_s)
        fail = self.failure.total(window_s)
        shed = self.shed.total(window_s)
        total = ok + fail + shed
        toks = self.tokens.total(window_s)
        tok_s_chip = toks / self._window_elapsed(window_s) / self.chips
        out = {
            "ttft_p50_s": round(_percentile(ttfts, 0.50), 6),
            "ttft_p99_s": round(_percentile(ttfts, 0.99), 6),
            "ttft_samples": n,
            "availability": round(ok / total, 6) if total else 1.0,
            "requests": int(total),
            "tokens_per_sec_per_chip": round(tok_s_chip, 3),
            "burn": {
                "ttft_p50": _ratio_burn(bad_p50, n, 1 - t.ttft_p50_fraction),
                "ttft_p99": _ratio_burn(bad_p99, n, 1 - t.ttft_p99_fraction),
                "availability": _ratio_burn(fail + shed, total,
                                            1 - t.availability),
            },
            # throughput is a floor, not a ratio SLI: burning means
            # serving below target while traffic exists
            "throughput_burning": bool(
                toks > 0 and tok_s_chip < t.tokens_per_sec_per_chip),
        }
        if self.itl_enabled:
            itls = self.itl.values(window_s)
            bad_itl = sum(1 for v in itls if v > t.itl_p99_s)
            out["itl_p50_s"] = round(_percentile(itls, 0.50), 6)
            out["itl_p99_s"] = round(_percentile(itls, 0.99), 6)
            out["itl_samples"] = len(itls)
            out["burn"]["itl_p99"] = _ratio_burn(
                bad_itl, len(itls), 1 - t.itl_p99_fraction)
        return out

    def snapshot(self) -> dict:
        """The ``/debug/slo`` payload (and the probe's verdict)."""
        fast = self._eval_window(self.window_fast_s)
        slow = self._eval_window(self.window_slow_s)
        slis = ("ttft_p50", "ttft_p99", "availability") + \
            (("itl_p99",) if self.itl_enabled else ())
        burn_rates = {
            sli: {"fast": round(fast["burn"][sli], 4),
                  "slow": round(slow["burn"][sli], 4)}
            for sli in slis
        }
        alerts = {
            sli: _alert_state(b["fast"], b["slow"])
            for sli, b in burn_rates.items()
        }
        alerts["throughput"] = _alert_state(
            1.5 if fast["throughput_burning"] else 0.0,
            1.5 if slow["throughput_burning"] else 0.0)
        # single worst fast-window burn across every SLI (throughput
        # folded in as its synthetic 1.5/0.0): the fleet telemetry
        # plane scrapes exactly this one field per replica instead of
        # walking the nested burn_rates dict (docs/observability.md)
        burn_max = max([b["fast"] for b in burn_rates.values()]
                       + [1.5 if fast["throughput_burning"] else 0.0])
        fast.pop("burn"), slow.pop("burn")
        fast.pop("throughput_burning"), slow.pop("throughput_burning")
        out = {
            "burn_max": round(burn_max, 4),
            "role": self.role,
            "targets": self.targets.to_dict(),
            "windows": {"fast_s": self.window_fast_s,
                        "slow_s": self.window_slow_s},
            "chips": self.chips,
            "sli": {"fast": fast, "slow": slow},
            "burn_rates": burn_rates,
            "alerts": alerts,
            "healthy": all(a != STATE_PAGE for a in alerts.values()),
        }
        if self.per_tenant:
            out["tenants"] = self.tenant_snapshot()
        return out

    # -- exposition ----------------------------------------------------

    def register_metrics(self, registry) -> None:
        """Attach the ``kaito:slo_*`` families to a metrics Registry.
        Everything is computed at scrape time from the windows, so the
        labelled-``fn`` Gauge form fits exactly."""
        from kaito_tpu.engine.metrics import Gauge

        def _burns() -> dict:
            snap = self.snapshot()
            out = {}
            for sli, b in snap["burn_rates"].items():
                out[(sli, "5m")] = b["fast"]
                out[(sli, "1h")] = b["slow"]
            return out

        def _states() -> dict:
            snap = self.snapshot()
            return {(sli,): _STATE_CODE[state]
                    for sli, state in snap["alerts"].items()}

        Gauge("kaito:slo_burn_rate",
              "Error-budget burn rate per SLI and window (>1 = burning)",
              registry, labels=("sli", "window"), fn=_burns)
        Gauge("kaito:slo_alert_state",
              "Burn-rate alert state per SLI (0=ok, 1=warn, 2=page)",
              registry, labels=("sli",), fn=_states)
        Gauge("kaito:slo_ttft_p50_seconds",
              "Rolling fast-window TTFT p50", registry,
              fn=lambda: self._eval_window(self.window_fast_s)["ttft_p50_s"])
        Gauge("kaito:slo_tokens_per_sec_per_chip",
              "Rolling fast-window generated tokens/s/chip", registry,
              fn=lambda: self._eval_window(
                  self.window_fast_s)["tokens_per_sec_per_chip"])
        Gauge("kaito:slo_availability",
              "Rolling fast-window availability", registry,
              fn=lambda: self._eval_window(self.window_fast_s)["availability"])
        Gauge("kaito:slo_healthy",
              "1 while no SLI is in the page state", registry,
              fn=lambda: 1.0 if self.snapshot()["healthy"] else 0.0)
        if self.itl_enabled:
            # ITL-only families — the itl_p99 entry in burn_rates /
            # alerts above is likewise gated, so the ITL-off exposition
            # stays byte-identical
            Gauge("kaito:slo_itl_p50_seconds",
                  "Rolling fast-window inter-token latency p50", registry,
                  fn=lambda: self._eval_window(
                      self.window_fast_s)["itl_p50_s"])
            Gauge("kaito:slo_itl_p99_seconds",
                  "Rolling fast-window inter-token latency p99", registry,
                  fn=lambda: self._eval_window(
                      self.window_fast_s)["itl_p99_s"])
        if self._role_set:
            Gauge("kaito:slo_role",
                  "Info gauge: the serving role this replica's SLO burn "
                  "attributes to", registry, labels=("role",),
                  fn=lambda: {(self.role,): 1.0})
        if self.per_tenant:
            # QoS-only families — registering them unconditionally
            # would add HELP/TYPE lines to the QoS-off exposition
            def _tenant_ttfts() -> dict:
                return {(t,): s["ttft_p50_s"]
                        for t, s in self.tenant_snapshot().items()}

            def _tenant_sheds() -> dict:
                return {(t,): float(s["shed"])
                        for t, s in self.tenant_snapshot().items()}

            Gauge("kaito:slo_tenant_ttft_p50_seconds",
                  "Rolling fast-window TTFT p50 per tenant", registry,
                  labels=("tenant",), fn=_tenant_ttfts)
            Gauge("kaito:slo_tenant_shed",
                  "Fast-window requests shed per tenant", registry,
                  labels=("tenant",), fn=_tenant_sheds)
            if self.itl_enabled:
                def _tenant_itls() -> dict:
                    return {(t,): s.get("itl_p99_s", 0.0)
                            for t, s in self.tenant_snapshot().items()}

                Gauge("kaito:slo_tenant_itl_p99_seconds",
                      "Rolling fast-window inter-token latency p99 per "
                      "tenant", registry, labels=("tenant",),
                      fn=_tenant_itls)


def condition_from_verdict(verdict: dict) -> tuple[str, str, str]:
    """Fold a ``/debug/slo`` snapshot (or the subset the probe ships)
    into (status, reason, message) for the Workspace ``SLOHealthy``
    condition."""
    alerts = verdict.get("alerts") or {}
    burning = sorted(sli for sli, st in alerts.items() if st != STATE_OK)
    healthy = bool(verdict.get("healthy", True)) and not burning
    if healthy:
        return "True", "SLOMet", "north-star SLOs met"
    paging = sorted(sli for sli, st in alerts.items() if st == STATE_PAGE)
    reason = "SLOBurnRate" if paging else "SLOWarning"
    return ("False" if paging else "True", reason,
            "burning error budget: " + ", ".join(burning))


def engine_chip_count(engine) -> int:
    """Chips behind a server: sum mesh device counts across DP groups
    (a mesh-less engine — CPU dev loop — counts as one chip)."""
    total = 0
    for e in getattr(engine, "engines", None) or [engine]:
        mesh = getattr(e, "mesh", None)
        try:
            total += int(mesh.devices.size) if mesh is not None else 1
        except Exception:
            total += 1
    return max(1, total)
