"""HF transformers fallback runtime for long-tail architectures.

The counterpart of the reference's text-generation runtime
(``presets/workspace/inference/text-generation/inference_api.py``): the
first-party JAX engine covers the catalog's model families; anything
else (an architecture the engine has no layer implementation for)
serves through HuggingFace ``transformers`` on torch behind the SAME
OpenAI surface, so every model the reference can serve has a serving
path here too.  The workload generator selects this runtime from the
preset's ``runtime: transformers`` (``models/autogen`` flips it for
unsupported architectures).

Deliberately small: stdlib HTTP, greedy/temperature sampling loop,
local-files-only model loading (zero-egress parity), byte-level
tokenizer fallback when the checkpoint ships no tokenizer.
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)


class FallbackState:
    def __init__(self, model_path: str, max_model_len: int = 2048,
                 served_name: str = ""):
        import os

        import torch
        from transformers import AutoModelForCausalLM, AutoTokenizer

        self.torch = torch
        t0 = time.monotonic()
        # local first (ModelMirror PVC / pre-warmed cache); when absent
        # and egress is allowed, download like the reference's
        # text-generation runtime does at startup (KAITO_OFFLINE=1
        # forces the zero-egress behavior)
        offline = os.environ.get("KAITO_OFFLINE", "") == "1"
        try:
            self.model = AutoModelForCausalLM.from_pretrained(
                model_path, local_files_only=True, dtype=torch.float32)
        except OSError:
            if offline:
                raise
            logger.info("no local copy of %s; downloading", model_path)
            self.model = AutoModelForCausalLM.from_pretrained(
                model_path, dtype=torch.float32)
        self.model.eval()
        try:
            self.tokenizer = AutoTokenizer.from_pretrained(
                model_path, local_files_only=True)
        except Exception:
            from kaito_tpu.engine.tokenizer import ByteTokenizer

            logger.warning("no tokenizer files at %s; byte-level fallback",
                           model_path)
            self.tokenizer = ByteTokenizer()
        self.max_model_len = max_model_len
        self.served_name = served_name or model_path.rstrip("/").rsplit(
            "/", 1)[-1]
        self.lock = threading.Lock()   # one generation at a time (CPU)
        # counters get hit from concurrent handler threads outside the
        # generation lock; they need their own
        self.counters_lock = threading.Lock()
        self.counters = {"requests_total": 0, "generation_tokens_total": 0}
        logger.info("fallback runtime ready in %.1fs (%s)",
                    time.monotonic() - t0, self.served_name)

    def stream_tokens(self, token_ids: list[int], max_tokens: int,
                      temperature: float, seed: int = 0,
                      ignore_eos: bool = False):
        """Yield generated token ids one at a time; the generator's
        ``finish`` attribute-carrier is returned via StopIteration
        value ("stop" on EOS, "length" on cutoff).  The EOS token
        itself is never emitted (OpenAI semantics)."""
        torch = self.torch
        eos = getattr(self.tokenizer, "eos_token_id", None)
        gen = torch.Generator().manual_seed(seed or 0)
        ids = torch.tensor([token_ids], dtype=torch.long)
        finish = "length"
        try:
            past = None
            cur = ids
            for _ in range(max_tokens):
                # lock per STEP, never across a yield: the consumer does
                # network I/O between tokens, and a stalled SSE client
                # must not stall every other request
                with self.lock, torch.no_grad():
                    res = self.model(cur, past_key_values=past,
                                     use_cache=True)
                past = res.past_key_values
                logits = res.logits[0, -1]
                if temperature and temperature > 0.0:
                    probs = torch.softmax(logits / temperature, dim=-1)
                    nxt = int(torch.multinomial(probs, 1, generator=gen))
                else:
                    nxt = int(torch.argmax(logits))
                if eos is not None and nxt == eos and not ignore_eos:
                    finish = "stop"
                    break
                with self.counters_lock:
                    self.counters["generation_tokens_total"] += 1
                yield nxt
                cur = torch.tensor([[nxt]], dtype=torch.long)
        finally:
            # counted even when the consumer disconnects mid-stream
            with self.counters_lock:
                self.counters["requests_total"] += 1
        return finish

    def generate(self, token_ids: list[int], max_tokens: int,
                 temperature: float, seed: int = 0,
                 ignore_eos: bool = False) -> tuple[list[int], str]:
        """Collect stream_tokens: (tokens, finish_reason)."""
        out: list[int] = []
        it = self.stream_tokens(token_ids, max_tokens, temperature,
                                seed=seed, ignore_eos=ignore_eos)
        while True:
            try:
                out.append(next(it))
            except StopIteration as s:
                return out, s.value or "length"


def make_fallback_server(state: FallbackState, host: str = "0.0.0.0",
                         port: int = 5000) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, code: int, body: dict):
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/health":
                self._json(200, {"status": "ok",
                                 "runtime": "transformers-fallback"})
            elif self.path == "/v1/models":
                self._json(200, {"object": "list", "data": [
                    {"id": state.served_name, "object": "model",
                     "owned_by": "kaito-tpu-fallback"}]})
            elif self.path == "/metrics":
                with state.counters_lock:
                    snapshot = dict(state.counters)
                lines = [f"kaito:{k} {v}" for k, v in snapshot.items()]
                data = ("\n".join(lines) + "\n").encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(n) or b"{}")
            except json.JSONDecodeError:
                return self._json(400, {"error": "invalid JSON"})
            chat = self.path == "/v1/chat/completions"
            if self.path not in ("/v1/completions", "/v1/chat/completions"):
                return self._json(404, {"error": f"no route {self.path}"})
            if chat:
                messages = body.get("messages") or []
                apply = getattr(state.tokenizer, "apply_chat_template", None)
                try:
                    prompt = apply(messages, tokenize=False,
                                   add_generation_prompt=True)
                except Exception:
                    prompt = "".join(
                        f"<|{m.get('role', 'user')}|>\n"
                        f"{m.get('content', '')}\n" for m in messages
                    ) + "<|assistant|>\n"
            else:
                prompt = body.get("prompt", "")
                if isinstance(prompt, list):
                    prompt = prompt[0] if prompt else ""
            toks = state.tokenizer.encode(prompt)
            max_tokens = int(body.get("max_tokens", 16))
            if len(toks) + max_tokens > state.max_model_len:
                return self._json(400, {"error": {
                    "message": f"prompt+max_tokens exceeds "
                               f"{state.max_model_len}",
                    "type": "invalid_request_error"}})
            if body.get("stream"):
                return self._stream(chat, toks, max_tokens, body)
            out, finish = state.generate(
                toks, max_tokens, float(body.get("temperature", 1.0)),
                seed=int(body.get("seed", 0) or 0),
                ignore_eos=bool(body.get("ignore_eos", False)))
            text = state.tokenizer.decode(out)
            rid = f"cmpl-{uuid.uuid4().hex[:20]}"
            usage = {"prompt_tokens": len(toks),
                     "completion_tokens": len(out),
                     "total_tokens": len(toks) + len(out)}
            if chat:
                self._json(200, {
                    "id": rid, "object": "chat.completion",
                    "model": state.served_name,
                    "choices": [{"index": 0, "finish_reason": finish,
                                 "message": {"role": "assistant",
                                             "content": text}}],
                    "usage": usage})
            else:
                self._json(200, {
                    "id": rid, "object": "text_completion",
                    "model": state.served_name,
                    "choices": [{"index": 0, "text": text,
                                 "finish_reason": finish}],
                    "usage": usage})

        def _stream(self, chat: bool, toks: list[int], max_tokens: int,
                    body: dict):
            """SSE streaming (OpenAI chunk shape), one token per event."""
            rid = f"cmpl-{uuid.uuid4().hex[:20]}"
            obj = "chat.completion.chunk" if chat else "text_completion"
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()

            def emit(payload: dict):
                self.wfile.write(b"data: " + json.dumps(payload).encode()
                                 + b"\n\n")
                self.wfile.flush()

            it = state.stream_tokens(
                toks, max_tokens, float(body.get("temperature", 1.0)),
                seed=int(body.get("seed", 0) or 0),
                ignore_eos=bool(body.get("ignore_eos", False)))
            finish = "length"
            out_toks: list[int] = []
            prev_text = ""
            try:
                if chat:
                    # OpenAI chat streams open with the role delta
                    emit({"id": rid, "object": obj,
                          "model": state.served_name,
                          "choices": [{"index": 0, "finish_reason": None,
                                       "delta": {"role": "assistant"}}]})
                while True:
                    try:
                        tok = next(it)
                    except StopIteration as s:
                        finish = s.value or "length"
                        break
                    out_toks.append(tok)
                    # incremental full-sequence decode: per-id decode
                    # garbles multi-byte codepoints / SentencePiece
                    # space markers (see engine token_surface_forms)
                    text = state.tokenizer.decode(out_toks)
                    piece, prev_text = text[len(prev_text):], text
                    if chat:
                        choice = {"index": 0, "finish_reason": None,
                                  "delta": {"content": piece}}
                    else:
                        choice = {"index": 0, "finish_reason": None,
                                  "text": piece}
                    emit({"id": rid, "object": obj,
                          "model": state.served_name, "choices": [choice]})
                final = {"index": 0, "finish_reason": finish}
                if chat:
                    final["delta"] = {}
                else:
                    final["text"] = ""
                emit({"id": rid, "object": obj, "model": state.served_name,
                      "choices": [final]})
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass     # client went away mid-stream
            finally:
                it.close()

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kaito-tpu-hf-fallback")
    ap.add_argument("--model", required=True,
                    help="local checkpoint dir or cached HF id")
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--max-model-len", type=int, default=2048)
    ap.add_argument("--served-model-name", default="")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    state = FallbackState(args.model, max_model_len=args.max_model_len,
                          served_name=args.served_model_name)
    srv = make_fallback_server(state, host=args.host, port=args.port)
    logger.info("fallback runtime serving %s on %s:%d", state.served_name,
                args.host, args.port)
    srv.serve_forever()


if __name__ == "__main__":
    main()
