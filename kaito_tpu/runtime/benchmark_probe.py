"""Self-benchmark startup probe.

Parity with the reference's in-cluster benchmark
(``presets/workspace/inference/vllm/benchmark_entrypoint.py``): runs as
the leader pod's startup probe, waits for /health, derives a safe
concurrency from the engine's KV-capacity gauges, drives a fixed load
(60 s, 2048-token prompts / 256-token outputs), snapshots the token
counters, and emits ``KAITO_BENCHMARK_CONFIG`` / ``KAITO_BENCHMARK_RESULT``
JSON lines (through /proc/1/fd/1 in-pod so the controller can tail
them), exiting 0/1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

BENCHMARK_DURATION_S = 60
BENCHMARK_INPUT_LEN = 2048
BENCHMARK_OUTPUT_LEN = 256


def _emit(tag: str, payload: dict, sink: str) -> None:
    line = f"{tag}{json.dumps(payload)}\n"
    try:
        with open(sink, "w") as f:
            f.write(line)
    except OSError:
        sys.stdout.write(line)
        sys.stdout.flush()


def _get(url: str, timeout: float = 10.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _metric(metrics_text: str, name: str) -> float:
    """Sum every series of a family: a labelled family (e.g. a counter
    split by reason, or a DP facade exporting per-group series) exposes
    several lines, and reading only the first one under-counts."""
    total = 0.0
    seen = False
    for line in metrics_text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            try:
                total += float(line.rsplit(" ", 1)[1])
                seen = True
            except ValueError:
                pass
    return total if seen else 0.0


def wait_healthy(base: str, deadline_s: float) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        try:
            if json.loads(_get(base + "/health"))["status"] == "ok":
                return True
        except Exception:
            pass
        time.sleep(5)
    return False


def derive_concurrency(base: str, input_len: int, output_len: int) -> int:
    """Concurrency from live KV capacity (the reference reads vLLM's
    cache-config gauges; we read kaito:kv_pages_total and the
    kaito:kv_page_size gauge the engine exports)."""
    m = _get(base + "/metrics")
    pages = _metric(m, "kaito:kv_pages_total")
    page_size = _metric(m, "kaito:kv_page_size") or 64
    capacity_tokens = pages * page_size
    per_seq = input_len + output_len
    return max(1, min(int(capacity_tokens // max(per_seq, 1)) or 1, 64))


def run_benchmark(base: str, *, duration_s: float = BENCHMARK_DURATION_S,
                  input_len: int = BENCHMARK_INPUT_LEN,
                  output_len: int = BENCHMARK_OUTPUT_LEN,
                  concurrency: int = 0, sink: str = "/proc/1/fd/1") -> dict:
    if concurrency <= 0:
        concurrency = derive_concurrency(base, input_len, output_len)
    cfg = {"engine": "kaito-tpu", "engine_version": "0.1.0",
           "input_len": input_len, "output_len": output_len,
           "duration_s": duration_s, "max_concurrency": concurrency}
    _emit("KAITO_BENCHMARK_CONFIG", cfg, sink)

    before = _get(base + "/metrics")
    gen0 = _metric(before, "kaito:generation_tokens_total")
    prompt_text = "benchmark " * max(input_len // 10, 1)

    stop = time.monotonic() + duration_s
    ttfts: list[float] = []     # time to FIRST streamed chunk, per request
    errors = [0]
    lock = threading.Lock()

    def worker():
        while time.monotonic() < stop:
            t0 = time.monotonic()
            body = json.dumps({
                "prompt": prompt_text, "max_tokens": output_len,
                "temperature": 1.0, "stream": True}).encode()
            try:
                req = urllib.request.Request(
                    base + "/v1/completions", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req,
                                            timeout=duration_s + 120) as r:
                    first = None
                    for line in r:
                        if first is None and line.startswith(b"data:"):
                            first = time.monotonic() - t0
                    if first is not None:
                        with lock:
                            ttfts.append(first)
            except Exception:
                errors[0] += 1

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 180)
    elapsed = time.monotonic() - t_start

    after = _get(base + "/metrics")
    gen1 = _metric(after, "kaito:generation_tokens_total")
    total_tokens = gen1 - gen0
    tpm = total_tokens / max(elapsed, 1e-6) * 60.0
    # client-observed TTFT from the streamed first chunk (not whole-
    # request latency); avg from the engine histogram for comparison
    ttfts.sort()
    ttft_p50 = ttfts[len(ttfts) // 2] if ttfts else 0.0
    ttft_avg = _metric(after, "kaito:time_to_first_token_seconds_sum") / \
        max(_metric(after, "kaito:time_to_first_token_seconds_count"), 1)
    result = {
        "vllm_total_tpm": round(tpm, 1),          # key kept for dashboard parity
        "total_tpm": round(tpm, 1),
        "generation_tokens": int(total_tokens),
        "ttft_p50_ms": round(ttft_p50 * 1000, 1),
        "ttft_avg_ms": round(ttft_avg * 1000, 1),
        "ttft_samples": len(ttfts),
        "elapsed_s": round(elapsed, 1),
        "errors": errors[0],
        "max_concurrency": concurrency,
    }
    try:
        health = json.loads(_get(base + "/health"))
        if isinstance(health, dict) and health.get("hbm_sizing"):
            # engine's self-measured HBM sizing + estimator drift rides
            # into status.performance alongside the throughput numbers
            result["hbm_sizing"] = health["hbm_sizing"]
    except Exception:
        pass
    try:
        slo = json.loads(_get(base + "/debug/slo"))
        if isinstance(slo, dict) and "alerts" in slo:
            # SLO verdict rides along so the workspace controller can
            # fold it into the SLOHealthy condition (runtime/slo.py)
            result["slo"] = {k: slo.get(k) for k in
                             ("healthy", "alerts", "burn_rates", "targets")}
            result["slo"]["sli"] = (slo.get("sli") or {}).get("fast")
    except Exception:
        pass
    _emit("KAITO_BENCHMARK_RESULT", result, sink)
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-url", default="http://127.0.0.1:5000")
    ap.add_argument("--duration", type=float, default=BENCHMARK_DURATION_S)
    ap.add_argument("--input-len", type=int, default=BENCHMARK_INPUT_LEN)
    ap.add_argument("--output-len", type=int, default=BENCHMARK_OUTPUT_LEN)
    ap.add_argument("--concurrency", type=int, default=0)
    ap.add_argument("--sink", default="/proc/1/fd/1")
    ap.add_argument("--health-deadline", type=float, default=1800)
    args = ap.parse_args(argv)
    if not wait_healthy(args.base_url, args.health_deadline):
        print("engine never became healthy", file=sys.stderr)
        return 1
    result = run_benchmark(
        args.base_url, duration_s=args.duration, input_len=args.input_len,
        output_len=args.output_len, concurrency=args.concurrency,
        sink=args.sink)
    return 0 if result["generation_tokens"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
