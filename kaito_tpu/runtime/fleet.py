"""Fleet telemetry plane: cross-replica aggregation + scaling signals.

The control-plane half of the observability story (docs/observability
.md "Fleet"): every engine replica already exposes ``/metrics`` and
``/debug/slo``, every routing front exposes ``kaito:router_*`` /
``kaito:epp_*`` — but each of those is a per-process, point-in-time
view.  ``FleetTelemetry`` lifts them to per-CR rolling signals:

1. **Discovery** — scrape targets come from the KubeStore: each
   InferenceSet's child Workspaces (one replica Service each) plus the
   set's EPP Service, and standalone Workspaces as single-replica CRs
   of their own.  A ``kaito-tpu.io/scrape-url`` annotation (Workspace
   or Service) overrides the DNS-form URL — dev loops and tests point
   it at loopback ports.

2. **Scrape** — each target is polled on a staggered schedule (phase
   derived from the URL hash so N replicas never thundering-herd one
   instant) with a per-target deadline, CONCURRENTLY, with an
   in-flight guard per target: a hung-but-alive replica degrades only
   its own freshness, never the cadence of its siblings.  Parsing
   reuses the strict exposition parser (``kaito_tpu/utils/promtext``)
   and the ``parse_load_metrics`` pattern from ``runtime/routing``.

3. **Fold** — per scrape round, fresh replica samples collapse into
   per-CR aggregates (sum/mean/p95 + ``replicas_reporting``) appended
   to bounded ring time-series (``runtime/slo.WindowSeries`` — the SLO
   watchdog's multi-window design, lifted from one process to the
   fleet).  Counter families become rates via per-replica deltas,
   reset-safe across replica restarts (uptime gauge).

4. **Export** — ``kaito:fleet_*{kind,name}`` gauges on the manager
   registry, a ``GET /debug/fleet`` JSON endpoint next to
   ``/debug/trace``, and a ``ScalingSignal`` condition per CR fed by a
   pure-function evaluator with enter-high/exit-low hysteresis and
   sustained-window logic (``idle | nominal | pressure | saturated``),
   plus deduped ``FleetPressureDetected`` / ``FleetPressureResolved``
   Events.

No actuation here: ``recommended_replicas`` is a hint in the output
contract (ROADMAP item 1's read side) — the autoscaler PR becomes a
pure consumer of this plane.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from kaito_tpu.runtime.slo import WindowSeries
from kaito_tpu.utils.promtext import parse_exposition, parse_labels

logger = logging.getLogger(__name__)

ANNOTATION_SCRAPE_URL = "kaito-tpu.io/scrape-url"

SIGNAL_IDLE = "idle"
SIGNAL_NOMINAL = "nominal"
SIGNAL_PRESSURE = "pressure"
SIGNAL_SATURATED = "saturated"
SIGNAL_CODE = {SIGNAL_IDLE: 0, SIGNAL_NOMINAL: 1,
               SIGNAL_PRESSURE: 2, SIGNAL_SATURATED: 3}

COND_SCALING_SIGNAL = "ScalingSignal"
EVENT_PRESSURE_DETECTED = "FleetPressureDetected"
EVENT_PRESSURE_RESOLVED = "FleetPressureResolved"
EVENT_FLIGHT_RECORDED = "FlightRecorded"

# engine series folded per replica: family -> (sample key, fold across
# labelled series of ONE payload).  Gauges; counters are listed below.
_ENGINE_GAUGES = {
    "kaito:batch_occupancy": ("occupancy", "mean"),
    "kaito:num_requests_waiting": ("waiting", "sum"),
    "kaito:kv_cache_usage_perc": ("kv_usage", "mean"),
    "kaito:active_slots": ("active_slots", "sum"),
    "kaito:slots_total": ("slots_total", "sum"),
    "kaito:process_uptime_seconds": ("uptime_s", "mean"),
    "kaito:process_resident_memory_bytes": ("rss_bytes", "sum"),
    "kaito:host_kv_entries": ("host_kv_entries", "sum"),
    "kaito:host_kv_bytes_used": ("host_kv_bytes", "sum"),
    "kaito:adapter_resident": ("adapter_resident", "sum"),
    "kaito:adapter_slots_total": ("adapter_slots_total", "sum"),
    # sampled device-time attribution (engine/devprof.py): last-window
    # gauges, present only on replicas running with devprof on — the
    # fold means over whoever reports, like the adapter families
    "kaito:device_comm_pct": ("device_comm_pct", "mean"),
    "kaito:device_comm_compute_overlap_pct": ("device_overlap_pct",
                                              "mean"),
    "kaito:device_idle_pct": ("device_idle_pct", "mean"),
    # incident flight recorder (utils/flightrec.py): bundles written
    # since process start, present only with --flight-dir — the summed
    # fold feeds the controller's FlightRecorded Event
    "kaito:flight_bundles_total": ("flight_bundles", "sum"),
    # tier-3 SSD KV (docs/kv-pool.md "Tier 3: SSD"): present only on
    # replicas running with --kv-pool-disk-bytes > 0
    "kaito:kv_tier_entries": ("kv_tier_entries", "sum"),
    "kaito:kv_tier_bytes_used": ("kv_tier_bytes", "sum"),
}
# cumulative counters -> per-replica delta rates at fold time
_ENGINE_COUNTERS = {
    "kaito:request_success_total": "requests_total",
    "kaito:request_shed_total": "shed_total",
    "kaito:generation_tokens_total": "gen_tokens_total",
    "kaito:prefix_cache_hits_total": "prefix_hits_total",
    "kaito:prefix_cache_misses_total": "prefix_misses_total",
    "kaito:spec_proposed_tokens_total": "spec_proposed_total",
    "kaito:spec_accepted_tokens_total": "spec_accepted_total",
    "kaito:host_kv_hits_total": "host_kv_hits_total",
    "kaito:host_kv_misses_total": "host_kv_misses_total",
    "kaito:host_kv_evictions_total": "host_kv_evictions_total",
    "kaito:adapter_loads_total": "adapter_loads_total",
    "kaito:adapter_evictions_total": "adapter_evictions_total",
    "kaito:adapter_hits_total": "adapter_hits_total",
    "kaito:grammar_cache_hits_total": "grammar_hits_total",
    "kaito:grammar_cache_misses_total": "grammar_misses_total",
    # tier-3 SSD KV (docs/kv-pool.md "Tier 3: SSD"): the labelled
    # hits family (tier="host"|"disk") sums across labels into one
    # local-tier hit counter; spills/evictions judge churn
    "kaito:kv_tier_hits_total": "kv_tier_hits_total",
    "kaito:kv_tier_spills_total": "kv_tier_spills_total",
    "kaito:kv_tier_evictions_total": "kv_tier_evictions_total",
    # packed prefill (docs/prefill.md): histogram _sum/_count fold into
    # plain counters (a fleet-level histogram merge would need every
    # bucket edge; mean pack size + dispatch rate answer the capacity
    # question), plus the prompt-token counter for tokens/s
    "kaito:prompt_tokens_total": "prompt_tokens_total",
    "kaito:engine_prefill_pack_size_sum": "prefill_packed_seqs_total",
    "kaito:engine_prefill_pack_size_count": "prefill_dispatches_total",
    "kaito:prefill_queue_wait_seconds_sum": "prefill_wait_seconds_total",
    "kaito:prefill_queue_wait_seconds_count": "prefill_waits_total",
}
# EPP / router front series (arrival side of the same CR).  The
# received counter keeps ticking even with ZERO backends — it is the
# scale-to-zero wake signal the autoscaler watches.
_EPP_COUNTERS = {
    "kaito:router_requests_forwarded_total": "forwarded_total",
    "kaito:epp_requests_forwarded_total": "forwarded_total",
    "kaito:router_requests_received_total": "received_total",
}
# tenant-labelled counters (present only when the engine runs with a
# QoS config) -> dynamic per-tenant keys "tenant_<what>_total:<tenant>"
_TENANT_COUNTERS = {
    "kaito:requests_shed_total": "tenant_shed_total",
    "kaito:requests_served_total": "tenant_served_total",
}


@dataclass
class FleetPolicy:
    """Signal thresholds (enter-high / exit-low pairs) + sustain
    windows.  Everything injectable so the unit tier and small e2e
    clusters can tighten the bands."""

    # pressure enters when ANY high watermark is sustained; exits to
    # nominal only when EVERY low watermark is sustained (hysteresis)
    occupancy_hi: float = 0.85
    occupancy_lo: float = 0.60
    queue_hi: float = 4.0          # waiting requests PER replica
    queue_lo: float = 1.0
    kv_hi: float = 0.90
    kv_lo: float = 0.70
    burn_hi: float = 1.0           # worst fast-window SLO burn
    burn_lo: float = 0.25
    shed_hi: float = 0.5           # sheds/s across the fleet
    shed_lo: float = 0.0
    # saturation: pressure so deep that +1 replica won't cut it
    sat_kv: float = 0.97
    sat_shed: float = 2.0
    sat_queue: float = 16.0        # per replica, with occupancy pinned
    sat_occupancy: float = 0.95
    # sustained-window lengths: a transition needs EVERY sample inside
    # the window on the far side of the watermark AND enough coverage
    sustain_s: float = 30.0
    idle_sustain_s: float = 300.0
    min_window_coverage: float = 0.8
    min_samples: int = 2
    # freshness horizon for replica samples (0 = derive from interval)
    freshness_s: float = 0.0
    # recommended_replicas hints (no actuation in this plane)
    scale_to_zero_hint: bool = False
    max_replicas_hint: int = 0     # 0 = unbounded

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in (
            "occupancy_hi", "occupancy_lo", "queue_hi", "queue_lo",
            "kv_hi", "kv_lo", "burn_hi", "burn_lo", "shed_hi", "shed_lo",
            "sat_kv", "sat_shed", "sat_queue", "sat_occupancy",
            "sustain_s", "idle_sustain_s")}


@dataclass
class SignalDecision:
    """Output contract of the pure evaluator — the read-side half of
    the autoscaler loop (ROADMAP item 1)."""

    state: str
    reason: str                    # CamelCase, condition/Event-ready
    message: str                   # stable wording (Event dedupe)
    drivers: list                  # which watermarks drove the state
    observed: dict                 # last aggregate sample
    recommended_replicas: int      # hint only; unused in this PR


# ---------------------------------------------------------------------------
# pure signal evaluation
# ---------------------------------------------------------------------------

def _per_replica_queue(s: dict) -> float:
    return s.get("queue_sum", 0.0) / max(1.0, s.get("replicas_reporting", 1))


def _pressure_drivers(s: dict, p: FleetPolicy) -> list[str]:
    """Which high watermarks does this aggregate sample cross?"""
    out = []
    if s.get("occupancy_mean", 0.0) >= p.occupancy_hi:
        out.append("occupancy")
    if _per_replica_queue(s) >= p.queue_hi:
        out.append("queue")
    if s.get("kv_mean", 0.0) >= p.kv_hi:
        out.append("kv")
    if s.get("burn_max", 0.0) >= p.burn_hi:
        out.append("slo-burn")
    if s.get("shed_rate", 0.0) > p.shed_hi:
        out.append("shed")
    return out


def _below_low_watermarks(s: dict, p: FleetPolicy) -> bool:
    return (s.get("occupancy_mean", 0.0) <= p.occupancy_lo
            and _per_replica_queue(s) <= p.queue_lo
            and s.get("kv_mean", 0.0) <= p.kv_lo
            and s.get("burn_max", 0.0) <= p.burn_lo
            and s.get("shed_rate", 0.0) <= p.shed_lo)


def _saturated(s: dict, p: FleetPolicy) -> bool:
    return (s.get("kv_mean", 0.0) >= p.sat_kv
            or s.get("shed_rate", 0.0) >= p.sat_shed
            or (s.get("occupancy_mean", 0.0) >= p.sat_occupancy
                and _per_replica_queue(s) >= p.sat_queue))


def _idle(s: dict) -> bool:
    return (s.get("requests_rate", 0.0) <= 0.0
            and s.get("queue_sum", 0.0) <= 0.0
            and s.get("active_slots", 0.0) <= 0.0)


def _sustained(samples: list[tuple[float, dict]], now: float,
               window_s: float, pred: Callable[[dict], bool],
               policy: FleetPolicy) -> bool:
    """True when EVERY sample inside ``[now - window_s, now]``
    satisfies ``pred`` AND the window has real coverage — enough
    samples, and the oldest one near the window's far edge.  Without
    the coverage check a single fresh sample would count as
    'sustained' right after startup."""
    inside = [(t, s) for t, s in samples if t >= now - window_s]
    if len(inside) < policy.min_samples:
        return False
    oldest = min(t for t, _ in inside)
    if now - oldest < window_s * policy.min_window_coverage:
        return False
    return all(pred(s) for _, s in inside)


def recommend_replicas(state: str, replicas: int, p: FleetPolicy) -> int:
    """The hint the autoscaler PR will consume.  Deliberately coarse —
    +1 on pressure, +50% on saturation, shrink toward idle — the
    actuation policy (warm pools, drain, cooldowns) lives with the
    actuator, not the telemetry plane."""
    replicas = max(1, int(replicas))
    if state == SIGNAL_SATURATED:
        want = replicas + max(1, math.ceil(replicas * 0.5))
    elif state == SIGNAL_PRESSURE:
        want = replicas + 1
    elif state == SIGNAL_IDLE:
        want = 0 if p.scale_to_zero_hint else 1
    else:
        want = replicas
    if p.max_replicas_hint > 0:
        want = min(want, p.max_replicas_hint)
    return want


def evaluate_signal(prev_state: str, samples: list[tuple[float, dict]],
                    policy: FleetPolicy, now: float,
                    replicas: int = 1) -> SignalDecision:
    """Pure function: (previous state, aggregate ring samples, policy,
    clock) -> next state + contract.  Enter-high/exit-low hysteresis:
    entering ``pressure`` needs a HIGH watermark sustained for
    ``sustain_s``; leaving it needs EVERY low watermark sustained for
    the same window — a fleet hovering at one threshold cannot flap."""
    p = policy
    prev = prev_state if prev_state in SIGNAL_CODE else SIGNAL_NOMINAL
    last = samples[-1][1] if samples else {}
    state = prev

    def sustained(pred, window=p.sustain_s):
        return _sustained(samples, now, window, pred, p)

    if sustained(lambda s: _saturated(s, p)):
        state = SIGNAL_SATURATED
    elif prev == SIGNAL_SATURATED:
        # exit saturation only once below the saturation band...
        if sustained(lambda s: not _saturated(s, p)):
            # ...and fall all the way to nominal only through the
            # pressure exit-low gate
            state = SIGNAL_NOMINAL if sustained(
                lambda s: _below_low_watermarks(s, p)) else SIGNAL_PRESSURE
    elif prev == SIGNAL_PRESSURE:
        if sustained(lambda s: _below_low_watermarks(s, p)):
            state = SIGNAL_NOMINAL
    else:                                  # idle | nominal
        if sustained(lambda s: bool(_pressure_drivers(s, p))):
            state = SIGNAL_PRESSURE
        elif prev == SIGNAL_IDLE:
            if last and not _idle(last):
                state = SIGNAL_NOMINAL     # traffic arrived: wake now
        elif sustained(_idle, p.idle_sustain_s):
            state = SIGNAL_IDLE

    drivers = _pressure_drivers(last, p) if last else []
    if state == SIGNAL_NOMINAL:
        reason, msg = "FleetNominal", "fleet load inside the nominal band"
    elif state == SIGNAL_IDLE:
        reason, msg = "FleetIdle", \
            f"no fleet traffic for {int(p.idle_sustain_s)}s"
    else:
        reason = "FleetSaturated" if state == SIGNAL_SATURATED \
            else "FleetPressure"
        # stable wording (no live numbers): repeats dedupe into one
        # Event with a bumped count instead of flooding the ring
        msg = (f"sustained {state}: "
               f"{', '.join(drivers) or 'load above watermarks'}")
    return SignalDecision(
        state=state, reason=reason, message=msg, drivers=drivers,
        observed=dict(last),
        recommended_replicas=recommend_replicas(state, replicas, p))


# ---------------------------------------------------------------------------
# scrape targets + samples
# ---------------------------------------------------------------------------

@dataclass
class ScrapeTarget:
    url: str
    replica: str                   # workspace name / "<name>-epp"
    role: str = "replica"          # "replica" | "epp"
    phase: float = 0.0             # stagger offset inside the interval


@dataclass
class ReplicaSample:
    """Last successful scrape of one target, plus derived rates."""

    ts: float = 0.0                # time_fn() at scrape success
    values: dict = field(default_factory=dict)
    rates: dict = field(default_factory=dict)
    scrape_seconds: float = 0.0
    consecutive_failures: int = 0
    last_error: str = ""


class _CRSeries:
    """Per-CR ring time-series of fold aggregates + signal state."""

    def __init__(self, kind: str, namespace: str, name: str,
                 max_window_s: float, time_fn: Callable[[], float]):
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.time_fn = time_fn
        self.ring: WindowSeries = WindowSeries(max_window_s, time_fn)
        # WindowSeries stores scalars; aggregates ride next to it as
        # (ts, dict) tuples pruned on the same horizon
        self.samples: list[tuple[float, dict]] = []
        self.max_window_s = max_window_s
        self.state = SIGNAL_NOMINAL
        self.state_since = time_fn()
        self.transitions = 0
        self.last_decision: Optional[SignalDecision] = None
        self.replicas_desired = 0
        # flight-recorder Event dedupe: folded bundle count at the last
        # FlightRecorded Event (None = no baseline yet — the first
        # observation must not read pre-existing bundles as an incident)
        self.flight_bundles_seen: Optional[float] = None
        # per-CR hint overrides from spec.autoscale (scale_to_zero,
        # max_replicas); None = global policy (one config source for
        # recommended_replicas hints AND actuation)
        self.hint_overrides: Optional[tuple[bool, int]] = None

    def add(self, agg: dict) -> None:
        now = self.time_fn()
        self.ring.add(agg.get("queue_sum", 0.0))   # bounded scalar ring
        self.samples.append((now, agg))
        cutoff = now - self.max_window_s
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.pop(0)
        # hard bound mirrors WindowSeries: a stuck clock cannot grow it
        del self.samples[:-4096]

    def window_stats(self, window_s: float) -> dict:
        now = self.time_fn()
        inside = [s for t, s in self.samples if t >= now - window_s]
        if not inside:
            return {}
        out: dict[str, dict] = {}
        for key in sorted({k for s in inside for k in s}):
            vals = [s[key] for s in inside if key in s]
            out[key] = {"last": round(vals[-1], 6),
                        "mean": round(sum(vals) / len(vals), 6),
                        "max": round(max(vals), 6)}
        return out


def _stable_phase(url: str, interval_s: float) -> float:
    h = int.from_bytes(hashlib.sha256(url.encode()).digest()[:8], "big")
    return (h / 2.0 ** 64) * interval_s


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def parse_replica_metrics(text: str) -> dict[str, float]:
    """Fold one ``/metrics`` payload into the fleet's sample keys.
    Labelled series of one family are summed (counters, absolute
    gauges) or averaged (utilization ratios) exactly like
    ``routing.parse_load_metrics`` — robust to DP-grouped engines."""
    sums: dict[str, list[float]] = {}
    means: dict[str, list[float]] = {}
    for name, labels, value in parse_exposition(text):
        gauge = _ENGINE_GAUGES.get(name)
        if gauge is not None:
            key, fold = gauge
            (means if fold == "mean" else sums).setdefault(
                key, []).append(value)
            continue
        ctr = _ENGINE_COUNTERS.get(name) or _EPP_COUNTERS.get(name)
        if ctr is not None:
            sums.setdefault(ctr, []).append(value)
            continue
        ten = _TENANT_COUNTERS.get(name)
        if ten is not None:
            tenant = parse_labels(labels).get("tenant", "")
            if tenant:
                sums.setdefault(f"{ten}:{tenant}", []).append(value)
    out = {k: sum(v) for k, v in sums.items()}
    out.update({k: sum(v) / len(v) for k, v in means.items()})
    return out


# ---------------------------------------------------------------------------
# the telemetry plane
# ---------------------------------------------------------------------------

class FleetTelemetry:
    """Discover → scrape → fold → evaluate → export.

    Cheap to construct (no threads, no sockets): the manager builds one
    per process and either runs the background loop (``start()``) or
    drives rounds explicitly (``scrape_once`` — what the test tiers
    do).  ``time_fn`` is injectable for deterministic units."""

    def __init__(self, store, policy: Optional[FleetPolicy] = None,
                 interval_s: float = 10.0, timeout_s: float = 2.0,
                 max_window_s: float = 900.0,
                 time_fn: Callable[[], float] = time.monotonic):
        self.store = store
        self.policy = policy or FleetPolicy()
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.max_window_s = float(max_window_s)
        self.time_fn = time_fn
        self._lock = threading.Lock()
        # CR key -> {url -> ScrapeTarget}; epp targets ride in the same
        # map with role="epp"
        self._targets: dict[tuple, dict[str, ScrapeTarget]] = {}
        self._samples: dict[tuple, dict[str, ReplicaSample]] = {}
        self._crs: dict[tuple, _CRSeries] = {}
        self._next_due: dict[str, float] = {}
        self._inflight: set[str] = set()
        self._last_agg: dict[tuple, dict] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- freshness -----------------------------------------------------

    @property
    def freshness_s(self) -> float:
        return self.policy.freshness_s or (3.0 * self.interval_s
                                           + self.timeout_s)

    # -- discovery -----------------------------------------------------

    def _resolve_url(self, obj, service) -> Optional[str]:
        """Workspace/Service -> scrape URL.  Annotation beats DNS; no
        Service and no annotation means the replica is not scrapable
        yet (it simply doesn't report)."""
        for o in (obj, service):
            if o is None:
                continue
            url = (getattr(o.metadata, "annotations", None)
                   or {}).get(ANNOTATION_SCRAPE_URL)
            if url:
                return url.rstrip("/")
        if service is None:
            return None
        ports = (service.spec or {}).get("ports") or []
        port = ports[0].get("port", 5000) if ports else 5000
        return f"http://{service.metadata.name}:{port}"

    def refresh_targets(self) -> None:
        """Rebuild the target map from the store: InferenceSet children
        + their EPP, and standalone Workspaces as their own CR."""
        from kaito_tpu.api.workspace import LABEL_CREATED_BY_INFERENCESET

        targets: dict[tuple, dict[str, ScrapeTarget]] = {}
        desired: dict[tuple, int] = {}
        hints: dict[tuple, tuple[bool, int]] = {}

        def add(key, url, replica, role):
            if url is None:
                return
            targets.setdefault(key, {})[url] = ScrapeTarget(
                url=url, replica=replica, role=role,
                phase=_stable_phase(url, self.interval_s))

        try:
            isets = self.store.list("InferenceSet")
        except Exception:
            isets = []
        for iset in isets:
            ns, name = iset.metadata.namespace, iset.metadata.name
            key = ("InferenceSet", ns, name)
            desired[key] = max(getattr(iset.status, "replicas", 0),
                               getattr(iset.spec, "replicas", 0))
            autoscale = getattr(iset.spec, "autoscale", None)
            if autoscale is not None and autoscale.enabled:
                hints[key] = (bool(autoscale.scale_to_zero),
                              int(autoscale.max_replicas))
            children = self.store.list(
                "Workspace", ns,
                labels={LABEL_CREATED_BY_INFERENCESET: name})
            for ws in children:
                svc = self.store.try_get("Service", ns, ws.metadata.name)
                add(key, self._resolve_url(ws, svc), ws.metadata.name,
                    "replica")
            epp_svc = self.store.try_get("Service", ns, f"{name}-epp")
            if epp_svc is not None:
                add(key, self._resolve_url(None, epp_svc), f"{name}-epp",
                    "epp")
        try:
            workspaces = self.store.list("Workspace")
        except Exception:
            workspaces = []
        for ws in workspaces:
            if ws.metadata.labels.get(LABEL_CREATED_BY_INFERENCESET):
                continue                  # counted under its set
            ns, name = ws.metadata.namespace, ws.metadata.name
            key = ("Workspace", ns, name)
            desired[key] = 1
            svc = self.store.try_get("Service", ns, name)
            url = self._resolve_url(ws, svc)
            if url is not None:
                add(key, url, name, "replica")

        with self._lock:
            self._targets = targets
            for key in list(self._samples):
                if key not in targets:
                    del self._samples[key]
            for key, tmap in targets.items():
                cr = self._crs.get(key)
                if cr is None:
                    cr = self._crs[key] = _CRSeries(
                        key[0], key[1], key[2], self.max_window_s,
                        self.time_fn)
                cr.replicas_desired = desired.get(key, len(tmap))
                cr.hint_overrides = hints.get(key)
                smap = self._samples.setdefault(key, {})
                for url in list(smap):
                    if url not in tmap:
                        del smap[url]     # replica left the set
            for key in list(self._crs):
                if key not in targets:
                    del self._crs[key]
                    self._last_agg.pop(key, None)

    # -- scraping ------------------------------------------------------

    def _fetch(self, url: str, path: str) -> Optional[bytes]:
        if not url.startswith("http://"):
            raise ValueError(f"unsupported scrape url: {url}")
        hostport = url[len("http://"):]
        host, _, port = hostport.partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80),
                                          timeout=self.timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return resp.read()
        finally:
            conn.close()

    def _scrape_target(self, key: tuple, t: ScrapeTarget) -> None:
        t0 = self.time_fn()
        values: dict[str, float] = {}
        err = ""
        try:
            body = self._fetch(t.url, "/metrics")
            if body is None:
                raise ConnectionError("non-200 /metrics")
            values = parse_replica_metrics(body.decode("utf-8", "replace"))
            if t.role == "replica":
                # one extra cheap field ride-along: the replica's worst
                # fast-window SLO burn (see slo.snapshot burn_max)
                try:
                    slo_body = self._fetch(t.url, "/debug/slo")
                    if slo_body is not None:
                        snap = json.loads(slo_body)
                        values["burn_max"] = float(
                            snap.get("burn_max", 0.0))
                        # per-role attribution (ROADMAP item 1): the
                        # replica's role keys a dynamic burn field so
                        # the P/D split can act on the right SLO per
                        # pool; the ITL SLI rides along when enabled
                        role = str(snap.get("role", "") or "unified")
                        values[f"role_burn:{role}"] = values["burn_max"]
                        itl = (snap.get("burn_rates") or {}).get(
                            "itl_p99")
                        if itl is not None:
                            values["itl_burn_max"] = float(
                                itl.get("fast", 0.0))
                except (ValueError, ConnectionError, OSError):
                    pass                  # burn is optional per scrape
        except (ConnectionError, OSError, ValueError) as e:
            err = f"{type(e).__name__}: {e}"
        now = self.time_fn()
        with self._lock:
            smap = self._samples.setdefault(key, {})
            prev = smap.get(t.url) or ReplicaSample()
            if err:
                prev.consecutive_failures += 1
                prev.last_error = err
                smap[t.url] = prev        # ts stays stale
                return
            rates = self._rates(prev, values, now)
            smap[t.url] = ReplicaSample(
                ts=now, values=values, rates=rates,
                scrape_seconds=now - t0, consecutive_failures=0)

    def _rates(self, prev: ReplicaSample, values: dict,
               now: float) -> dict:
        """Counter deltas -> per-second rates.  A counter that moved
        backwards (replica restart — the uptime gauge confirms) rates
        as 0 for one round instead of going hugely negative."""
        if not prev.ts or now <= prev.ts:
            return {}
        dt = now - prev.ts
        restarted = values.get("uptime_s", float("inf")) < dt
        out = {}
        keys = ["requests_total", "shed_total", "gen_tokens_total",
                "prefix_hits_total", "prefix_misses_total",
                "spec_proposed_total", "spec_accepted_total",
                "host_kv_hits_total", "host_kv_misses_total",
                "host_kv_evictions_total",
                "kv_tier_hits_total", "kv_tier_spills_total",
                "kv_tier_evictions_total",
                "adapter_loads_total", "adapter_evictions_total",
                "adapter_hits_total",
                "grammar_hits_total", "grammar_misses_total",
                "prompt_tokens_total", "prefill_packed_seqs_total",
                "prefill_dispatches_total", "prefill_wait_seconds_total",
                "prefill_waits_total",
                "forwarded_total", "received_total"]
        # per-tenant counters carry the tenant in the key itself
        # ("tenant_shed_total:acme"), so rate whatever both samples have
        keys += [k for k in values if k.startswith("tenant_")
                 and "_total:" in k]
        for key in keys:
            if key not in values or key not in prev.values:
                continue
            delta = values[key] - prev.values[key]
            if delta < 0 or restarted:
                delta = 0.0
            stem, _, tenant = key.partition(":")
            rkey = stem[:-len("_total")] + "_rate"
            out[f"{rkey}:{tenant}" if tenant else rkey] = delta / dt
        return out

    def scrape_once(self, force: bool = False, wait: bool = True) -> int:
        """One staggered round: spawn a worker per due target (guarded
        so a hung target never piles up), optionally join with the
        per-target deadline, then fold.  Returns the number of targets
        polled this round."""
        now = self.time_fn()
        with self._lock:
            due: list[tuple[tuple, ScrapeTarget]] = []
            for key, tmap in self._targets.items():
                for t in tmap.values():
                    nd = self._next_due.get(t.url)
                    if nd is None:
                        nd = now + (0.0 if force else t.phase)
                        self._next_due[t.url] = nd
                    if not force and now < nd:
                        continue
                    if t.url in self._inflight:
                        continue          # hung: only ITS freshness lags
                    self._inflight.add(t.url)
                    self._next_due[t.url] = max(nd, now) + self.interval_s
                    due.append((key, t))
        workers = []
        for key, t in due:
            th = threading.Thread(target=self._scrape_guarded,
                                  args=(key, t), daemon=True,
                                  name="fleet-scrape")
            th.start()
            workers.append(th)
        if wait:
            deadline = time.monotonic() + self.timeout_s + 1.0
            for th in workers:
                th.join(max(0.0, deadline - time.monotonic()))
        self.fold()
        return len(due)

    def _scrape_guarded(self, key: tuple, t: ScrapeTarget) -> None:
        try:
            self._scrape_target(key, t)
        finally:
            with self._lock:
                self._inflight.discard(t.url)

    # -- folding -------------------------------------------------------

    def ingest(self, key: tuple, url: str, values: dict,
               rates: Optional[dict] = None, role: str = "replica",
               replica: str = "") -> None:
        """Test/embedding hook: feed a replica sample without a socket
        (the unit tier drives the evaluator through this)."""
        with self._lock:
            self._targets.setdefault(key, {})[url] = ScrapeTarget(
                url=url, replica=replica or url, role=role)
            if key not in self._crs:
                self._crs[key] = _CRSeries(key[0], key[1], key[2],
                                           self.max_window_s, self.time_fn)
                self._crs[key].replicas_desired = 1
            self._samples.setdefault(key, {})[url] = ReplicaSample(
                ts=self.time_fn(), values=dict(values),
                rates=dict(rates or {}))

    def _fresh(self, key: tuple) -> tuple[list, list]:
        now = self.time_fn()
        horizon = now - self.freshness_s
        replicas, epps = [], []
        tmap = self._targets.get(key, {})
        for url, s in self._samples.get(key, {}).items():
            if s.ts <= 0 or s.ts < horizon:
                continue
            t = tmap.get(url)
            (epps if t is not None and t.role == "epp"
             else replicas).append(s)
        return replicas, epps

    def fold(self) -> None:
        """Collapse fresh replica samples into one aggregate sample per
        CR and append it to the CR's ring."""
        with self._lock:
            keys = list(self._targets)
        for key in keys:
            with self._lock:
                replicas, epps = self._fresh(key)
                cr = self._crs.get(key)
            if cr is None:
                continue
            agg = self._aggregate(replicas, epps)
            with self._lock:
                cr.add(agg)
                self._last_agg[key] = agg

    @staticmethod
    def _aggregate(replicas: list, epps: list) -> dict:
        def vals(k):
            return [s.values[k] for s in replicas if k in s.values]

        def rate(k):
            return sum(s.rates.get(k, 0.0) for s in replicas)

        def fold(k, how):
            v = vals(k)
            if not v:
                return 0.0
            if how == "sum":
                return sum(v)
            if how == "mean":
                return sum(v) / len(v)
            return _percentile(v, 0.95)

        hit = rate("prefix_hits_rate")
        miss = rate("prefix_misses_rate")
        prop = rate("spec_proposed_rate")
        acc = rate("spec_accepted_rate")
        hkv_hit = rate("host_kv_hits_rate")
        hkv_miss = rate("host_kv_misses_rate")
        gr_hit = rate("grammar_hits_rate")
        gr_miss = rate("grammar_misses_rate")
        agg = {
            "replicas_reporting": float(len(replicas)),
            "queue_sum": fold("waiting", "sum"),
            "queue_p95": fold("waiting", "p95"),
            "occupancy_mean": fold("occupancy", "mean"),
            "occupancy_p95": fold("occupancy", "p95"),
            "kv_mean": fold("kv_usage", "mean"),
            "kv_p95": fold("kv_usage", "p95"),
            "active_slots": fold("active_slots", "sum"),
            "slots_total": fold("slots_total", "sum"),
            "rss_bytes": fold("rss_bytes", "sum"),
            "uptime_min": min(vals("uptime_s"), default=0.0),
            "requests_total": fold("requests_total", "sum"),
            "gen_tokens_total": fold("gen_tokens_total", "sum"),
            "requests_rate": rate("requests_rate"),
            "shed_rate": rate("shed_rate"),
            "tokens_rate": rate("gen_tokens_rate"),
            "burn_max": max(vals("burn_max"), default=0.0),
            # per-token ITL SLI (replicas running with --itl): worst
            # fast-window itl_p99 burn across the fleet
            "itl_burn_max": max(vals("itl_burn_max"), default=0.0),
            # incident flight recorder: bundles written across replicas
            # (apply_signals turns an increase into a FlightRecorded
            # Event on the owning CR)
            "flight_bundles": fold("flight_bundles", "sum"),
            "prefix_hit_rate": hit / (hit + miss) if hit + miss > 0 else 0.0,
            "spec_accept_rate": acc / prop if prop > 0 else 0.0,
            # host KV offload tier, cluster-wide: capacity (entries /
            # bytes sums), churn (evictions/s), and effectiveness (hit
            # fraction of pops) — the rollout dashboards judge whether
            # the tier is sized right from these three
            "host_kv_entries": fold("host_kv_entries", "sum"),
            "host_kv_bytes": fold("host_kv_bytes", "sum"),
            "host_kv_evictions_rate": rate("host_kv_evictions_rate"),
            "host_kv_hit_rate": (hkv_hit / (hkv_hit + hkv_miss)
                                 if hkv_hit + hkv_miss > 0 else 0.0),
            # tier-3 SSD KV (docs/kv-pool.md "Tier 3: SSD"): capacity
            # (entries/bytes across replicas running the tier), local
            # tiered-probe hit rate, and demotion/prune churn
            "kv_tier_entries": fold("kv_tier_entries", "sum"),
            "kv_tier_bytes": fold("kv_tier_bytes", "sum"),
            "kv_tier_hits_rate": rate("kv_tier_hits_rate"),
            "kv_tier_spills_rate": rate("kv_tier_spills_rate"),
            "kv_tier_evictions_rate": rate("kv_tier_evictions_rate"),
            # multi-LoRA adapter plane (docs/multi-lora.md): residency
            # vs capacity (is the slot table sized right?), hot-load +
            # eviction churn, and per-request adapter traffic
            "adapter_resident": fold("adapter_resident", "sum"),
            "adapter_slots_total": fold("adapter_slots_total", "sum"),
            "adapter_loads_rate": rate("adapter_loads_rate"),
            "adapter_evictions_rate": rate("adapter_evictions_rate"),
            "adapter_hits_rate": rate("adapter_hits_rate"),
            # structured output (docs/structured-output.md): fraction
            # of constrained requests served a precompiled grammar —
            # a low rate cluster-wide means the schema working set
            # exceeds --grammar-cache-entries
            "grammar_cache_hit_rate": (
                gr_hit / (gr_hit + gr_miss)
                if gr_hit + gr_miss > 0 else 0.0),
            # packed prefill (docs/prefill.md): prompt tokens/s,
            # prefill dispatches/s, mean sequences per dispatch (the
            # packing win — 1.0 means serial), and mean staged->first-
            # dispatch queue wait (the TTFT component packing attacks)
            "prefill_tokens_rate": rate("prompt_tokens_rate"),
            "prefill_dispatch_rate": rate("prefill_dispatches_rate"),
            "prefill_pack_mean": (
                rate("prefill_packed_seqs_rate")
                / rate("prefill_dispatches_rate")
                if rate("prefill_dispatches_rate") > 0 else 0.0),
            "prefill_queue_wait_mean": (
                rate("prefill_wait_seconds_rate")
                / rate("prefill_waits_rate")
                if rate("prefill_waits_rate") > 0 else 0.0),
            # sampled device-time attribution (engine/devprof.py):
            # means over the replicas that report (devprof-off
            # replicas emit no device_* series and don't dilute)
            "device_comm_pct": fold("device_comm_pct", "mean"),
            "device_overlap_pct": fold("device_overlap_pct", "mean"),
            "device_idle_pct": fold("device_idle_pct", "mean"),
        }
        if epps:
            agg["arrival_rate"] = sum(
                s.rates.get("forwarded_rate", 0.0) for s in epps)
            agg["received_rate"] = sum(
                s.rates.get("received_rate", 0.0) for s in epps)
            agg["epp_reporting"] = float(len(epps))
        # per-tenant slices (QoS engines only): sum each tenant's
        # shed/served rate across replicas, keyed "tenant_shed_rate:<t>"
        for s in replicas:
            for rk, rv in s.rates.items():
                if rk.startswith("tenant_") and ":" in rk:
                    agg[rk] = agg.get(rk, 0.0) + rv
        # per-role SLO burn (ROADMAP item 1): worst burn per serving
        # role across replicas, keyed "role_burn:<role>" — the P/D
        # autoscaler scales prefill pools on TTFT burn and decode pools
        # on ITL burn without mixing the two
        for s in replicas:
            for rk, rv in s.values.items():
                if rk.startswith("role_burn:"):
                    agg[rk] = max(agg.get(rk, 0.0), rv)
        return agg

    # -- evaluation + condition/event surfacing ------------------------

    def evaluate(self, key: tuple) -> Optional[SignalDecision]:
        """Run the pure evaluator over one CR's ring; updates the CR's
        sticky state.  None until the first fold lands (no telemetry ->
        no opinion, so embedding a Manager never writes conditions for
        CRs nobody scrapes)."""
        with self._lock:
            cr = self._crs.get(key)
            if cr is None or not cr.samples:
                return None
            samples = list(cr.samples)
            prev = cr.state
            replicas = cr.replicas_desired or 1
            overrides = cr.hint_overrides
        policy = self.policy
        if overrides is not None:
            # spec.autoscale is the single config source: its
            # scale-to-zero / max-replicas bounds shape the hint the
            # actuator consumes (satellite of the autoscaler PR)
            import dataclasses

            policy = dataclasses.replace(
                policy, scale_to_zero_hint=overrides[0],
                max_replicas_hint=overrides[1])
        decision = evaluate_signal(prev, samples, policy,
                                   self.time_fn(), replicas)
        with self._lock:
            if decision.state != cr.state:
                cr.state = decision.state
                cr.state_since = self.time_fn()
                cr.transitions += 1
            cr.last_decision = decision
        return decision

    def signal(self, key: tuple) -> Optional[tuple[str, float, SignalDecision]]:
        """Actuator-facing read: (state, state_since, last decision)
        for one CR, or None before the first evaluation.  The
        autoscaler consumes this instead of re-parsing conditions."""
        with self._lock:
            cr = self._crs.get(key)
            if cr is None or cr.last_decision is None:
                return None
            return cr.state, cr.state_since, cr.last_decision

    def last_aggregate(self, key: tuple) -> dict:
        """Last folded aggregate for one CR ({} when never folded) —
        the autoscaler's scale-to-zero wake check reads
        ``received_rate`` from here."""
        with self._lock:
            return dict(self._last_agg.get(key, {}))

    def apply_signals(self) -> None:
        """Evaluate every CR and surface the verdict: ``ScalingSignal``
        condition (+ status hint fields on InferenceSet) and deduped
        pressure Events.  Store writes only happen on CHANGE — a
        steady fleet adds zero resourceVersion churn per resync."""
        from kaito_tpu.api.meta import Condition, get_condition, set_condition
        from kaito_tpu.controllers.runtime import update_with_retry
        from kaito_tpu.k8s.events import record_event

        with self._lock:
            keys = list(self._crs)
        for key in keys:
            with self._lock:
                cr = self._crs.get(key)
                prev = cr.state if cr else SIGNAL_NOMINAL
            decision = self.evaluate(key)
            if decision is None:
                continue
            kind, ns, name = key
            obj = self.store.try_get(kind, ns, name)
            if obj is None:
                continue
            # abnormal-true convention (PodPressure-style): True means
            # a scaling action is signalled; False means nominal
            status = "True" if decision.state != SIGNAL_NOMINAL else "False"
            reason, message = decision.reason, decision.message
            if decision.observed.get("replicas_reporting", 0) <= 0:
                status, reason = "Unknown", "NoTelemetry"
                message = "no replica reported a fresh scrape"
            cur = get_condition(obj.status.conditions, COND_SCALING_SIGNAL)
            hint = decision.recommended_replicas
            needs_write = (cur is None or cur.status != status
                           or cur.reason != reason
                           or (kind == "InferenceSet"
                               and (getattr(obj.status, "scaling_signal", "")
                                    != decision.state
                                    or getattr(obj.status,
                                               "recommended_replicas", -1)
                                    != hint)))
            if needs_write:
                def mutate(o):
                    set_condition(o.status.conditions, Condition(
                        type=COND_SCALING_SIGNAL, status=status,
                        reason=reason, message=message))
                    if hasattr(o.status, "scaling_signal"):
                        o.status.scaling_signal = decision.state
                    if hasattr(o.status, "recommended_replicas"):
                        o.status.recommended_replicas = hint
                try:
                    update_with_retry(self.store, kind, ns, name, mutate)
                except Exception:
                    logger.debug("ScalingSignal write failed for %s",
                                 key, exc_info=True)
            entered_pressure = (decision.state in (SIGNAL_PRESSURE,
                                                   SIGNAL_SATURATED)
                                and prev not in (SIGNAL_PRESSURE,
                                                 SIGNAL_SATURATED))
            left_pressure = (prev in (SIGNAL_PRESSURE, SIGNAL_SATURATED)
                             and decision.state not in (SIGNAL_PRESSURE,
                                                        SIGNAL_SATURATED))
            if entered_pressure:
                record_event(self.store, obj, "Warning",
                             EVENT_PRESSURE_DETECTED, decision.message)
            elif left_pressure:
                record_event(self.store, obj, "Normal",
                             EVENT_PRESSURE_RESOLVED,
                             f"fleet back to {decision.state}")
            # incident flight recorder: surface a FlightRecorded Event
            # the moment any replica's bundle count advances past the
            # remembered baseline (first observation only arms it, so
            # pre-existing bundles don't read as a fresh incident;
            # restarts lower the sum and just re-baseline)
            fb = decision.observed.get("flight_bundles", 0.0)
            with self._lock:
                cr = self._crs.get(key)
                seen = cr.flight_bundles_seen if cr is not None else None
                if cr is not None:
                    cr.flight_bundles_seen = fb
            if seen is not None and fb > seen:
                record_event(
                    self.store, obj, "Warning", EVENT_FLIGHT_RECORDED,
                    f"flight-recorder bundle(s) written "
                    f"({int(seen)} -> {int(fb)}): fetch via "
                    f"GET /debug/flight on the replicas")

    # -- export: gauges + /debug/fleet ---------------------------------

    def register_metrics(self, registry) -> None:
        """Attach ``kaito:fleet_*{kind,name}`` to the manager registry.
        Everything reads the last fold, so the labelled-fn Gauge form
        fits exactly (same pattern as the SLO watchdog)."""
        from kaito_tpu.engine.metrics import Gauge

        def family(field_, scale=1.0):
            def _fn():
                with self._lock:
                    return {(k[0], k[2]): agg.get(field_, 0.0) * scale
                            for k, agg in self._last_agg.items()}
            return _fn

        def agg_family(fields):
            def _fn():
                out = {}
                with self._lock:
                    for k, agg in self._last_agg.items():
                        for agg_name, f in fields.items():
                            out[(k[0], k[2], agg_name)] = agg.get(f, 0.0)
                return out
            return _fn

        r = registry
        Gauge("kaito:fleet_replicas_reporting",
              "Replicas with a fresh scrape, per CR", r,
              labels=("kind", "name"), fn=family("replicas_reporting"))
        Gauge("kaito:fleet_queue_depth",
              "Waiting requests across the fleet (sum/mean/p95)", r,
              labels=("kind", "name", "agg"),
              fn=agg_family({"sum": "queue_sum", "p95": "queue_p95"}))
        Gauge("kaito:fleet_batch_occupancy",
              "Decode-slot occupancy across the fleet", r,
              labels=("kind", "name", "agg"),
              fn=agg_family({"mean": "occupancy_mean",
                             "p95": "occupancy_p95"}))
        Gauge("kaito:fleet_kv_usage",
              "KV page-pool utilization across the fleet", r,
              labels=("kind", "name", "agg"),
              fn=agg_family({"mean": "kv_mean", "p95": "kv_p95"}))
        Gauge("kaito:fleet_requests_total",
              "Finished requests summed over reporting replicas", r,
              labels=("kind", "name"), fn=family("requests_total"))
        Gauge("kaito:fleet_requests_per_s",
              "Fleet request completion rate", r,
              labels=("kind", "name"), fn=family("requests_rate"))
        Gauge("kaito:fleet_tokens_per_s",
              "Fleet generated-token rate", r,
              labels=("kind", "name"), fn=family("tokens_rate"))
        Gauge("kaito:fleet_shed_per_s",
              "Fleet admission-shed rate (429s)", r,
              labels=("kind", "name"), fn=family("shed_rate"))
        Gauge("kaito:fleet_prefix_hit_rate",
              "Fleet prefix-cache hit ratio (rate-weighted)", r,
              labels=("kind", "name"), fn=family("prefix_hit_rate"))
        Gauge("kaito:fleet_spec_accept_rate",
              "Fleet speculative-decoding accept ratio", r,
              labels=("kind", "name"), fn=family("spec_accept_rate"))
        Gauge("kaito:fleet_slo_burn_max",
              "Worst replica fast-window SLO burn per CR", r,
              labels=("kind", "name"), fn=family("burn_max"))
        Gauge("kaito:fleet_slo_itl_burn_max",
              "Worst replica fast-window ITL p99 burn per CR "
              "(replicas running with --itl)", r,
              labels=("kind", "name"), fn=family("itl_burn_max"))

        def _role_burns():
            out = {}
            with self._lock:
                for k, agg in self._last_agg.items():
                    for field_, v in agg.items():
                        if field_.startswith("role_burn:"):
                            role = field_.split(":", 1)[1]
                            out[(k[0], k[2], role)] = v
            return out

        Gauge("kaito:fleet_slo_role_burn_max",
              "Worst replica fast-window SLO burn per CR and serving "
              "role (prefill/decode/unified)", r,
              labels=("kind", "name", "role"), fn=_role_burns)
        Gauge("kaito:fleet_flight_bundles",
              "Flight-recorder bundles written across reporting "
              "replicas", r,
              labels=("kind", "name"), fn=family("flight_bundles"))
        Gauge("kaito:fleet_host_kv_entries",
              "Host KV offload entries summed over the fleet", r,
              labels=("kind", "name"), fn=family("host_kv_entries"))
        Gauge("kaito:fleet_host_kv_bytes",
              "Host KV offload bytes summed over the fleet", r,
              labels=("kind", "name"), fn=family("host_kv_bytes"))
        Gauge("kaito:fleet_host_kv_evictions_per_s",
              "Fleet host KV offload eviction rate (churn)", r,
              labels=("kind", "name"),
              fn=family("host_kv_evictions_rate"))
        Gauge("kaito:fleet_host_kv_hit_rate",
              "Fleet host KV offload hit ratio (rate-weighted)", r,
              labels=("kind", "name"), fn=family("host_kv_hit_rate"))
        Gauge("kaito:fleet_kv_tier_entries",
              "SSD KV tier entries summed over the fleet", r,
              labels=("kind", "name"), fn=family("kv_tier_entries"))
        Gauge("kaito:fleet_kv_tier_bytes",
              "SSD KV tier bytes summed over the fleet", r,
              labels=("kind", "name"), fn=family("kv_tier_bytes"))
        Gauge("kaito:fleet_kv_tier_hits_per_s",
              "Fleet rate of prefix imports served from the local "
              "host/SSD tiers", r,
              labels=("kind", "name"), fn=family("kv_tier_hits_rate"))
        Gauge("kaito:fleet_kv_tier_spills_per_s",
              "Fleet rate of host-LRU victims demoted to SSD", r,
              labels=("kind", "name"), fn=family("kv_tier_spills_rate"))
        Gauge("kaito:fleet_kv_tier_evictions_per_s",
              "Fleet rate of SSD-tier budget prunes (churn)", r,
              labels=("kind", "name"),
              fn=family("kv_tier_evictions_rate"))
        Gauge("kaito:fleet_adapter_resident",
              "LoRA adapters resident in HBM slots, fleet-wide", r,
              labels=("kind", "name"), fn=family("adapter_resident"))
        Gauge("kaito:fleet_adapter_slots_total",
              "LoRA HBM slot capacity summed over the fleet", r,
              labels=("kind", "name"), fn=family("adapter_slots_total"))
        Gauge("kaito:fleet_adapter_loads_per_s",
              "Fleet adapter hot-load rate (install + host fault-in)", r,
              labels=("kind", "name"), fn=family("adapter_loads_rate"))
        Gauge("kaito:fleet_adapter_evictions_per_s",
              "Fleet adapter slot-eviction rate (churn: slots too "
              "few for the working set)", r,
              labels=("kind", "name"), fn=family("adapter_evictions_rate"))
        Gauge("kaito:fleet_adapter_hits_per_s",
              "Fleet rate of requests served by an already-resident "
              "adapter", r,
              labels=("kind", "name"), fn=family("adapter_hits_rate"))
        Gauge("kaito:fleet_grammar_cache_hit_rate",
              "Fleet grammar compile-cache hit ratio for constrained "
              "requests (rate-weighted)", r,
              labels=("kind", "name"), fn=family("grammar_cache_hit_rate"))
        Gauge("kaito:fleet_prefill_tokens_per_s",
              "Fleet prompt-token prefill rate", r,
              labels=("kind", "name"), fn=family("prefill_tokens_rate"))
        Gauge("kaito:fleet_prefill_dispatches_per_s",
              "Fleet prefill dispatch rate (packed rounds count once)", r,
              labels=("kind", "name"), fn=family("prefill_dispatch_rate"))
        Gauge("kaito:fleet_prefill_pack_mean",
              "Mean sequences per prefill dispatch across the fleet "
              "(1.0 = serial; higher = packing engaged)", r,
              labels=("kind", "name"), fn=family("prefill_pack_mean"))
        Gauge("kaito:fleet_prefill_queue_wait_mean",
              "Mean staged-to-first-prefill-dispatch wait across the "
              "fleet (seconds)", r,
              labels=("kind", "name"), fn=family("prefill_queue_wait_mean"))
        Gauge("kaito:fleet_device_comm_pct",
              "Mean collective share of device wall across replicas "
              "sampling device profiles (engine/devprof.py)", r,
              labels=("kind", "name"), fn=family("device_comm_pct"))
        Gauge("kaito:fleet_device_overlap_pct",
              "Mean share of collective time hidden behind compute "
              "across sampling replicas", r,
              labels=("kind", "name"), fn=family("device_overlap_pct"))
        Gauge("kaito:fleet_device_idle_pct",
              "Mean idle share of device wall across sampling "
              "replicas", r,
              labels=("kind", "name"), fn=family("device_idle_pct"))

        def tenant_family(prefix):
            def _fn():
                out = {}
                with self._lock:
                    for k, agg in self._last_agg.items():
                        for ak, v in agg.items():
                            if ak.startswith(prefix):
                                tenant = ak[len(prefix):]
                                out[(k[0], k[2], tenant)] = v
                return out
            return _fn

        Gauge("kaito:fleet_tenant_served_per_s",
              "Fleet per-tenant completion rate (QoS engines only)", r,
              labels=("kind", "name", "tenant"),
              fn=tenant_family("tenant_served_rate:"))
        Gauge("kaito:fleet_tenant_shed_per_s",
              "Fleet per-tenant admission-shed rate (QoS engines only)",
              r, labels=("kind", "name", "tenant"),
              fn=tenant_family("tenant_shed_rate:"))

        def _states():
            with self._lock:
                return {(k[0], k[2]): SIGNAL_CODE[cr.state]
                        for k, cr in self._crs.items()}

        Gauge("kaito:fleet_signal_state",
              "Scaling signal per CR (0=idle 1=nominal 2=pressure "
              "3=saturated)", r, labels=("kind", "name"), fn=_states)

    def snapshot(self) -> dict:
        """The ``GET /debug/fleet`` payload."""
        now = self.time_fn()
        out: dict = {
            "interval_s": self.interval_s,
            "timeout_s": self.timeout_s,
            "freshness_s": round(self.freshness_s, 3),
            "policy": self.policy.to_dict(),
            "fleet": {},
        }
        with self._lock:
            keys = sorted(self._crs)
        for key in keys:
            with self._lock:
                cr = self._crs.get(key)
                if cr is None:
                    continue
                tmap = dict(self._targets.get(key, {}))
                smap = dict(self._samples.get(key, {}))
                agg = dict(self._last_agg.get(key, {}))
                decision = cr.last_decision
                state, since = cr.state, cr.state_since
                transitions = cr.transitions
                desired = cr.replicas_desired
            replicas = {}
            for url, t in sorted(tmap.items()):
                s = smap.get(url) or ReplicaSample()
                fresh = s.ts > 0 and now - s.ts <= self.freshness_s
                replicas[t.replica] = {
                    "url": url,
                    "role": t.role,
                    "fresh": fresh,
                    "age_s": round(now - s.ts, 3) if s.ts else None,
                    "scrape_seconds": round(s.scrape_seconds, 4),
                    "consecutive_failures": s.consecutive_failures,
                    "last_error": s.last_error,
                    "values": {k: round(v, 6)
                               for k, v in sorted(s.values.items())},
                    "rates": {k: round(v, 6)
                              for k, v in sorted(s.rates.items())},
                }
            kind, ns, name = key
            out["fleet"][f"{kind}/{ns}/{name}"] = {
                "kind": kind, "namespace": ns, "name": name,
                "replicas_desired": desired,
                "replicas_reporting": int(agg.get("replicas_reporting", 0)),
                "replicas": replicas,
                "last": {k: round(v, 6) for k, v in sorted(agg.items())},
                "windows": {
                    "60s": cr.window_stats(60.0),
                    "300s": cr.window_stats(300.0),
                },
                "signal": {
                    "state": state,
                    "since_s": round(now - since, 3),
                    "transitions": transitions,
                    "reason": decision.reason if decision else "",
                    "message": decision.message if decision else "",
                    "drivers": list(decision.drivers) if decision else [],
                    "recommended_replicas":
                        decision.recommended_replicas if decision else 0,
                },
            }
        return out

    # -- background loop -----------------------------------------------

    def start(self) -> None:
        """Run the scrape loop on a daemon thread (ticks every
        ``interval_s / 4`` so staggered phases land close to their due
        time; each tick only polls targets that are actually due)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(max(0.05, self.interval_s / 4.0)):
                try:
                    self.scrape_once(wait=False)
                except Exception:
                    logger.exception("fleet scrape round failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-telemetry")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
