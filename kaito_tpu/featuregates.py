"""Feature gates (parity: ``pkg/featuregates/featuregates.go:17-57``).

Same map-based surface, TPU-relevant names: parse "a=true,b=false"
strings, validate against the known set, expose defaults.
"""

from __future__ import annotations

DEFAULT_GATES: dict[str, bool] = {
    "disableNodeAutoProvisioning": False,
    "gatewayAPIInferenceExtension": False,
    "enableInferenceSetController": True,
    "enableMultiRoleInferenceController": False,
    "modelMirror": False,
    "modelStreaming": False,
    "enableBaseImageAutoUpgrade": False,
    "autoscaler": False,
    "pallasAttention": True,
    "sequenceParallelism": True,
}


def parse_feature_gates(s: str) -> dict[str, bool]:
    gates = dict(DEFAULT_GATES)
    if not s:
        return gates
    for pair in s.split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(f"invalid feature gate {pair!r}, want name=bool")
        name, val = pair.split("=", 1)
        name = name.strip()
        if name not in DEFAULT_GATES:
            raise ValueError(
                f"unknown feature gate {name!r}; known: {sorted(DEFAULT_GATES)}")
        lowered = val.strip().lower()
        if lowered not in ("true", "false"):
            raise ValueError(f"feature gate {name!r} value {val!r} not a bool")
        gates[name] = lowered == "true"
    return gates
