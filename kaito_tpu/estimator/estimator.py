"""HBM capacity estimator: how many TPU chips does a model need?

TPU-native analogue of the reference node estimator
(``pkg/workspace/estimator/nodesestimator/estimator.go:70``
EstimateNodeCount and the formula doc
``presets/workspace/generator/model-sku-calculation.md``).  The
reference computes a per-GPU memory budget
``gpuMem*0.84 - (2.3GiB + maxModelLen*bytesPerToken/gpuCount)`` and
divides expanded weights by it; we do the same accounting against a
chip's HBM, with TPU-appropriate constants, and round the answer up to
a *valid slice topology* instead of a VM count.

Differences from the reference, by design:

- XLA preallocates and manages HBM without torch/CUDA fragmentation, so
  the utilization cap is higher (0.92 vs 0.84).
- The fixed overhead covers the XLA runtime + compiled executables +
  collective scratch, not CUDA context + torch allocator slack.
- The answer is a topology (``"4x4"``) because TPUs provision in slice
  shapes, not node counts.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

from kaito_tpu.models.metadata import ModelMetadata
from kaito_tpu.sku.catalog import TPUChipSpec, topology_chips

GiB = 2**30

# TPU estimator constants (counterparts of estimator.go:34-59).
HBM_UTILIZATION = 0.92          # fraction of HBM the engine may plan for
WEIGHT_EXPANSION = 1.02         # loaded weights vs on-disk size
PER_CHIP_OVERHEAD_BYTES = int(1.25 * GiB)  # XLA runtime + programs + scratch
WEIGHT_OVERHEAD_FACTOR = 0.03   # proportional slack (buffers, donation gaps)

# Bytes per weight for supported quantization schemes.  Served int4
# (engine/quant.py) is packed nibbles + fp32 per-group scales at
# g=128: 0.5 + 4/128 = 0.53125 — same density as mxfp4's 4.25
# bits/weight, by coincidence of constants.
_QUANT_BYTES = {"": 2.0, "bf16": 2.0, "fp16": 2.0, "int8": 1.0, "fp8": 1.0,
                "mxfp4": 0.53125, "int4": 0.53125}


def weight_bytes(md: ModelMetadata, quantization: Optional[str] = None) -> int:
    """Loaded-weight bytes including expansion factor."""
    quant = md.quantization if quantization is None else quantization
    per_weight = _QUANT_BYTES.get(quant.lower(), 2.0)
    params = md.arch.param_count()
    return int(params * per_weight * WEIGHT_EXPANSION * (1 + WEIGHT_OVERHEAD_FACTOR))


@dataclass(frozen=True)
class SliceEstimate:
    """Result of sizing a model onto a chip generation."""

    chip: TPUChipSpec
    topology: str
    num_chips: int
    weights_bytes: int            # total, all chips
    kv_bytes_per_token: int       # all layers, un-sharded
    per_chip_budget: int          # usable HBM per chip
    kv_budget_bytes: int          # slice-wide bytes left for KV cache
    max_kv_tokens: int            # total KV tokens the slice can hold

    @property
    def per_chip_weights(self) -> int:
        return self.weights_bytes // max(self.num_chips, 1)


def _per_chip_budget(chip: TPUChipSpec) -> int:
    return int(chip.hbm_bytes * HBM_UTILIZATION) - PER_CHIP_OVERHEAD_BYTES


def estimate_chip_count(
    md: ModelMetadata,
    chip: TPUChipSpec,
    *,
    max_model_len: Optional[int] = None,
    kv_dtype_bytes: int = 2,
    quantization: Optional[str] = None,
) -> int:
    """Minimum chips such that weights (sharded) plus the KV cache of at
    least one max-length sequence fit (reference requirement:
    ``estimator.go:153`` — a GPU must hold its weight shard AND its share
    of one full-context KV)."""
    budget = _per_chip_budget(chip)
    if budget <= 0:
        raise ValueError(f"chip {chip.generation} has no usable HBM budget")
    w = weight_bytes(md, quantization)
    ctx = max_model_len or md.max_model_len
    kv_one_seq = ctx * md.kv_bytes_per_token(kv_dtype_bytes)
    chips = math.ceil((w + kv_one_seq) / budget)
    return max(chips, 1)


def estimate_slice(
    md: ModelMetadata,
    chip: TPUChipSpec,
    *,
    max_model_len: Optional[int] = None,
    kv_dtype_bytes: int = 2,
    quantization: Optional[str] = None,
    min_chips: int = 1,
) -> SliceEstimate:
    """Size the model onto the smallest valid slice topology of ``chip``.

    Raises if no topology of this generation can hold the model (the
    reference errors when a model cannot distribute; we do the same
    rather than silently spilling to host memory).
    """
    need = max(min_chips, estimate_chip_count(
        md, chip, max_model_len=max_model_len,
        kv_dtype_bytes=kv_dtype_bytes, quantization=quantization))
    topology = chip.topology_for_chips(need)
    if topology is None:
        raise ValueError(
            f"model {md.name!r} needs {need} {chip.generation} chips; largest "
            f"valid slice is {chip.valid_topologies[-1]} "
            f"({topology_chips(chip.valid_topologies[-1])} chips)"
        )
    n = topology_chips(topology)
    budget = _per_chip_budget(chip)
    w = weight_bytes(md, quantization)
    kv_budget = n * budget - w
    bpt = md.kv_bytes_per_token(kv_dtype_bytes)
    return SliceEstimate(
        chip=chip,
        topology=topology,
        num_chips=n,
        weights_bytes=w,
        kv_bytes_per_token=bpt,
        per_chip_budget=budget,
        kv_budget_bytes=max(kv_budget, 0),
        max_kv_tokens=max(kv_budget, 0) // bpt if bpt else 0,
    )


def max_kv_tokens(
    md: ModelMetadata,
    chip: TPUChipSpec,
    num_chips: int,
    *,
    kv_dtype_bytes: int = 2,
    quantization: Optional[str] = None,
) -> int:
    """KV token capacity of a given chip count (drives the engine's page
    pool size and the benchmark probe's concurrency derivation, the way
    the reference reads vLLM's KV-capacity gauges)."""
    budget = num_chips * _per_chip_budget(chip) - weight_bytes(md, quantization)
    bpt = md.kv_bytes_per_token(kv_dtype_bytes)
    return max(budget, 0) // bpt if bpt else 0
