from kaito_tpu.estimator.estimator import (  # noqa: F401
    HBM_UTILIZATION,
    WEIGHT_EXPANSION,
    PER_CHIP_OVERHEAD_BYTES,
    SliceEstimate,
    estimate_chip_count,
    estimate_slice,
    max_kv_tokens,
    weight_bytes,
)
