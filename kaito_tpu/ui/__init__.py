"""Demo chat UI: a dependency-free frontend for a served Workspace.

The counterpart of the reference's DemoUI chart
(``charts/DemoUI/inference`` — a Chainlit pod pointed at the workspace
service URL): here one stdlib HTTP server ships an embedded chat page
and proxies ``/v1/*`` to the workspace service, so the browser never
needs CORS and the pod needs no pip installs (zero-egress clusters).

Run: ``python -m kaito_tpu.ui --backend http://<ws>.<ns>.svc:5000``.
The engine server also mounts the same page at ``/ui`` for single-pod
demos.
"""

from __future__ import annotations

import argparse
import json
import logging
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>kaito-tpu chat</title>
<style>
 body{font-family:system-ui,sans-serif;max-width:760px;margin:2rem auto;
      padding:0 1rem;background:#111;color:#eee}
 h1{font-size:1.1rem;color:#9cf}
 #log{border:1px solid #333;border-radius:8px;padding:1rem;min-height:300px;
      white-space:pre-wrap}
 .u{color:#9cf;margin:.5rem 0 .2rem}
 .a{color:#dfd;margin:.2rem 0 .8rem}
 form{display:flex;gap:.5rem;margin-top:1rem}
 input{flex:1;padding:.6rem;border-radius:6px;border:1px solid #444;
       background:#1a1a1a;color:#eee}
 button{padding:.6rem 1.2rem;border-radius:6px;border:0;background:#247;
        color:#fff;cursor:pointer}
</style></head><body>
<h1>kaito-tpu &mdash; chat demo</h1>
<div id="log"></div>
<form id="f"><input id="q" placeholder="Ask something" autofocus>
<button>Send</button></form>
<script>
const log = document.getElementById("log");
const messages = [];
document.getElementById("f").addEventListener("submit", async (e) => {
  e.preventDefault();
  const q = document.getElementById("q");
  const text = q.value.trim();
  if (!text) return;
  q.value = "";
  messages.push({role: "user", content: text});
  log.insertAdjacentHTML("beforeend",
    `<div class="u">you: ${text.replace(/</g, "&lt;")}</div>`);
  const out = document.createElement("div");
  out.className = "a";
  out.textContent = "assistant: ";
  log.appendChild(out);
  let acc = "";
  try {
    const resp = await fetch("/v1/chat/completions", {
      method: "POST", headers: {"Content-Type": "application/json"},
      body: JSON.stringify({messages, stream: true, max_tokens: 512}),
    });
    if (!resp.ok) {
      const err = await resp.text();
      out.textContent = `error ${resp.status}: ${err.slice(0, 300)}`;
      messages.pop();            // don't replay the failed turn
      return;
    }
    const reader = resp.body.getReader();
    const dec = new TextDecoder();
    let buf = "";
    while (true) {
      const {done, value} = await reader.read();
      if (done) break;
      buf += dec.decode(value, {stream: true});
      const lines = buf.split("\\n");
      buf = lines.pop();         // keep the incomplete tail unparsed
      for (const line of lines) {
        if (!line.startsWith("data: ") || line.includes("[DONE]")) continue;
        try {
          const delta = JSON.parse(line.slice(6)).choices[0].delta;
          if (delta.content) { acc += delta.content; out.textContent =
            "assistant: " + acc; }
        } catch {}
      }
    }
  } catch (err) {
    out.textContent = `error: ${err}`;
    messages.pop();
    return;
  }
  messages.push({role: "assistant", content: acc});
  window.scrollTo(0, document.body.scrollHeight);
});
</script></body></html>"""


def serve_page(handler: BaseHTTPRequestHandler) -> None:
    """Write the chat page on any stdlib handler (shared by the
    standalone proxy and the engine server's /ui route)."""
    body = PAGE.encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "text/html; charset=utf-8")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def make_handler(backend: str):
    class UIHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path in ("/", "/ui", "/ui/"):
                return serve_page(self)
            if self.path == "/health":
                body = b'{"status": "ok"}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def do_POST(self):
            if not self.path.lstrip("/").startswith("v1/"):
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            n = int(self.headers.get("Content-Length", "0"))
            payload = self.rfile.read(n)
            req = urllib.request.Request(
                backend.rstrip("/") + "/" + self.path.lstrip("/"),
                data=payload,
                headers={"Content-Type": "application/json"})
            try:
                upstream = urllib.request.urlopen(req, timeout=600)
            except urllib.error.HTTPError as e:
                upstream = e
            except urllib.error.URLError as e:
                # backend down/restarting: a clean 502 the page can
                # show, not a dropped socket
                body = json.dumps({"error": {
                    "message": f"workspace backend unreachable: "
                               f"{e.reason}", "type": "bad_gateway"}}
                ).encode()
                self.send_response(502)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self.send_response(upstream.status)
            ctype = upstream.headers.get("Content-Type",
                                         "application/json")
            self.send_header("Content-Type", ctype)
            if "text/event-stream" in ctype:
                # forward whatever is available NOW (read1) — a full
                # read(4096) would batch the SSE tokens into bursts
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                while True:
                    chunk = upstream.read1(4096)
                    if not chunk:
                        break
                    self.wfile.write(f"{len(chunk):x}\r\n".encode()
                                     + chunk + b"\r\n")
                self.wfile.write(b"0\r\n\r\n")
            else:
                body = upstream.read()
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

    return UIHandler


def make_server(backend: str, host: str = "0.0.0.0",
                port: int = 8000) -> ThreadingHTTPServer:
    return ThreadingHTTPServer((host, port), make_handler(backend))


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kaito-tpu-ui")
    ap.add_argument("--backend", required=True,
                    help="workspace service URL, e.g. "
                         "http://ws.default.svc.cluster.local:5000")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    srv = make_server(args.backend, args.host, args.port)
    logger.info("demo UI on %s:%d -> %s", args.host, args.port, args.backend)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
