from kaito_tpu.ui import main

main()
