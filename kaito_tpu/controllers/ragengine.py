"""RAGEngine reconciler.

Parity: ``pkg/ragengine/controllers/ragengine_controller.go:82`` +
``preset_rag.go:198`` — provision optional compute, render the RAG
service Deployment (env vars carry embedding/LLM/vector-DB config) +
Service, guardrails ConfigMap volume, conditions.
"""

from __future__ import annotations

from kaito_tpu.api.meta import Condition, ObjectMeta, set_condition
from kaito_tpu.api.ragengine import (
    COND_RAG_RESOURCE_READY,
    COND_RAG_SERVICE_READY,
    RAGEngine,
)
from kaito_tpu.controllers.objects import Unstructured
from kaito_tpu.controllers.runtime import Reconciler, Result, update_with_retry
from kaito_tpu.manifests.core import generate_service

LABEL_RAGENGINE = "kaito-tpu.io/ragengine"


def rag_env(rag: RAGEngine) -> list[dict]:
    """Env contract consumed by kaito_tpu.rag.app (reference:
    pkg/ragengine/manifests/manifests.go:155 env block + config.py)."""
    s = rag.spec
    env = [
        {"name": "LLM_INFERENCE_URL", "value": s.inference_service.url},
        {"name": "LLM_CONTEXT_WINDOW",
         "value": str(s.inference_service.context_window_size or 0)},
        {"name": "VECTOR_DB_ENGINE", "value": s.storage.vector_db.engine},
        {"name": "VECTOR_DB_URL", "value": s.storage.vector_db.url},
    ]
    if s.embedding.local is not None:
        env.append({"name": "EMBEDDING_MODEL_ID",
                    "value": s.embedding.local.model_id})
    if s.embedding.remote is not None:
        env.append({"name": "REMOTE_EMBEDDING_URL",
                    "value": s.embedding.remote.url})
    if s.guardrails.enabled:
        env.append({"name": "GUARDRAILS_POLICY_FILE",
                    "value": "/mnt/guardrails/policy.yaml"})
    return env


def generate_rag_deployment(rag: RAGEngine) -> Unstructured:
    labels = {LABEL_RAGENGINE: rag.metadata.name}
    volumes, mounts = [], []
    if rag.spec.guardrails.enabled and rag.spec.guardrails.config_map_ref:
        volumes.append({"name": "guardrails",
                        "configMap": {"name": rag.spec.guardrails.config_map_ref}})
        mounts.append({"name": "guardrails", "mountPath": "/mnt/guardrails"})
    resources = {}
    if rag.spec.embedding.local is not None:
        # local embedding model runs on one TPU chip (north-star item)
        resources = {"requests": {"google.com/tpu": "1"},
                     "limits": {"google.com/tpu": "1"}}
    return Unstructured(
        "Deployment",
        ObjectMeta(name=rag.metadata.name, namespace=rag.metadata.namespace,
                   labels=labels,
                   owner_references=[{"kind": "RAGEngine",
                                      "name": rag.metadata.name}]),
        spec={
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [{
                        "name": "rag",
                        "image": "ghcr.io/kaito-tpu/rag:latest",
                        "command": ["python", "-m", "kaito_tpu.rag.app",
                                    "--port", "5000"],
                        "env": rag_env(rag),
                        "ports": [{"containerPort": 5000}],
                        "volumeMounts": mounts,
                        "resources": resources,
                        "readinessProbe": {
                            "httpGet": {"path": "/health", "port": 5000}},
                    }],
                    "volumes": volumes,
                },
            },
        })


class RAGEngineReconciler(Reconciler):
    kind = "RAGEngine"

    def reconcile(self, rag: RAGEngine) -> Result:
        if rag.metadata.deletion_timestamp:
            return Result()
        rag.default()
        errs = rag.validate()
        if errs:
            self._set_cond(rag, COND_RAG_RESOURCE_READY, "False",
                           "ValidationFailed", "; ".join(errs))
            return Result()
        self._set_cond(rag, COND_RAG_RESOURCE_READY, "True", "Ready", "")

        dep = generate_rag_deployment(rag)
        if self.store.try_get("Deployment", rag.metadata.namespace,
                              dep.metadata.name) is None:
            self.store.create(dep)
        svc_name = rag.metadata.name
        if self.store.try_get("Service", rag.metadata.namespace, svc_name) is None:
            self.store.create(generate_service(
                svc_name, rag.metadata.namespace,
                {LABEL_RAGENGINE: rag.metadata.name}))

        live = self.store.get("Deployment", rag.metadata.namespace,
                              dep.metadata.name)
        ready = live.status.get("readyReplicas", 0) >= 1
        self._set_cond(rag, COND_RAG_SERVICE_READY,
                       "True" if ready else "False",
                       "Ready" if ready else "Pending", "")
        return Result() if ready else Result(requeue_after=5.0)

    def _set_cond(self, rag, type_, status, reason, message):
        def mutate(o):
            set_condition(o.status.conditions, Condition(
                type=type_, status=status, reason=reason, message=message))
        update_with_retry(self.store, "RAGEngine", rag.metadata.namespace,
                          rag.metadata.name, mutate)
