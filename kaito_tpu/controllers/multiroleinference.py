"""MultiRoleInference reconciler: prefill/decode disaggregation.

Parity: ``pkg/controllers/multiroleinference/controller.go:404-720`` —
one InferenceSet per role (shared served-model-name, role labels), a
default endpoint-picker plugin config for PD-aware routing, an
InferencePool per MRI, readiness aggregated across roles.

TPU-native KV hand-off: prefill pods publish KV pages for a request;
the decode pod pulls them over DCN/host-DMA (kaito_tpu.engine.pd);
the EPP routes a request's decode phase to the replica that already
holds its KV.
"""

from __future__ import annotations

from kaito_tpu.api.inferenceset import (
    InferenceSet,
    InferenceSetSpec,
    WorkspaceTemplate,
)
from kaito_tpu.api.meta import Condition, ObjectMeta, set_condition
from kaito_tpu.api.multiroleinference import (
    ROLE_DECODE,
    ROLE_PREFILL,
    MultiRoleInference,
)
from kaito_tpu.api.workspace import InferenceSpec, ResourceSpec
from kaito_tpu.controllers.inferenceset import COND_SET_READY
from kaito_tpu.controllers.objects import Unstructured
from kaito_tpu.controllers.runtime import Reconciler, Result, Store, update_with_retry

LABEL_MRI = "kaito-tpu.io/multirole-inference"
LABEL_ROLE = "kaito-tpu.io/inference-role"

COND_MRI_READY = "MultiRoleInferenceReady"


def default_pd_plugins_config() -> dict:
    """EPP plugin chain for PD-aware routing (reference:
    defaultPDPluginsConfig, controller.go:566): prefill/decode filter +
    KV-locality scorer + queue-depth scorer."""
    return {
        "plugins": [
            {"type": "pd-filter"},
            {"type": "kv-locality-scorer", "weight": 2},
            {"type": "queue-depth-scorer", "weight": 1},
        ],
    }


class MultiRoleInferenceReconciler(Reconciler):
    kind = "MultiRoleInference"

    def reconcile(self, mri: MultiRoleInference) -> Result:
        if mri.metadata.deletion_timestamp:
            for iset in self.store.list(
                    "InferenceSet", mri.metadata.namespace,
                    labels={LABEL_MRI: mri.metadata.name}):
                self.store.delete("InferenceSet", iset.metadata.namespace,
                                  iset.metadata.name)
            return Result()
        mri.default()
        errs = mri.validate()
        if errs:
            self._set_cond(mri, COND_MRI_READY, "False", "ValidationFailed",
                           "; ".join(errs))
            return Result()

        all_ready = True
        for role in mri.spec.roles:
            iset = self._ensure_role_set(mri, role)
            ready = (iset.status.ready_replicas >= role.replicas)
            all_ready &= ready

            def set_role(o, rt=role.type, rd=ready):
                o.status.role_ready[rt] = rd
            update_with_retry(self.store, "MultiRoleInference",
                              mri.metadata.namespace, mri.metadata.name,
                              set_role)

        self._ensure_inference_pool(mri)
        self._ensure_epp(mri)
        self._set_cond(mri, COND_MRI_READY,
                       "True" if all_ready else "False",
                       "Ready" if all_ready else "RolesPending",
                       "")
        return Result() if all_ready else Result(requeue_after=5.0)

    def _ensure_role_set(self, mri: MultiRoleInference, role) -> InferenceSet:
        name = f"{mri.metadata.name}-{role.type}"
        existing = self.store.try_get("InferenceSet", mri.metadata.namespace, name)
        if existing is not None:
            if existing.spec.replicas != role.replicas:
                def scale(o):
                    o.spec.replicas = role.replicas
                existing = update_with_retry(
                    self.store, "InferenceSet", mri.metadata.namespace, name, scale)
            return existing
        # role runtime config rides the engine config surface; decode
        # pods get the routing sidecar / KV-pull env via role labels
        iset = InferenceSet(
            ObjectMeta(name=name, namespace=mri.metadata.namespace,
                       labels={LABEL_MRI: mri.metadata.name,
                               LABEL_ROLE: role.type},
                       owner_references=[{"kind": "MultiRoleInference",
                                          "name": mri.metadata.name,
                                          "uid": mri.metadata.uid}]),
            InferenceSetSpec(
                replicas=role.replicas,
                template=WorkspaceTemplate(
                    resource=ResourceSpec(instance_type=role.instance_type,
                                          tpu_topology=role.tpu_topology),
                    inference=InferenceSpec(preset=mri.spec.model.name),
                    labels={LABEL_MRI: mri.metadata.name, LABEL_ROLE: role.type},
                    annotations={"kaito-tpu.io/inference-role": role.type},
                )))
        return self.store.create(iset)

    def _ensure_inference_pool(self, mri: MultiRoleInference) -> None:
        name = f"{mri.metadata.name}-pool"
        if self.store.try_get("InferencePool", mri.metadata.namespace, name):
            return
        plugins = mri.spec.epp_plugins_config or default_pd_plugins_config()
        self.store.create(Unstructured(
            "InferencePool",
            ObjectMeta(name=name, namespace=mri.metadata.namespace,
                       owner_references=[{"kind": "MultiRoleInference",
                                          "name": mri.metadata.name}]),
            spec={
                "targetPortNumber": 5000,
                "selector": {LABEL_MRI: mri.metadata.name},
                "extensionRef": {"name": f"{mri.metadata.name}-epp"},
                "eppPluginsConfig": plugins,
            }))

    def _ensure_epp(self, mri: MultiRoleInference) -> None:
        """Render the PD-aware endpoint picker the pool's extensionRef
        names: backend specs carry ``=role/group`` so the picker's
        pd-filter and kv-locality-scorer can steer decode requests to
        the prefill-owning replica group (docs/routing.md)."""
        from kaito_tpu.api.workspace import LABEL_CREATED_BY_INFERENCESET
        from kaito_tpu.manifests.epp import EPP_PORT, generate_epp_workload

        ns = mri.metadata.namespace
        backends = []
        for ws in self.store.list("Workspace", ns,
                                  labels={LABEL_MRI: mri.metadata.name}):
            role = ws.metadata.labels.get(LABEL_ROLE, "")
            group = ws.metadata.labels.get(LABEL_CREATED_BY_INFERENCESET, "")
            backends.append(
                f"http://{ws.metadata.name}:{EPP_PORT}={role}/{group}")
        backends.sort()
        plugins = mri.spec.epp_plugins_config or default_pd_plugins_config()
        objs = generate_epp_workload(
            f"{mri.metadata.name}-epp", ns, backends=backends,
            plugins_config=plugins,
            owner={"kind": "MultiRoleInference", "name": mri.metadata.name})
        for obj in objs:
            existing = self.store.try_get(obj.kind, ns, obj.metadata.name)
            if existing is None:
                self.store.create(obj)
            elif (obj.kind == "Deployment"
                  and existing.spec["template"]["spec"]["containers"][0]
                  ["command"]
                  != obj.spec["template"]["spec"]["containers"][0]
                  ["command"]):
                existing.spec = obj.spec
                self.store.update(existing)

    def _set_cond(self, mri, type_, status, reason, message):
        def mutate(o):
            set_condition(o.status.conditions, Condition(
                type=type_, status=status, reason=reason, message=message))
        update_with_retry(self.store, "MultiRoleInference",
                          mri.metadata.namespace, mri.metadata.name, mutate)
