"""Generic (unstructured) cluster objects: Nodes, NodePools,
StatefulSets, Services, Jobs — anything that isn't one of our typed
kinds lives in the store as an Unstructured with a YAML-shaped payload.
"""

from __future__ import annotations

from typing import Optional

from kaito_tpu.api.meta import KaitoObject, ObjectMeta


class Unstructured(KaitoObject):
    def __init__(self, kind: str, meta: ObjectMeta,
                 spec: Optional[dict] = None, status: Optional[dict] = None):
        self.kind = kind
        super().__init__(meta)
        self.spec = spec or {}
        self.status = status or {}

    def to_dict(self) -> dict:
        return {
            "apiVersion": _API_VERSIONS.get(self.kind, "v1"),
            "kind": self.kind,
            "metadata": {
                "name": self.metadata.name,
                "namespace": self.metadata.namespace,
                "labels": dict(self.metadata.labels),
                "annotations": dict(self.metadata.annotations),
            },
            "spec": self.spec,
        }


_API_VERSIONS = {
    "Node": "v1",
    "Service": "v1",
    "ConfigMap": "v1",
    "StatefulSet": "apps/v1",
    "Deployment": "apps/v1",
    "ControllerRevision": "apps/v1",
    "Job": "batch/v1",
    "NodePool": "karpenter.sh/v1",
    "NodeClaim": "karpenter.sh/v1",
    "PersistentVolumeClaim": "v1",
    "InferencePool": "inference.networking.x-k8s.io/v1",
}


def node(name: str, labels: dict, ready: bool = True) -> Unstructured:
    return Unstructured(
        "Node", ObjectMeta(name=name, namespace="", labels=dict(labels)),
        status={"ready": ready})


def is_node_ready(n: Unstructured) -> bool:
    return bool(n.status.get("ready"))
