"""Controller runtime: typed object store, reconcilers, revisions,
expectations.

The in-process equivalent of the reference's controller-runtime usage
plus its test fakes (``pkg/utils/test/mock_client.go:34``), designed the
way SURVEY.md §4 says the reference should have been: the SAME store
backs production reconciliation loops and tests, so multi-component
behavior (workspace → provisioner → nodes → statefulset → status) is
exercisable end-to-end without a cluster.  A real-cluster backend can
implement Store against the k8s API 1:1.
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
import threading
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from kaito_tpu.api.meta import KaitoObject, ObjectMeta, now_iso

logger = logging.getLogger(__name__)


class ConflictError(Exception):
    """Optimistic-concurrency conflict (stale resourceVersion)."""


class NotFoundError(Exception):
    pass


class Store:
    """Namespaced typed object store with resourceVersion semantics and
    watch callbacks."""

    def __init__(self):
        self._objects: dict[str, dict[tuple[str, str], KaitoObject]] = defaultdict(dict)
        self._lock = threading.RLock()
        self._rv = 0
        self._watchers: list[Callable[[str, str, KaitoObject], None]] = []
        self._uid = 0
        # in-memory Event sink (k8s/events.py): reconcilers record
        # operator-visible transitions here; tests and the fake store
        # read them back.  Imported lazily — k8s.store imports this
        # module, so a top-level import would cycle.
        from kaito_tpu.k8s.events import EventRecorder

        self.events = EventRecorder()

    # -- CRUD ----------------------------------------------------------

    def create(self, obj: KaitoObject) -> KaitoObject:
        with self._lock:
            kind = obj.kind
            key = obj.metadata.key
            if key in self._objects[kind]:
                raise ConflictError(f"{kind} {key} already exists")
            self._rv += 1
            self._uid += 1
            obj.metadata.resource_version = self._rv
            obj.metadata.uid = obj.metadata.uid or f"uid-{self._uid}"
            stored = obj.deepcopy()
            self._objects[kind][key] = stored
            self._notify("ADDED", kind, stored)
            return stored.deepcopy()

    def get(self, kind: str, namespace: str, name: str) -> KaitoObject:
        with self._lock:
            obj = self._objects[kind].get((namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return obj.deepcopy()

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[KaitoObject]:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             labels: Optional[dict[str, str]] = None) -> list[KaitoObject]:
        with self._lock:
            out = []
            for (ns, _), obj in self._objects[kind].items():
                if namespace is not None and ns != namespace:
                    continue
                if labels and any(obj.metadata.labels.get(k) != v
                                  for k, v in labels.items()):
                    continue
                out.append(obj.deepcopy())
            return sorted(out, key=lambda o: o.metadata.name)

    def update(self, obj: KaitoObject) -> KaitoObject:
        with self._lock:
            kind, key = obj.kind, obj.metadata.key
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key} not found")
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key}: stale resourceVersion "
                    f"{obj.metadata.resource_version} != {current.metadata.resource_version}")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            stored = obj.deepcopy()
            self._objects[kind][key] = stored
            self._notify("MODIFIED", kind, stored)
            # finalizer-aware deletion completion
            if stored.metadata.deletion_timestamp and not stored.metadata.finalizers:
                del self._objects[kind][key]
                self._notify("DELETED", kind, stored)
            return stored.deepcopy()

    def delete(self, kind: str, namespace: str, name: str) -> None:
        """Mark deleted; object lingers until finalizers clear."""
        with self._lock:
            obj = self._objects[kind].get((namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            if obj.metadata.finalizers:
                if not obj.metadata.deletion_timestamp:
                    obj.metadata.deletion_timestamp = now_iso()
                    self._rv += 1
                    obj.metadata.resource_version = self._rv
                    self._notify("MODIFIED", kind, obj.deepcopy())
                return
            del self._objects[kind][(namespace, name)]
            self._notify("DELETED", kind, obj.deepcopy())

    # -- watch ---------------------------------------------------------

    def watch(self, fn: Callable[[str, str, KaitoObject], None]) -> None:
        self._watchers.append(fn)

    def _notify(self, event: str, kind: str, obj: KaitoObject) -> None:
        for fn in list(self._watchers):
            try:
                fn(event, kind, obj)
            except Exception:
                logger.exception("watch callback failed")


def update_with_retry(store: Store, kind: str, namespace: str, name: str,
                      mutate: Callable[[KaitoObject], None],
                      attempts: int = 5) -> KaitoObject:
    """Optimistic-concurrency retry loop (reference:
    ``pkg/utils/workspace/workspace.go`` UpdateWorkspaceWithRetry)."""
    last: Exception = RuntimeError("no attempts")
    for _ in range(attempts):
        obj = store.get(kind, namespace, name)
        mutate(obj)
        try:
            return store.update(obj)
        except ConflictError as e:
            last = e
    raise last


# ---------------------------------------------------------------------------
# ControllerRevision (reference: workspace_controller.go:384-494)
# ---------------------------------------------------------------------------

MAX_REVISION_HISTORY = 10


@dataclass
class ControllerRevision(KaitoObject):
    kind = "ControllerRevision"

    def __init__(self, meta: ObjectMeta, data: dict, revision: int):
        super().__init__(meta)
        self.data = data
        self.revision = revision


def hash_spec(payload: dict) -> str:
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def sync_controller_revision(store: Store, owner: KaitoObject,
                             payload: dict) -> ControllerRevision:
    """Record the owner's spec as a numbered revision; dedupe on hash;
    prune history beyond MAX_REVISION_HISTORY."""
    h = hash_spec(payload)
    ns = owner.metadata.namespace
    prefix = f"{owner.metadata.name}-rev-"
    revisions = [r for r in store.list("ControllerRevision", ns)
                 if r.metadata.name.startswith(prefix)]
    revisions.sort(key=lambda r: r.revision)
    if revisions and revisions[-1].data.get("hash") == h:
        return revisions[-1]
    next_num = (revisions[-1].revision + 1) if revisions else 1
    rev = ControllerRevision(
        ObjectMeta(name=f"{prefix}{next_num}", namespace=ns,
                   labels={"kaito-tpu.io/owner": owner.metadata.name}),
        data={"hash": h, "payload": payload},
        revision=next_num)
    store.create(rev)
    for old in revisions[: max(0, len(revisions) + 1 - MAX_REVISION_HISTORY)]:
        store.delete("ControllerRevision", ns, old.metadata.name)
    return rev


# ---------------------------------------------------------------------------
# Expectations (reference: pkg/utils/controller.go:86-242)
# ---------------------------------------------------------------------------

class Expectations:
    """Guards replica managers against stale-cache over-creation: a
    controller records how many creates/deletes it issued and skips
    resync until the watch events arrive."""

    def __init__(self):
        self._adds: dict[str, int] = defaultdict(int)
        self._dels: dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            self._adds[key] += n

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            self._dels[key] += n

    def creation_observed(self, key: str) -> None:
        with self._lock:
            if self._adds[key] > 0:
                self._adds[key] -= 1

    def deletion_observed(self, key: str) -> None:
        with self._lock:
            if self._dels[key] > 0:
                self._dels[key] -= 1

    def satisfied(self, key: str) -> bool:
        with self._lock:
            return self._adds[key] <= 0 and self._dels[key] <= 0

    def clear(self, key: str) -> None:
        with self._lock:
            self._adds.pop(key, None)
            self._dels.pop(key, None)


# ---------------------------------------------------------------------------
# Reconciler driver
# ---------------------------------------------------------------------------

@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0


class Reconciler:
    """Base reconciler: subclasses implement reconcile(obj) -> Result."""

    kind: str = ""

    def __init__(self, store: Store):
        self.store = store

    def reconcile(self, obj: KaitoObject) -> Result:
        raise NotImplementedError

    def reconcile_key(self, namespace: str, name: str) -> Result:
        obj = self.store.try_get(self.kind, namespace, name)
        if obj is None:
            return Result()
        return self.reconcile(obj)

    def reconcile_all(self, max_passes: int = 10) -> None:
        """Drive reconciliation to a fixed point (test/dev harness; the
        production manager wires watch events into a workqueue)."""
        for _ in range(max_passes):
            requeued = False
            for obj in self.store.list(self.kind):
                res = self.reconcile(obj)
                requeued |= res.requeue or res.requeue_after > 0
            if not requeued:
                return
