"""ModelMirror reconciler: cluster-scoped weight cache.

Parity: ``pkg/modelmirror/controllers/modelmirror_controller.go:60-345``
— managed mode ensures shared storage (GKE: Filestore RWX PVC or a GCS
bucket) and a download Job that fetches the model into it, tracking
Pending → Downloading → Ready; static mode trusts pre-seeded storage.
"""

from __future__ import annotations

from kaito_tpu.api.meta import Condition, ObjectMeta, set_condition
from kaito_tpu.api.modelmirror import (
    PHASE_DOWNLOADING,
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_READY,
    ModelMirror,
)
from kaito_tpu.controllers.objects import Unstructured
from kaito_tpu.controllers.runtime import Reconciler, Result, update_with_retry
from kaito_tpu.k8s.events import record_event

MIRROR_NAMESPACE = "kaito-tpu-system"


def generate_download_job(mirror: ModelMirror) -> Unstructured:
    """Weight-fetch Job (reference: pkg/modelmirror/download/job.go:33,
    hf-transfer into the PVC; ours prefers GCS via gsutil when a bucket
    is configured)."""
    src = mirror.spec.source
    if mirror.spec.storage.bucket:
        cmd = (f"python -m kaito_tpu.runtime.weight_fetch "
               f"--model-id {src.model_id} "
               f"--dest gs://{mirror.spec.storage.bucket}/{src.model_id}")
    else:
        cmd = (f"python -m kaito_tpu.runtime.weight_fetch "
               f"--model-id {src.model_id} --dest /mnt/models/{src.model_id}")
    spec = {
        "backoffLimit": 3,
        "template": {"spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": "downloader",
                "image": "ghcr.io/kaito-tpu/engine:latest",
                "command": ["sh", "-c", cmd],
                "volumeMounts": [] if mirror.spec.storage.bucket else
                [{"name": "models", "mountPath": "/mnt/models"}],
            }],
            "volumes": [] if mirror.spec.storage.bucket else
            [{"name": "models", "persistentVolumeClaim":
              {"claimName": f"{mirror.metadata.name}-models"}}],
        }},
    }
    return Unstructured(
        "Job", ObjectMeta(name=f"{mirror.metadata.name}-download",
                          namespace=MIRROR_NAMESPACE),
        spec=spec)


class ModelMirrorReconciler(Reconciler):
    kind = "ModelMirror"

    def reconcile(self, mirror: ModelMirror) -> Result:
        if mirror.metadata.deletion_timestamp:
            return Result()
        mirror.default()
        errs = mirror.validate()
        if errs:
            self._set_phase(mirror, PHASE_FAILED, "; ".join(errs))
            return Result()

        if mirror.spec.mode == "static":
            self._set_phase(mirror, PHASE_READY, "static storage trusted")
            return Result()

        # managed: ensure RWX PVC unless a bucket is used
        if not mirror.spec.storage.bucket:
            pvc_name = f"{mirror.metadata.name}-models"
            if self.store.try_get("PersistentVolumeClaim", MIRROR_NAMESPACE,
                                  pvc_name) is None:
                self.store.create(Unstructured(
                    "PersistentVolumeClaim",
                    ObjectMeta(name=pvc_name, namespace=MIRROR_NAMESPACE),
                    spec={"accessModes": ["ReadWriteMany"],
                          "storageClassName":
                          mirror.spec.storage.storage_class_name or "filestore-rwx",
                          "resources": {"requests":
                                        {"storage": mirror.spec.storage.size}}}))

        job_name = f"{mirror.metadata.name}-download"
        job = self.store.try_get("Job", MIRROR_NAMESPACE, job_name)
        if job is None:
            self.store.create(generate_download_job(mirror))
            self._set_phase(mirror, PHASE_DOWNLOADING, "download job created")
            return Result(requeue_after=10.0)
        if job.status.get("succeeded"):
            self._set_phase(mirror, PHASE_READY, "weights cached")
            return Result()
        if job.status.get("failed"):
            self._set_phase(mirror, PHASE_FAILED,
                            str(job.status.get("message", "download failed")))
            return Result()
        self._set_phase(mirror, PHASE_DOWNLOADING, "downloading")
        return Result(requeue_after=10.0)

    _PHASE_EVENTS = {
        PHASE_DOWNLOADING: ("Normal", "DownloadStarted"),
        PHASE_READY: ("Normal", "MirrorReady"),
        PHASE_FAILED: ("Warning", "MirrorFailed"),
    }

    def _set_phase(self, mirror, phase, message):
        prev = {"phase": None}

        def mutate(o):
            prev["phase"] = o.status.phase
            o.status.phase = phase
            set_condition(o.status.conditions, Condition(
                type="Ready", status="True" if phase == PHASE_READY else "False",
                reason=phase, message=message))
        update_with_retry(self.store, "ModelMirror", mirror.metadata.namespace,
                          mirror.metadata.name, mutate)
        if prev["phase"] != phase and phase in self._PHASE_EVENTS:
            etype, reason = self._PHASE_EVENTS[phase]
            record_event(self.store, mirror, etype, reason, message)
