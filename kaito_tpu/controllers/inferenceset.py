"""InferenceSet reconciler: replica manager over Workspaces.

Parity: ``pkg/inferenceset/inferenceset_controller.go:195-493`` —
create/delete child Workspaces from the template (deleting not-ready
replicas first on scale-down), guard with expectations against
stale-cache over-creation, surface scale-subresource status
(replicas/readyReplicas/selector) for KEDA/HPA, aggregate per-replica
benchmark TPM, and install the Gateway API InferencePool + EPP.
"""

from __future__ import annotations

import logging

from kaito_tpu.api.inferenceset import InferenceSet
from kaito_tpu.api.meta import Condition, ObjectMeta, condition_true, set_condition
from kaito_tpu.api.workspace import (
    ANNOTATION_DRAINING,
    COND_INFERENCE_READY,
    LABEL_CREATED_BY_INFERENCESET,
    Workspace,
)
from kaito_tpu.controllers.objects import Unstructured
from kaito_tpu.controllers.runtime import (
    Expectations,
    Reconciler,
    Result,
    Store,
    update_with_retry,
)
from kaito_tpu.controllers.workspace import BENCH_METRIC_PEAK_TPM
from kaito_tpu.k8s.events import record_event

logger = logging.getLogger(__name__)

COND_SET_READY = "InferenceSetReady"


def make_child_workspace(iset: InferenceSet, index: int) -> Workspace:
    """Render the index-th replica Workspace from the set's template.
    Module-level so the autoscaler can plan the NEXT replica (warm-pool
    provisioning needs its name and slice shape before it exists)."""
    import copy

    t = iset.spec.template
    name = f"{iset.metadata.name}-{index}"
    return Workspace(
        ObjectMeta(
            name=name, namespace=iset.metadata.namespace,
            labels={**t.labels,
                    LABEL_CREATED_BY_INFERENCESET: iset.metadata.name},
            annotations=dict(t.annotations),
            owner_references=[{"kind": "InferenceSet",
                               "name": iset.metadata.name,
                               "uid": iset.metadata.uid}]),
        resource=copy.deepcopy(t.resource),
        inference=copy.deepcopy(t.inference))


class InferenceSetReconciler(Reconciler):
    kind = "InferenceSet"

    def __init__(self, store: Store, gateway_api_enabled: bool = False):
        super().__init__(store)
        self.expectations = Expectations()
        self.gateway_api_enabled = gateway_api_enabled
        store.watch(self._observe)

    def _observe(self, event: str, kind: str, obj) -> None:
        if kind != "Workspace":
            return
        owner = obj.metadata.labels.get(LABEL_CREATED_BY_INFERENCESET)
        if not owner:
            return
        key = f"{obj.metadata.namespace}/{owner}"
        if event == "ADDED":
            self.expectations.creation_observed(key)
        elif event == "DELETED":
            self.expectations.deletion_observed(key)

    # ------------------------------------------------------------------

    def _children(self, iset: InferenceSet) -> list[Workspace]:
        return self.store.list(
            "Workspace", iset.metadata.namespace,
            labels={LABEL_CREATED_BY_INFERENCESET: iset.metadata.name})

    def _make_child(self, iset: InferenceSet, index: int) -> Workspace:
        return make_child_workspace(iset, index)

    def _nodes_per_replica(self, iset: InferenceSet,
                           children: list[Workspace]) -> int:
        """Nodes one replica costs, for the nodeCountLimit guard.
        Observed child status wins; with no children yet (scale from
        zero) the template is planned instead — defaulting to 1 there
        over-admitted multi-node presets exactly when the guard matters
        most.  Planning failures fall back to 1 (the workspace
        reconciler will surface PlanFailed on the child itself)."""
        observed = [c.status.target_node_count for c in children
                    if c.status.target_node_count > 0]
        if observed:
            return max(observed)
        try:
            from kaito_tpu.controllers.workspace import plan_workspace

            ws = self._make_child(iset, 0)
            _, plan, _ = plan_workspace(self.store, ws)
            return max(1, plan.num_hosts * ws.resource.count)
        except Exception:
            logger.debug("template plan failed for %s; node guard "
                         "assumes 1 node/replica", iset.metadata.name,
                         exc_info=True)
            return 1

    def reconcile(self, iset: InferenceSet) -> Result:
        if iset.metadata.deletion_timestamp:
            for ws in self._children(iset):
                self.store.delete("Workspace", ws.metadata.namespace,
                                  ws.metadata.name)
            return Result()
        iset.default()
        errs = iset.validate()
        if errs:
            self._set_cond(iset, COND_SET_READY, "False", "ValidationFailed",
                           "; ".join(errs))
            return Result()

        key = f"{iset.metadata.namespace}/{iset.metadata.name}"
        if not self.expectations.satisfied(key):
            return Result(requeue_after=1.0)

        children = self._children(iset)
        want = iset.spec.replicas

        # node-count guard (spec.nodeCountLimit)
        if iset.spec.node_count_limit:
            max_replicas = iset.spec.node_count_limit \
                // self._nodes_per_replica(iset, children)
            want = min(want, max(max_replicas, 0))

        if len(children) < want:
            used = {c.metadata.name for c in children}
            creating = 0
            # probe indices unboundedly: scale-up/down churn leaves
            # sparse index sets (e.g. {0, 3, 7}), so any fixed probe
            # range can run out of fresh names before reaching want
            i = 0
            while len(children) + creating < want:
                child = self._make_child(iset, i)
                i += 1
                if child.metadata.name in used:
                    continue
                self.expectations.expect_creations(key, 1)
                self.store.create(child)
                creating += 1
            if creating:
                record_event(self.store, iset, "Normal", "ScalingUp",
                             f"created {creating} replica workspace(s) "
                             f"toward {want}")
        elif len(children) > want:
            # delete draining-marked first (the autoscaler already
            # flushed their traffic through the EPP), then not-ready
            # (reference: :222-247)
            def victim_order(ws):
                return (not ws.metadata.annotations.get(ANNOTATION_DRAINING),
                        condition_true(ws.status.conditions,
                                       COND_INFERENCE_READY))

            victims = sorted(children, key=victim_order)[: len(children) - want]
            for v in victims:
                self.expectations.expect_deletions(key, 1)
                self.store.delete("Workspace", v.metadata.namespace,
                                  v.metadata.name)
            if victims:
                record_event(self.store, iset, "Normal", "ScalingDown",
                             f"deleted {len(victims)} replica workspace(s) "
                             f"toward {want}")

        children = self._children(iset)
        ready = [c for c in children
                 if condition_true(c.status.conditions, COND_INFERENCE_READY)]
        tpm = sum(c.status.performance.metrics.get(BENCH_METRIC_PEAK_TPM, 0.0)
                  for c in ready)

        def set_status(o):
            o.status.replicas = len(children)
            o.status.ready_replicas = len(ready)
            o.status.selector = f"{LABEL_CREATED_BY_INFERENCESET}={iset.metadata.name}"
            o.status.aggregated_peak_tokens_per_minute = tpm
            set_condition(o.status.conditions, Condition(
                type=COND_SET_READY,
                status="True" if len(ready) >= want and want >= 0 else "False",
                reason="Ready" if len(ready) >= want else "ScalingUp",
                message=f"{len(ready)}/{want} replicas ready"))
        update_with_retry(self.store, "InferenceSet", iset.metadata.namespace,
                          iset.metadata.name, set_status)
        was_ready = condition_true(iset.status.conditions, COND_SET_READY)
        if len(ready) >= want and not was_ready:
            record_event(self.store, iset, "Normal", "RolloutComplete",
                         f"{len(ready)}/{want} replicas ready")

        if self.gateway_api_enabled:
            self._ensure_inference_pool(iset)
            self._ensure_epp(iset)
        return Result() if len(ready) >= want else Result(requeue_after=5.0)

    def _ensure_inference_pool(self, iset: InferenceSet) -> None:
        """Install the Gateway API InferencePool + endpoint picker
        (reference: ensureGatewayAPIInferenceExtension :493 via Flux
        HelmRelease; we render the InferencePool object directly)."""
        name = f"{iset.metadata.name}-pool"
        if self.store.try_get("InferencePool", iset.metadata.namespace, name):
            return
        self.store.create(Unstructured(
            "InferencePool",
            ObjectMeta(name=name, namespace=iset.metadata.namespace,
                       owner_references=[{"kind": "InferenceSet",
                                          "name": iset.metadata.name}]),
            spec={
                "targetPortNumber": 5000,
                "selector": {LABEL_CREATED_BY_INFERENCESET: iset.metadata.name},
                "extensionRef": {"name": f"{iset.metadata.name}-epp"},
            }))

    def _ensure_epp(self, iset: InferenceSet) -> None:
        """Render the endpoint picker the pool's extensionRef names
        (docs/routing.md): a Deployment running
        ``kaito_tpu.runtime.epp`` plus its Service.  The backend set is
        the replica workspaces' Services, recomputed every reconcile so
        scale-up/down keeps the picker's ``--backend`` args current."""
        from kaito_tpu.manifests.epp import EPP_PORT, generate_epp_workload

        ns = iset.metadata.namespace
        children = self._children(iset)
        backends = sorted(f"http://{c.metadata.name}:{EPP_PORT}"
                          for c in children)
        draining = sorted(f"http://{c.metadata.name}:{EPP_PORT}"
                          for c in children
                          if c.metadata.annotations.get(ANNOTATION_DRAINING))
        # the same kaito-tpu.io/kv-pool annotation the workspace
        # template renders into --kv-pool on the engines also arms the
        # picker's advert scraper + fetch hints, so the two sides of
        # the cluster KV pool can never be enabled apart (the template
        # is what child workspaces inherit; the CR metadata is the
        # manual escape hatch)
        kv_pool = str(
            iset.spec.template.annotations.get("kaito-tpu.io/kv-pool")
            or iset.metadata.annotations.get("kaito-tpu.io/kv-pool")
            or "").lower() in ("true", "1", "on", "enabled")
        # same coupling for multi-LoRA: the kaito-tpu.io/adapters
        # document the template renders into --adapter-slots on the
        # engines arms the picker's /v1/adapters residency scraper +
        # adapter-affinity scorer (docs/multi-lora.md)
        adapter_affinity = bool(str(
            iset.spec.template.annotations.get("kaito-tpu.io/adapters")
            or iset.metadata.annotations.get("kaito-tpu.io/adapters")
            or "").strip())
        objs = generate_epp_workload(
            f"{iset.metadata.name}-epp", ns, backends=backends,
            draining=draining, kv_pool=kv_pool,
            adapter_affinity=adapter_affinity,
            owner={"kind": "InferenceSet", "name": iset.metadata.name})
        for obj in objs:
            existing = self.store.try_get(obj.kind, ns, obj.metadata.name)
            if existing is None:
                self.store.create(obj)
            elif (obj.kind == "Deployment"
                  and existing.spec["template"]["spec"]["containers"][0]
                  ["command"]
                  != obj.spec["template"]["spec"]["containers"][0]
                  ["command"]):
                existing.spec = obj.spec
                self.store.update(existing)

    def _set_cond(self, iset, type_, status, reason, message):
        def mutate(o):
            set_condition(o.status.conditions, Condition(
                type=type_, status=status, reason=reason, message=message))
        update_with_retry(self.store, "InferenceSet", iset.metadata.namespace,
                          iset.metadata.name, mutate)
