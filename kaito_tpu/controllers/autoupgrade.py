"""Auto-upgrade runner.

Parity: ``pkg/controllers/autoupgrade/runner.go:58-300`` — a periodic
(non-reconciler) runner: inside the InferenceSet's cron maintenance
window, label one not-yet-upgraded child workspace at a time with the
upgrade-to-version annotation; the workspace controller then swaps the
StatefulSet image and the benchmark re-runs.
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import Optional

from kaito_tpu.api.meta import condition_true
from kaito_tpu.api.workspace import (
    ANNOTATION_UPGRADE_TO,
    COND_INFERENCE_READY,
    LABEL_CREATED_BY_INFERENCESET,
)
from kaito_tpu.controllers.runtime import Store, update_with_retry
from kaito_tpu.k8s.events import record_event


def cron_matches(cron: str, at: datetime) -> bool:
    """Minimal 5-field cron matcher (minute hour dom month dow)."""
    fields = cron.split()
    if len(fields) != 5:
        raise ValueError(f"invalid cron {cron!r}")
    values = [at.minute, at.hour, at.day, at.month, at.isoweekday() % 7]

    def match(spec: str, v: int) -> bool:
        if spec == "*":
            return True
        for part in spec.split(","):
            if part.startswith("*/"):
                if v % int(part[2:]) == 0:
                    return True
            elif "-" in part:
                lo, hi = part.split("-")
                if int(lo) <= v <= int(hi):
                    return True
            elif part.isdigit() and int(part) == v:
                return True
        return False

    return all(match(s, v) for s, v in zip(fields, values))


def _expand(spec: str, lo: int, hi: int) -> list[int]:
    """Expand one cron field to its sorted allowed values in [lo, hi].
    ``*/n`` keeps the matcher's semantics (v % n == 0, not lo+k*n)."""
    if spec == "*":
        return list(range(lo, hi + 1))
    vals: set[int] = set()
    for part in spec.split(","):
        if part.startswith("*/"):
            step = int(part[2:])
            vals.update(v for v in range(lo, hi + 1) if v % step == 0)
        elif "-" in part:
            a, b = part.split("-")
            vals.update(range(int(a), int(b) + 1))
        elif part.isdigit():
            vals.add(int(part))
    return sorted(v for v in vals if lo <= v <= hi)


def last_fire(cron: str, at: datetime) -> Optional[datetime]:
    """Most recent cron fire time <= ``at`` (minute resolution).

    Direct computation: expand each field once, walk back day-by-day
    until a day matches dom/month/dow, then take the largest allowed
    (hour, minute) within bound.  O(fields + days scanned) instead of
    the old minute-by-minute probe over the whole window — a 7-day
    window probed the matcher 10,080 times per InferenceSet per tick.
    Returns None when nothing fired in the past year (e.g. a Feb-30
    cron)."""
    fields = cron.split()
    if len(fields) != 5:
        raise ValueError(f"invalid cron {cron!r}")
    minutes = _expand(fields[0], 0, 59)
    hours = _expand(fields[1], 0, 23)
    doms = set(_expand(fields[2], 1, 31))
    months = set(_expand(fields[3], 1, 12))
    dows = set(_expand(fields[4], 0, 6))
    if not (minutes and hours and doms and months and dows):
        return None
    at = at.replace(second=0, microsecond=0)
    day = at.replace(hour=0, minute=0)
    for back in range(366):
        d = day - timedelta(days=back)
        if not (d.month in months and d.day in doms
                and d.isoweekday() % 7 in dows):
            continue
        if back:
            return d.replace(hour=hours[-1], minute=minutes[-1])
        # today: largest allowed (hour, minute) not after `at`
        for h in reversed(hours):
            if h > at.hour:
                continue
            for m in reversed(minutes):
                if h < at.hour or m <= at.minute:
                    return d.replace(hour=h, minute=m)
        # nothing fired yet today — keep walking back
    return None


class AutoUpgradeRunner:
    """Call tick() on an interval (the manager wires this at ~1/min)."""

    def __init__(self, store: Store, target_version: str):
        self.store = store
        self.target_version = target_version

    def in_window(self, iset, at: Optional[datetime] = None) -> bool:
        au = iset.spec.auto_upgrade
        if not au.enabled or not au.maintenance_window.cron:
            return False
        at = (at or datetime.now(timezone.utc)).replace(second=0,
                                                        microsecond=0)
        # within `duration` minutes after the most recent cron fire
        fire = last_fire(au.maintenance_window.cron, at)
        return fire is not None and (at - fire) < timedelta(
            minutes=au.maintenance_window.duration_minutes)

    def tick(self, at: Optional[datetime] = None) -> Optional[str]:
        """Upgrade at most one workspace; returns its name if any."""
        for iset in self.store.list("InferenceSet"):
            if not self.in_window(iset, at):
                continue
            children = self.store.list(
                "Workspace", iset.metadata.namespace,
                labels={LABEL_CREATED_BY_INFERENCESET: iset.metadata.name})
            # one at a time: wait for any in-flight upgrade to go ready
            in_flight = [c for c in children
                         if c.metadata.annotations.get(ANNOTATION_UPGRADE_TO)
                         == self.target_version
                         and not condition_true(c.status.conditions,
                                                COND_INFERENCE_READY)]
            if in_flight:
                continue
            for c in children:
                if c.metadata.annotations.get(ANNOTATION_UPGRADE_TO) != self.target_version:
                    def annotate(o):
                        o.metadata.annotations[ANNOTATION_UPGRADE_TO] = \
                            self.target_version
                    update_with_retry(self.store, "Workspace",
                                      c.metadata.namespace, c.metadata.name,
                                      annotate)
                    record_event(self.store, iset, "Normal",
                                 "UpgradeWindowFired",
                                 f"maintenance window open; upgrading "
                                 f"{c.metadata.name} to "
                                 f"{self.target_version}")
                    record_event(self.store, c, "Normal", "UpgradeStarted",
                                 f"auto-upgrade to {self.target_version} "
                                 f"triggered by maintenance window")
                    return c.metadata.name
        return None
