"""Auto-upgrade runner.

Parity: ``pkg/controllers/autoupgrade/runner.go:58-300`` — a periodic
(non-reconciler) runner: inside the InferenceSet's cron maintenance
window, label one not-yet-upgraded child workspace at a time with the
upgrade-to-version annotation; the workspace controller then swaps the
StatefulSet image and the benchmark re-runs.
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Optional

from kaito_tpu.api.meta import condition_true
from kaito_tpu.api.workspace import (
    ANNOTATION_UPGRADE_TO,
    COND_INFERENCE_READY,
    LABEL_CREATED_BY_INFERENCESET,
)
from kaito_tpu.controllers.runtime import Store, update_with_retry


def cron_matches(cron: str, at: datetime) -> bool:
    """Minimal 5-field cron matcher (minute hour dom month dow)."""
    fields = cron.split()
    if len(fields) != 5:
        raise ValueError(f"invalid cron {cron!r}")
    values = [at.minute, at.hour, at.day, at.month, at.isoweekday() % 7]

    def match(spec: str, v: int) -> bool:
        if spec == "*":
            return True
        for part in spec.split(","):
            if part.startswith("*/"):
                if v % int(part[2:]) == 0:
                    return True
            elif "-" in part:
                lo, hi = part.split("-")
                if int(lo) <= v <= int(hi):
                    return True
            elif part.isdigit() and int(part) == v:
                return True
        return False

    return all(match(s, v) for s, v in zip(fields, values))


class AutoUpgradeRunner:
    """Call tick() on an interval (the manager wires this at ~1/min)."""

    def __init__(self, store: Store, target_version: str):
        self.store = store
        self.target_version = target_version

    def in_window(self, iset, at: Optional[datetime] = None) -> bool:
        au = iset.spec.auto_upgrade
        if not au.enabled or not au.maintenance_window.cron:
            return False
        at = at or datetime.now(timezone.utc)
        # within `duration` minutes after a cron match
        for back in range(au.maintenance_window.duration_minutes):
            probe = at.replace(second=0, microsecond=0)
            probe = probe.fromtimestamp(probe.timestamp() - back * 60, tz=timezone.utc)
            if cron_matches(au.maintenance_window.cron, probe):
                return True
        return False

    def tick(self, at: Optional[datetime] = None) -> Optional[str]:
        """Upgrade at most one workspace; returns its name if any."""
        for iset in self.store.list("InferenceSet"):
            if not self.in_window(iset, at):
                continue
            children = self.store.list(
                "Workspace", iset.metadata.namespace,
                labels={LABEL_CREATED_BY_INFERENCESET: iset.metadata.name})
            # one at a time: wait for any in-flight upgrade to go ready
            in_flight = [c for c in children
                         if c.metadata.annotations.get(ANNOTATION_UPGRADE_TO)
                         == self.target_version
                         and not condition_true(c.status.conditions,
                                                COND_INFERENCE_READY)]
            if in_flight:
                continue
            for c in children:
                if c.metadata.annotations.get(ANNOTATION_UPGRADE_TO) != self.target_version:
                    def annotate(o):
                        o.metadata.annotations[ANNOTATION_UPGRADE_TO] = \
                            self.target_version
                    update_with_retry(self.store, "Workspace",
                                      c.metadata.namespace, c.metadata.name,
                                      annotate)
                    return c.metadata.name
        return None
