"""Drift reconciler: safe rolling node replacement.

Parity: ``pkg/controllers/drift/controller.go:96-246`` — when a node of
a workspace is marked drifted AND the workspace's InferenceSet has at
least one other ready replica, open that workspace's NodePool drift
budget (0→1) so the provisioner can replace the node; close budgets
once drift clears.  One pool at a time cluster-wide.
"""

from __future__ import annotations

from kaito_tpu.api.meta import condition_true
from kaito_tpu.api.workspace import (
    COND_INFERENCE_READY,
    LABEL_CREATED_BY_INFERENCESET,
)
from kaito_tpu.controllers.runtime import Reconciler, Result, Store
from kaito_tpu.k8s.events import record_event
from kaito_tpu.provision.karpenter import LABEL_OWNER
from kaito_tpu.provision.provisioner import ProvisionRequest
from kaito_tpu.sku.catalog import CHIP_CATALOG, TPUSliceSpec


class DriftReconciler(Reconciler):
    kind = "Workspace"

    def __init__(self, store: Store, provisioner):
        super().__init__(store)
        self.provisioner = provisioner

    def _drifted_owners(self) -> set[str]:
        out = set()
        for n in self.store.list("Node"):
            if n.status.get("drifted"):
                owner = n.metadata.labels.get(LABEL_OWNER)
                if owner:
                    out.add(owner)
        return out

    def _has_ready_sibling(self, ws) -> bool:
        iset_name = ws.metadata.labels.get(LABEL_CREATED_BY_INFERENCESET)
        if not iset_name:
            return False
        siblings = self.store.list(
            "Workspace", ws.metadata.namespace,
            labels={LABEL_CREATED_BY_INFERENCESET: iset_name})
        return any(
            s.metadata.name != ws.metadata.name
            and condition_true(s.status.conditions, COND_INFERENCE_READY)
            for s in siblings)

    def _req(self, ws) -> ProvisionRequest:
        # budget toggling only needs the owner name; slice spec is moot
        return ProvisionRequest(
            owner_name=ws.metadata.name,
            owner_namespace=ws.metadata.namespace,
            slice_spec=TPUSliceSpec(chip=CHIP_CATALOG["v5e"], topology="1x1"))

    def reconcile_drift(self) -> Result:
        """Cluster-wide pass (not per-object): open at most ONE budget."""
        drifted = self._drifted_owners()
        opened = False
        for ws in self.store.list("Workspace"):
            req = self._req(ws)
            if ws.metadata.name in drifted and not opened \
                    and self._has_ready_sibling(ws):
                self.provisioner.set_drift_budget(req, True)
                opened = True
                record_event(self.store, ws, "Warning", "DriftDetected",
                             "drifted node detected; disruption budget "
                             "opened for rolling replacement")
            else:
                self.provisioner.set_drift_budget(req, False)
        return Result(requeue_after=30.0 if drifted else 0.0)

    def reconcile(self, obj) -> Result:
        return self.reconcile_drift()
