"""Admission webhook server.

Parity with the reference's knative-style admission webhooks
(``pkg/workspace/webhooks/webhooks.go:39``): a validating +
defaulting endpoint for our kinds, speaking the k8s
``admission.k8s.io/v1`` AdmissionReview protocol on stdlib HTTP(S).
The schema logic itself lives on the typed kinds (api/*.validate and
.default) — the webhook is a thin transport.
"""

from __future__ import annotations

import argparse
import base64
import copy
import json
import logging
import ssl
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger(__name__)


def _load_kind(kind: str, payload: dict):
    """Build a typed object from a YAML-shaped admission object."""
    from kaito_tpu.api import (
        InferenceSet,
        ModelMirror,
        MultiRoleInference,
        ObjectMeta,
        RAGEngine,
        Workspace,
    )
    from kaito_tpu.api.inferenceset import InferenceSetSpec, WorkspaceTemplate
    from kaito_tpu.api.workspace import (
        AdapterSpec,
        InferenceSpec,
        ResourceSpec,
        TuningInput,
        TuningOutput,
        TuningSpec,
    )

    meta_d = payload.get("metadata", {})
    meta = ObjectMeta(name=meta_d.get("name", ""),
                      namespace=meta_d.get("namespace", "default"),
                      labels=dict(meta_d.get("labels", {})),
                      annotations=dict(meta_d.get("annotations", {})))

    def resource_spec(d):
        return ResourceSpec(
            instance_type=d.get("instanceType", "ct5lp-hightpu-4t"),
            count=int(d.get("count", 1)),
            tpu_topology=d.get("tpuTopology", ""),
            label_selector=dict(d.get("labelSelector", {}) or {}),
            preferred_nodes=list(d.get("preferredNodes", []) or []))

    if kind == "Workspace":
        inference = None
        if "inference" in payload:
            i = payload["inference"] or {}
            inference = InferenceSpec(
                preset=i.get("preset", ""), template=i.get("template"),
                config=i.get("config", ""),
                adapters=[AdapterSpec(name=a.get("name", ""),
                                      source_image=a.get("sourceImage", ""),
                                      strength=float(a.get("strength", 1.0)))
                          for a in i.get("adapters", []) or []])
        tuning = None
        if "tuning" in payload:
            t = payload["tuning"] or {}
            inp = t.get("input", {}) or {}
            out = t.get("output", {}) or {}
            tuning = TuningSpec(
                preset=t.get("preset", ""), method=t.get("method", "lora"),
                config=t.get("config", ""),
                input=TuningInput(urls=list(inp.get("urls", []) or []),
                                  image=inp.get("image", ""),
                                  volume=inp.get("volume")),
                output=TuningOutput(image=out.get("image", ""),
                                    image_push_secret=out.get("imagePushSecret", ""),
                                    volume=out.get("volume")))
        return Workspace(meta, resource=resource_spec(payload.get("resource", {})),
                         inference=inference, tuning=tuning)
    raise KeyError(kind)


class AdmissionHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _write_json(self, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _respond(self, review: dict, allowed: bool, message: str = "",
                 patch: Optional[list] = None):
        resp = {"uid": review.get("request", {}).get("uid", ""),
                "allowed": allowed}
        if message:
            resp["status"] = {"message": message}
        if patch:
            resp["patchType"] = "JSONPatch"
            resp["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
        self._write_json({"apiVersion": "admission.k8s.io/v1",
                          "kind": "AdmissionReview",
                          "response": resp})

    def _respond_conversion(self, review: dict):
        """CRD ConversionReview v1 (reference: the conversion webhook
        behind api/v1alpha1/*_conversion.go): objects convert to the
        requested version in EITHER direction — spoke->hub on writes
        of legacy manifests, hub->spoke when clients read at the
        served legacy version."""
        from kaito_tpu.api.conversion import HUB_VERSION, convert

        req = review.get("request", {})
        desired = req.get("desiredAPIVersion", "") or HUB_VERSION
        converted = [convert(obj, desired) for obj in req.get("objects", [])]
        self._write_json({
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "ConversionReview",
            "response": {"uid": req.get("uid", ""),
                         "result": {"status": "Success"},
                         "convertedObjects": converted}})

    def do_POST(self):
        try:
            n = int(self.headers.get("Content-Length", "0"))
            review = json.loads(self.rfile.read(n))
            if self.path.startswith("/convert"):
                return self._respond_conversion(review)
            req = review.get("request", {})
            kind = req.get("kind", {}).get("kind", "")
            obj = req.get("object", {}) or {}
        except (ValueError, json.JSONDecodeError):
            self.send_response(400)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return

        try:
            typed = _load_kind(kind, obj)
        except KeyError:
            return self._respond(review, True)  # kinds we don't gate

        if self.path.startswith("/default"):
            before = copy.deepcopy(obj)
            typed.default()
            patch = []
            if typed.resource.count != int(
                    (before.get("resource") or {}).get("count", 0) or 0):
                patch.append({"op": "add" if "resource" not in before else "replace",
                              "path": "/resource/count"
                              if "resource" in before else "/resource",
                              "value": typed.resource.count
                              if "resource" in before
                              else {"count": typed.resource.count}})
            return self._respond(review, True, patch=patch or None)

        typed.default()
        errs = typed.validate()
        if errs:
            return self._respond(review, False, message="; ".join(errs))
        return self._respond(review, True)


def make_server(host: str = "0.0.0.0", port: int = 9443,
                certfile: str = "", keyfile: str = "") -> ThreadingHTTPServer:
    server = ThreadingHTTPServer((host, port), AdmissionHandler)
    if certfile:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certfile, keyfile or None)
        server.socket = ctx.wrap_socket(server.socket, server_side=True)
    return server


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=9443)
    ap.add_argument("--tls-cert", default="")
    ap.add_argument("--tls-key", default="")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    make_server(port=args.port, certfile=args.tls_cert,
                keyfile=args.tls_key).serve_forever()


if __name__ == "__main__":
    main()
