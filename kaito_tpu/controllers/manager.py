"""Controller manager: wires every reconciler behind feature gates.

Parity: ``cmd/workspace/main.go:100-405`` — flag parsing, gate
validation, provisioner factory, controller wiring, and the run loop.
In-process it drives watch-triggered reconciliation plus periodic
resync; against a real cluster the same wiring hangs off informers.
"""

from __future__ import annotations

import argparse
import logging
import threading
import time
from typing import Optional

from kaito_tpu.controllers.drift import DriftReconciler
from kaito_tpu.controllers.inferenceset import InferenceSetReconciler
from kaito_tpu.controllers.modelmirror import ModelMirrorReconciler
from kaito_tpu.controllers.multiroleinference import MultiRoleInferenceReconciler
from kaito_tpu.controllers.autoupgrade import AutoUpgradeRunner
from kaito_tpu.controllers.ragengine import RAGEngineReconciler
from kaito_tpu.controllers.runtime import Store
from kaito_tpu.controllers.workspace import WorkspaceReconciler
from kaito_tpu.featuregates import parse_feature_gates
from kaito_tpu.provision import new_node_provisioner

logger = logging.getLogger(__name__)


class Manager:
    def __init__(self, store: Optional[Store] = None,
                 node_provisioner: str = "karpenter",
                 feature_gates: str = "",
                 base_image_version: str = "latest"):
        self.store = store or Store()
        self.gates = parse_feature_gates(feature_gates)
        self.provisioner = new_node_provisioner(
            "byo" if self.gates["disableNodeAutoProvisioning"] else node_provisioner,
            self.store)

        self.workspace = WorkspaceReconciler(self.store, self.provisioner,
                                             self.gates)
        self.reconcilers = [self.workspace]
        if self.gates["enableInferenceSetController"]:
            self.inferenceset = InferenceSetReconciler(
                self.store,
                gateway_api_enabled=self.gates["gatewayAPIInferenceExtension"])
            self.reconcilers.append(self.inferenceset)
        if self.gates["enableMultiRoleInferenceController"]:
            self.mri = MultiRoleInferenceReconciler(self.store)
            self.reconcilers.append(self.mri)
        if self.gates["modelMirror"]:
            self.modelmirror = ModelMirrorReconciler(self.store)
            self.reconcilers.append(self.modelmirror)
        self.ragengine = RAGEngineReconciler(self.store)
        self.reconcilers.append(self.ragengine)
        self.drift = DriftReconciler(self.store, self.provisioner)
        self.autoupgrade = (
            AutoUpgradeRunner(self.store, base_image_version)
            if self.gates["enableBaseImageAutoUpgrade"] else None)

        self._stop = threading.Event()

    def resync(self) -> None:
        """One full reconcile pass over every kind."""
        for rec in self.reconcilers:
            for obj in self.store.list(rec.kind):
                try:
                    rec.reconcile(obj)
                except Exception:
                    logger.exception("reconcile %s/%s failed", rec.kind,
                                     obj.metadata.name)
        self.drift.reconcile_drift()
        if self.autoupgrade:
            self.autoupgrade.tick()

    def run(self, interval: float = 2.0) -> None:
        logger.info("manager running; gates=%s", self.gates)
        while not self._stop.is_set():
            self.resync()
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()


def main(argv=None):
    import os

    ap = argparse.ArgumentParser(prog="kaito-tpu-manager")
    ap.add_argument("--node-provisioner", default="karpenter",
                    choices=["karpenter", "byo"])
    ap.add_argument("--feature-gates", default="")
    ap.add_argument("--base-image-version", default="latest")
    ap.add_argument("--resync-seconds", type=float, default=0.0,
                    help="0 = auto: 2s in-memory, 30s against a real API "
                         "server (watch events carry the fast path)")
    ap.add_argument("--kube-api-url", default="",
                    help="API server base URL (in-cluster service-account "
                         "config is used when unset)")
    ap.add_argument("--in-memory-store", action="store_true",
                    help="use the in-process store even in-cluster (dev)")
    ap.add_argument("--namespace",
                    default=os.environ.get("POD_NAMESPACE", "default"))
    ap.add_argument("--disable-preset-autogen", action="store_true",
                    help="do not auto-generate presets for unregistered "
                         "org/model ids (catalog + HF hub)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    # production preset auto-generation: unregistered org/model
    # Workspaces resolve via the committed catalog, then the HF hub
    # (reference: GeneratePreset at reconcile time,
    # presets/workspace/generator/generator.go:805-830).  Wired at the
    # entrypoint — not in Manager.__init__ — so embedding a Manager
    # (tests, tools) never silently switches the process-global
    # registry onto the network path.
    if not args.disable_preset_autogen:
        from kaito_tpu.models.hub import install_default_fetcher

        install_default_fetcher()

    store = None
    in_cluster = "KUBERNETES_SERVICE_HOST" in os.environ
    if not args.in_memory_store and (args.kube_api_url or in_cluster):
        from kaito_tpu.k8s import KubeClient, KubeStore

        store = KubeStore(KubeClient(base_url=args.kube_api_url),
                          namespace=args.namespace)
        logger.info("using Kubernetes API store (%s)",
                    args.kube_api_url or "in-cluster")
    mgr = Manager(store=store, node_provisioner=args.node_provisioner,
                  feature_gates=args.feature_gates,
                  base_image_version=args.base_image_version)
    if store is not None:
        # informer analogue: watch streams feed the expectations and
        # event-driven callbacks registered by the reconcilers
        from kaito_tpu.k8s.codec import TYPED_KINDS

        store.start_watching(list(TYPED_KINDS))
    resync = args.resync_seconds or (30.0 if store is not None else 2.0)
    mgr.run(resync)


if __name__ == "__main__":
    main()
