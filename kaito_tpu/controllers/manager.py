"""Controller manager: wires every reconciler behind feature gates.

Parity: ``cmd/workspace/main.go:100-405`` — flag parsing, gate
validation, provisioner factory, controller wiring, and the run loop.
In-process it drives watch-triggered reconciliation plus periodic
resync; against a real cluster the same wiring hangs off informers.
"""

from __future__ import annotations

import argparse
import logging
import threading
import time
from typing import Optional

from kaito_tpu.controllers.drift import DriftReconciler
from kaito_tpu.controllers.inferenceset import InferenceSetReconciler
from kaito_tpu.controllers.metrics import ManagerMetrics, start_manager_server
from kaito_tpu.controllers.modelmirror import ModelMirrorReconciler
from kaito_tpu.controllers.multiroleinference import MultiRoleInferenceReconciler
from kaito_tpu.controllers.autoupgrade import AutoUpgradeRunner
from kaito_tpu.controllers.ragengine import RAGEngineReconciler
from kaito_tpu.controllers.runtime import Store
from kaito_tpu.controllers.workspace import WorkspaceReconciler
from kaito_tpu.featuregates import parse_feature_gates
from kaito_tpu.provision import new_node_provisioner
from kaito_tpu.runtime.fleet import FleetTelemetry

logger = logging.getLogger(__name__)


class Manager:
    def __init__(self, store: Optional[Store] = None,
                 node_provisioner: str = "karpenter",
                 feature_gates: str = "",
                 base_image_version: str = "latest",
                 metrics: Optional[ManagerMetrics] = None):
        self.store = store or Store()
        self.metrics = metrics or ManagerMetrics()
        events = getattr(self.store, "events", None)
        if events is not None:
            self.metrics.attach_event_counter(events)
        if hasattr(self.store, "on_watch_restart"):
            self.store.on_watch_restart = \
                lambda kind: self.metrics.watch_restarts.inc(kind=kind)
        self.gates = parse_feature_gates(feature_gates)
        self.provisioner = new_node_provisioner(
            "byo" if self.gates["disableNodeAutoProvisioning"] else node_provisioner,
            self.store)

        self.workspace = WorkspaceReconciler(self.store, self.provisioner,
                                             self.gates)
        self.reconcilers = [self.workspace]
        if self.gates["enableInferenceSetController"]:
            self.inferenceset = InferenceSetReconciler(
                self.store,
                gateway_api_enabled=self.gates["gatewayAPIInferenceExtension"])
            self.reconcilers.append(self.inferenceset)
        if self.gates["enableMultiRoleInferenceController"]:
            self.mri = MultiRoleInferenceReconciler(self.store)
            self.reconcilers.append(self.mri)
        if self.gates["modelMirror"]:
            self.modelmirror = ModelMirrorReconciler(self.store)
            self.reconcilers.append(self.modelmirror)
        self.ragengine = RAGEngineReconciler(self.store)
        self.reconcilers.append(self.ragengine)
        self.drift = DriftReconciler(self.store, self.provisioner)
        self.autoupgrade = (
            AutoUpgradeRunner(self.store, base_image_version)
            if self.gates["enableBaseImageAutoUpgrade"] else None)
        # fleet telemetry plane (ROADMAP item 1's read side): cheap to
        # construct — no threads or sockets until run()/start()
        self.fleet = FleetTelemetry(self.store)
        self.fleet.register_metrics(self.metrics.registry)
        # the actuation half: consumes fleet signals, mutates replicas
        self.autoscaler = None
        if self.gates["autoscaler"]:
            from kaito_tpu.controllers.autoscaler import AutoscalerController

            self.autoscaler = AutoscalerController(self.store, self.fleet,
                                                   self.provisioner)
            self.autoscaler.register_metrics(self.metrics.registry)

        self._stop = threading.Event()

    def _reconcile_one(self, rec, obj) -> None:
        """One instrumented reconcile: counted, timed, and recorded as
        a span (trace id = object key, so ``/debug/trace?trace_id=
        Workspace/ns/name`` shows one CR's reconcile history)."""
        controller = type(rec).__name__
        trace_id = f"{rec.kind}/{obj.metadata.namespace}/{obj.metadata.name}"
        result = "ok"
        t0 = time.monotonic()
        try:
            with self.metrics.tracer.span(f"reconcile.{rec.kind}", trace_id,
                                          controller=controller):
                res = rec.reconcile(obj)
            if res is not None and (res.requeue or res.requeue_after > 0):
                result = "requeue"
        except Exception:
            result = "error"
            logger.exception("reconcile %s/%s failed", rec.kind,
                             obj.metadata.name)
        self.metrics.observe_reconcile(controller, result,
                                       time.monotonic() - t0)

    def resync(self) -> None:
        """One full reconcile pass over every kind."""
        self.metrics.resync_total.inc()
        for rec in self.reconcilers:
            for obj in self.store.list(rec.kind):
                self._reconcile_one(rec, obj)
        t0 = time.monotonic()
        drift_result = "ok"
        try:
            with self.metrics.tracer.span("reconcile.Drift", "Drift/cluster",
                                          controller="DriftReconciler"):
                self.drift.reconcile_drift()
        except Exception:
            drift_result = "error"
            logger.exception("drift pass failed")
        self.metrics.observe_reconcile("DriftReconciler", drift_result,
                                       time.monotonic() - t0)
        if self.autoupgrade:
            self.autoupgrade.tick()
        self.metrics.refresh_conditions(self.store)
        # fleet pass: rebuild targets from the store, then fold the
        # latest scrapes into signals.  No-op when nothing reported.
        try:
            self.fleet.refresh_targets()
            self.fleet.apply_signals()
        except Exception:
            logger.exception("fleet telemetry pass failed")
        if self.autoscaler is not None:
            t0 = time.monotonic()
            asc_result = "ok"
            try:
                with self.metrics.tracer.span(
                        "reconcile.Autoscaler", "Autoscaler/cluster",
                        controller="AutoscalerController"):
                    self.autoscaler.tick()
            except Exception:
                asc_result = "error"
                logger.exception("autoscaler pass failed")
            self.metrics.observe_reconcile("AutoscalerController",
                                           asc_result,
                                           time.monotonic() - t0)

    def run(self, interval: float = 2.0) -> None:
        logger.info("manager running; gates=%s", self.gates)
        self.fleet.start()
        try:
            while not self._stop.is_set():
                self.resync()
                self._stop.wait(interval)
        finally:
            self.fleet.stop()

    def stop(self) -> None:
        self._stop.set()


def main(argv=None):
    import os

    ap = argparse.ArgumentParser(prog="kaito-tpu-manager")
    ap.add_argument("--node-provisioner", default="karpenter",
                    choices=["karpenter", "byo"])
    ap.add_argument("--feature-gates", default="")
    ap.add_argument("--base-image-version", default="latest")
    ap.add_argument("--resync-seconds", type=float, default=0.0,
                    help="0 = auto: 2s in-memory, 30s against a real API "
                         "server (watch events carry the fast path)")
    ap.add_argument("--kube-api-url", default="",
                    help="API server base URL (in-cluster service-account "
                         "config is used when unset)")
    ap.add_argument("--in-memory-store", action="store_true",
                    help="use the in-process store even in-cluster (dev)")
    ap.add_argument("--namespace",
                    default=os.environ.get("POD_NAMESPACE", "default"))
    ap.add_argument("--disable-preset-autogen", action="store_true",
                    help="do not auto-generate presets for unregistered "
                         "org/model ids (catalog + HF hub)")
    ap.add_argument("--metrics-port", type=int, default=8080,
                    help="manager /metrics + /debug/trace port (0 = off; "
                         "matches the chart's metrics containerPort)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    # production preset auto-generation: unregistered org/model
    # Workspaces resolve via the committed catalog, then the HF hub
    # (reference: GeneratePreset at reconcile time,
    # presets/workspace/generator/generator.go:805-830).  Wired at the
    # entrypoint — not in Manager.__init__ — so embedding a Manager
    # (tests, tools) never silently switches the process-global
    # registry onto the network path.
    if not args.disable_preset_autogen:
        from kaito_tpu.models.hub import install_default_fetcher

        install_default_fetcher()

    store = None
    in_cluster = "KUBERNETES_SERVICE_HOST" in os.environ
    if not args.in_memory_store and (args.kube_api_url or in_cluster):
        from kaito_tpu.k8s import KubeClient, KubeStore

        store = KubeStore(KubeClient(base_url=args.kube_api_url),
                          namespace=args.namespace)
        logger.info("using Kubernetes API store (%s)",
                    args.kube_api_url or "in-cluster")
    mgr = Manager(store=store, node_provisioner=args.node_provisioner,
                  feature_gates=args.feature_gates,
                  base_image_version=args.base_image_version)
    if args.metrics_port:
        start_manager_server(mgr.metrics, port=args.metrics_port,
                             fleet=mgr.fleet)
    if store is not None:
        # informer analogue: watch streams feed the expectations and
        # event-driven callbacks registered by the reconcilers
        from kaito_tpu.k8s.codec import TYPED_KINDS

        store.start_watching(list(TYPED_KINDS))
    resync = args.resync_seconds or (30.0 if store is not None else 2.0)
    mgr.run(resync)


if __name__ == "__main__":
    main()
