"""Workspace reconciler.

The core orchestration loop (reference:
``pkg/workspace/controllers/workspace_controller.go:116`` Reconcile):
finalizer → ControllerRevision → plan slice via estimator/planner →
provision TPU capacity → gate on ModelMirror → render + apply workload
→ sync conditions/status.  The mesh planner replaces the reference's
EstimateNodeCount + configureParallelism pair: a single decision
produces both the capacity ask and the parallelism layout.
"""

from __future__ import annotations

import logging
from typing import Optional

from kaito_tpu.api.meta import Condition, ObjectMeta, get_condition, set_condition
from kaito_tpu.api.modelmirror import (
    PHASE_READY,
    ModelMirror,
    ModelMirrorSpec,
    MirrorSource,
)
from kaito_tpu.api.workspace import (
    ANNOTATION_DISABLE_BENCHMARK,
    ANNOTATION_UPGRADE_TO,
    COND_BENCHMARK_COMPLETE,
    COND_INFERENCE_READY,
    COND_NODE_CLAIM_READY,
    COND_RESOURCE_READY,
    COND_SLO_HEALTHY,
    COND_TUNING_STARTED,
    COND_WORKSPACE_SUCCEEDED,
    LABEL_WORKSPACE_NAME,
    Workspace,
)
from kaito_tpu.k8s.events import record_event
from kaito_tpu.controllers.objects import Unstructured
from kaito_tpu.controllers.runtime import (
    Reconciler,
    Result,
    Store,
    sync_controller_revision,
    update_with_retry,
)
from kaito_tpu.manifests.inference import generate_inference_workload
from kaito_tpu.manifests.tuning_job import generate_tuning_job
from kaito_tpu.models.registry import get_model_by_name
from kaito_tpu.parallel.plan import ParallelPlan, plan_parallelism
from kaito_tpu.provision.provisioner import ProvisionRequest
from kaito_tpu.sku.catalog import (
    MACHINE_TYPES,
    CHIP_CATALOG,
    TPUSliceSpec,
    get_tpu_config_from_node_labels,
)

logger = logging.getLogger(__name__)

FINALIZER = "kaito-tpu.io/workspace-finalizer"
BENCH_METRIC_PEAK_TPM = "peakTokensPerMinute"


def plan_workspace(store: Store, ws: Workspace):
    """Preset + instance type -> (model metadata, ParallelPlan,
    TPUSliceSpec).  Module-level so consumers that plan a Workspace
    that does not exist yet — the InferenceSet node-count guard and
    the autoscaler's warm-pool render — share one decision with the
    reconcile path instead of re-deriving capacity math."""
    md = get_model_by_name(ws.preset_name)
    entry = MACHINE_TYPES.get(ws.resource.instance_type)
    if entry is not None:
        chip = CHIP_CATALOG[entry[0]]
    else:
        # BYO path: derive chip from an existing labeled node
        spec = None
        for n in store.list("Node", labels=ws.resource.label_selector or None):
            spec = get_tpu_config_from_node_labels(n.metadata.labels)
            if spec:
                break
        if spec is None:
            raise ValueError(
                f"cannot determine TPU generation for {ws.metadata.name}: "
                f"unknown instance type and no labeled BYO nodes")
        chip = spec.chip
    workload = "train" if ws.tuning is not None else "serve"
    target = None
    if ws.resource.tpu_topology:
        from kaito_tpu.sku.catalog import topology_chips

        target = topology_chips(ws.resource.tpu_topology)
    # an int8 KV pool halves bytes/token, so the planner can fit the
    # same context on fewer chips (estimator threads the byte width
    # through kv_bytes_per_token)
    kv_dtype = ws.metadata.annotations.get(
        "kaito-tpu.io/kv-cache-dtype", "")
    # weight-only quantization shrinks weight bytes (int8: 1/2, int4:
    # ~1/4 with group scales), so the same model fits fewer chips; a
    # bogus scheme fails the plan (PlanFailed condition + event) before
    # any capacity is asked for, mirroring the qos/speculative-draft
    # pattern (docs/quantization.md)
    quant = ws.metadata.annotations.get("kaito-tpu.io/quantization", "")
    if quant and quant not in ("int8", "int4"):
        # mirrors engine/quant.py QUANT_SCHEMES without importing the
        # engine (the controller stays jax-free, like the qos check)
        raise ValueError(
            f"invalid kaito-tpu.io/quantization annotation: unknown "
            f"scheme {quant!r} (known: int8, int4)")
    # speculative-draft pairing fails the plan (PlanFailed
    # condition + event) when the named draft is unknown or shares
    # no tokenizer with the target — before any capacity is asked
    # for (docs/speculative.md)
    from kaito_tpu.models.registry import resolve_speculative_draft
    resolve_speculative_draft(md, ws.metadata.annotations.get(
        "kaito-tpu.io/speculative-draft", ""))
    # a malformed QoS document fails the plan (PlanFailed condition +
    # event) before any capacity is asked for, instead of crash-looping
    # the engine pod at startup (docs/qos.md)
    from kaito_tpu.engine.qos import parse_qos_config
    try:
        parse_qos_config(ws.metadata.annotations.get(
            "kaito-tpu.io/qos", ""))
    except ValueError as e:
        raise ValueError(f"invalid kaito-tpu.io/qos annotation: {e}")
    # a malformed multi-LoRA document fails the plan the same way —
    # the parse helper lives in manifests (jax-free) and is the exact
    # code the renderer runs, so plan-time acceptance == render-time
    # acceptance (docs/multi-lora.md)
    from kaito_tpu.manifests.inference import (
        parse_adapters_annotation, parse_comm_overlap_annotation,
        parse_devprof_annotation, parse_flight_annotation,
        parse_itl_annotation, parse_kv_pool_disk_annotation,
        parse_structured_output_annotation)
    try:
        parse_adapters_annotation(ws.metadata.annotations.get(
            "kaito-tpu.io/adapters", ""))
    except ValueError as e:
        raise ValueError(f"invalid kaito-tpu.io/adapters annotation: {e}")
    # a malformed devprof interval fails the plan the same way — the
    # exact parse the renderer runs, so plan-time acceptance ==
    # render-time acceptance (docs/observability.md)
    try:
        parse_devprof_annotation(ws.metadata.annotations.get(
            "kaito-tpu.io/devprof", ""))
    except ValueError as e:
        raise ValueError(f"invalid kaito-tpu.io/devprof annotation: {e}")
    # a malformed comm-overlap gate fails the plan the same way — the
    # exact parse the renderer runs, so plan-time acceptance ==
    # render-time acceptance (docs/multichip.md)
    try:
        parse_comm_overlap_annotation(ws.metadata.annotations.get(
            "kaito-tpu.io/comm-overlap", ""))
    except ValueError as e:
        raise ValueError(
            f"invalid kaito-tpu.io/comm-overlap annotation: {e}")
    # a malformed structured-output document fails the plan the same
    # way — again the exact parse the renderer runs, so plan-time
    # acceptance == render-time acceptance (docs/structured-output.md)
    try:
        parse_structured_output_annotation(ws.metadata.annotations.get(
            "kaito-tpu.io/structured-output", ""))
    except ValueError as e:
        raise ValueError(
            f"invalid kaito-tpu.io/structured-output annotation: {e}")
    # a malformed ITL gate or flight-recorder dir fails the plan the
    # same way — the exact parses the renderer runs, so plan-time
    # acceptance == render-time acceptance (docs/observability.md)
    try:
        parse_itl_annotation(ws.metadata.annotations.get(
            "kaito-tpu.io/itl", ""))
    except ValueError as e:
        raise ValueError(f"invalid kaito-tpu.io/itl annotation: {e}")
    try:
        parse_flight_annotation(
            ws.metadata.annotations.get("kaito-tpu.io/flight-dir", ""),
            ws.metadata.annotations.get(
                "kaito-tpu.io/flight-max-bundles", ""))
    except ValueError as e:
        raise ValueError(
            f"invalid kaito-tpu.io/flight-dir annotation: {e}")
    # a malformed SSD-tier budget (or one named without the pool)
    # fails the plan the same way — the exact parse the renderer runs,
    # so plan-time acceptance == render-time acceptance
    # (docs/kv-pool.md "Tier 3: SSD")
    try:
        parse_kv_pool_disk_annotation(
            ws.metadata.annotations.get("kaito-tpu.io/kv-pool-disk", ""),
            ws.metadata.annotations.get("kaito-tpu.io/kv-pool", ""))
    except ValueError as e:
        raise ValueError(
            f"invalid kaito-tpu.io/kv-pool-disk annotation: {e}")
    # CP prefill auto-carve is evidence-gated (plan_parallelism
    # docstring: BENCH_r05 cp_speedup 0.68 < 1.0) — serve plans
    # only carve a sequence axis when the user opts in
    cp_opt_in = ws.metadata.annotations.get(
        "kaito-tpu.io/cp-autocarve", "") == "true"
    plan = plan_parallelism(md, chip, workload=workload,
                            target_chips=target,
                            kv_dtype_bytes=1 if kv_dtype == "int8" else 2,
                            quantization=quant or None,
                            cp_autocarve=cp_opt_in)
    slice_spec = TPUSliceSpec(
        chip=chip, topology=plan.topology,
        machine_type=ws.resource.instance_type
        if ws.resource.instance_type in MACHINE_TYPES else "")
    return md, plan, slice_spec


class WorkspaceReconciler(Reconciler):
    kind = "Workspace"

    def __init__(self, store: Store, provisioner, feature_gates=None):
        super().__init__(store)
        self.provisioner = provisioner
        self.gates = feature_gates or {}

    # ------------------------------------------------------------------

    def reconcile(self, ws: Workspace) -> Result:
        if ws.metadata.deletion_timestamp:
            return self._finalize(ws)
        if FINALIZER not in ws.metadata.finalizers:
            ws.metadata.finalizers.append(FINALIZER)
            ws = self.store.update(ws)

        ws.default()
        errs = ws.validate()
        if errs:
            if self._set_cond(ws, COND_RESOURCE_READY, "False",
                              "ValidationFailed", "; ".join(errs)):
                record_event(self.store, ws, "Warning", "ValidationFailed",
                             "; ".join(errs))
            return Result()

        sync_controller_revision(self.store, ws, ws.revision_payload())

        try:
            md, plan, slice_spec = self._plan(ws)
        except (KeyError, ValueError) as e:
            if self._set_cond(ws, COND_RESOURCE_READY, "False", "PlanFailed",
                              str(e)):
                record_event(self.store, ws, "Warning", "PlanFailed", str(e))
            return Result()

        # capacity
        req = ProvisionRequest(
            owner_name=ws.metadata.name,
            owner_namespace=ws.metadata.namespace,
            slice_spec=slice_spec,
            num_slices=plan.num_slices * ws.resource.count,
            extra_labels=dict(ws.resource.label_selector),
            preferred_nodes=list(ws.resource.preferred_nodes))
        self.provisioner.provision(req)
        # snapshot-capable provisioners (karpenter) build ONE snapshot
        # per reconcile: readiness, node list, and the status condition
        # all derive from it (reference nodeReadinessSnapshot/
        # CollectNodeStatusInfo, provisioner.go:391-560)
        snap_cond = None
        if hasattr(self.provisioner, "ensure_ready_snapshot"):
            snap = self.provisioner.ensure_ready_snapshot(req)
            ready, nodes = snap.all_ready, snap.ready_nodes
            snap_cond = snap.condition()
        else:
            ready, nodes = self.provisioner.ensure_ready(req)
        # node repair runs regardless of overall readiness: a dead node
        # in an otherwise-covered slice still pins its pool replica
        # slot and must be replaced
        if hasattr(self.provisioner, "repair_unhealthy"):
            repaired = self.provisioner.repair_unhealthy(req)
            if repaired:
                logger.info("repairing NotReady nodes for %s: %s",
                            ws.metadata.name, repaired)
                record_event(self.store, ws, "Warning", "NodeRepaired",
                             f"deleted NotReady nodes for replacement: "
                             f"{', '.join(repaired)}")
        prov_s = (self.provisioner.provision_seconds(req)
                  if hasattr(self.provisioner, "provision_seconds") else None)

        def set_target(o):
            o.status.target_node_count = plan.num_hosts * ws.resource.count
            o.status.worker_nodes = nodes
            o.status.observed_generation = o.metadata.generation
            if prov_s is not None:
                o.status.performance.metrics[
                    "provision_to_ready_seconds"] = round(prov_s, 3)
        ws = update_with_retry(self.store, "Workspace", ws.metadata.namespace,
                               ws.metadata.name, set_target)

        if not ready:
            if self._set_cond(ws, COND_NODE_CLAIM_READY, "False",
                              snap_cond["reason"] if snap_cond
                              else "Provisioning",
                              snap_cond["message"] if snap_cond
                              else f"{len(nodes)} nodes ready"):
                record_event(self.store, ws, "Normal", "ProvisioningStarted",
                             f"waiting for TPU capacity "
                             f"({len(nodes)} nodes ready)")
            return Result(requeue_after=5.0)
        ready_msg = f"{len(nodes)} nodes ready"
        if prov_s is not None:
            ready_msg += f" (provisioned in {prov_s:.1f}s)"
        if self._set_cond(ws, COND_NODE_CLAIM_READY, "True", "NodesReady",
                          ready_msg):
            record_event(self.store, ws, "Normal", "NodeClaimSatisfied",
                         ready_msg)
        self._set_cond(ws, COND_RESOURCE_READY, "True", "ResourceReady", "")

        # weight cache gate (reference: ensureModelMirror :173 +
        # waitForModelMirror :291, behind the ModelMirror feature gate)
        if self.gates.get("modelMirror") and md.hf_id:
            if not self._ensure_model_mirror(md):
                return Result(requeue_after=5.0)

        if ws.tuning is not None:
            return self._reconcile_tuning(ws, md, plan, req)
        return self._reconcile_inference(ws, md, plan, req)

    # ------------------------------------------------------------------

    def _plan(self, ws: Workspace):
        return plan_workspace(self.store, ws)

    def _ensure_model_mirror(self, md) -> bool:
        name = md.name.replace("/", "-")
        mirror = self.store.try_get("ModelMirror", "", name)
        if mirror is None:
            self.store.create(ModelMirror(
                ObjectMeta(name=name, namespace=""),
                ModelMirrorSpec(source=MirrorSource(model_id=md.hf_id))))
            return False
        return mirror.status.phase == PHASE_READY

    # ------------------------------------------------------------------

    def _reconcile_inference(self, ws: Workspace, md, plan: ParallelPlan,
                             req: ProvisionRequest) -> Result:
        node_selector = self.provisioner.node_selector(req)
        benchmark = ws.metadata.annotations.get(ANNOTATION_DISABLE_BENCHMARK) != "true"
        objs = generate_inference_workload(ws, md, plan, node_selector,
                                           benchmark=benchmark)
        for obj in objs:
            self._apply(obj, ws)

        # image upgrade (reference: workspace_controller.go:676-685)
        upgrade_to = ws.metadata.annotations.get(ANNOTATION_UPGRADE_TO)
        if upgrade_to:
            bumped = {"v": False}

            def bump(ss):
                c = ss.spec["template"]["spec"]["containers"][0]
                base = c["image"].rsplit(":", 1)[0]
                bumped["v"] = c["image"] != f"{base}:{upgrade_to}"
                c["image"] = f"{base}:{upgrade_to}"
            update_with_retry(self.store, "StatefulSet", ws.metadata.namespace,
                              ws.metadata.name, bump)
            if bumped["v"]:
                record_event(self.store, ws, "Normal", "UpgradeApplied",
                             f"base image rolled to version {upgrade_to}")

        ss = self.store.try_get("StatefulSet", ws.metadata.namespace,
                                ws.metadata.name)
        ready = bool(ss) and ss.status.get("readyReplicas", 0) >= ss.spec["replicas"]
        if self._set_cond(ws, COND_INFERENCE_READY,
                          "True" if ready else "False",
                          "InferenceReady" if ready else "PodsPending",
                          f"{(ss.status.get('readyReplicas', 0) if ss else 0)}"
                          f"/{plan.num_hosts} ready"):
            record_event(self.store, ws, "Normal",
                         "RolloutComplete" if ready else "RolloutStarted",
                         f"{(ss.status.get('readyReplicas', 0) if ss else 0)}"
                         f"/{plan.num_hosts} replicas ready")

        # benchmark result ingestion (reference: benchmark.go tails pod
        # logs for KAITO_BENCHMARK_RESULT; our probe posts to the SS
        # status, same contract re-homed)
        bench = (ss.status.get("benchmark") if ss else None) or {}
        if benchmark and ready and bench:
            # failure surfaces as a condition instead of silently
            # recording zeros (reference: benchmark result parse
            # failures flip the workspace condition, benchmark.go)
            try:
                tpm = float(bench.get("total_tpm") or 0.0)
                n_errors = int(bench.get("errors") or 0)
                failed = bool(bench.get("error")) or (
                    tpm <= 0.0 and n_errors > 0)
                fail_msg = str(bench.get("error")
                               or f"{n_errors} request errors, "
                                  f"zero throughput")
            except (TypeError, ValueError) as e:
                # a malformed payload IS a benchmark failure — it must
                # flip the condition, not crash the reconcile
                failed, fail_msg = True, f"malformed benchmark result: {e}"
            if failed:
                if self._set_cond(ws, COND_BENCHMARK_COMPLETE, "False",
                                  "BenchmarkFailed", fail_msg):
                    record_event(self.store, ws, "Warning", "BenchmarkFailed",
                                 fail_msg)
            else:
                def record(o):
                    o.status.performance.metrics[BENCH_METRIC_PEAK_TPM] = \
                        float(bench.get("total_tpm", 0.0))
                    o.status.performance.config = {
                        k: str(v) for k, v in bench.items()
                        if k != "total_tpm"}
                ws = update_with_retry(self.store, "Workspace",
                                       ws.metadata.namespace,
                                       ws.metadata.name, record)
                if self._set_cond(ws, COND_BENCHMARK_COMPLETE, "True",
                                  "BenchmarkComplete", ""):
                    record_event(
                        self.store, ws, "Normal", "BenchmarkComplete",
                        f"probe measured "
                        f"{float(bench.get('total_tpm', 0.0)):.0f} tok/min")
            # SLO verdict folding (runtime/slo.py): the probe ships the
            # engine's /debug/slo snapshot inside the benchmark result;
            # kubectl get workspace then shows the SLOHealthy condition
            verdict = bench.get("slo")
            if isinstance(verdict, dict):
                from kaito_tpu.runtime.slo import condition_from_verdict

                status, reason, message = condition_from_verdict(verdict)
                if self._set_cond(ws, COND_SLO_HEALTHY, status, reason,
                                  message):
                    record_event(self.store, ws,
                                 "Normal" if status == "True" else "Warning",
                                 reason, message)
        if ready:
            self._set_cond(ws, COND_WORKSPACE_SUCCEEDED, "True", "Ready", "")
        return Result() if ready else Result(requeue_after=5.0)

    def _reconcile_tuning(self, ws: Workspace, md, plan: ParallelPlan,
                          req: ProvisionRequest) -> Result:
        node_selector = self.provisioner.node_selector(req)
        job = generate_tuning_job(ws, md, plan, node_selector)
        self._apply(job, ws)
        self._set_cond(ws, COND_TUNING_STARTED, "True", "JobCreated", "")
        live = self.store.try_get("Job", ws.metadata.namespace, job.metadata.name)
        if live and live.status.get("succeeded"):
            self._set_cond(ws, COND_WORKSPACE_SUCCEEDED, "True", "JobSucceeded", "")
            return Result()
        if live and live.status.get("failed"):
            self._set_cond(ws, COND_WORKSPACE_SUCCEEDED, "False", "JobFailed",
                           str(live.status.get("message", "")))
            return Result()
        return Result(requeue_after=5.0)

    # ------------------------------------------------------------------

    def _apply(self, obj: Unstructured, owner: Workspace) -> None:
        """Create-or-selectively-update (reference: selective field
        update, workspace_controller.go:655-668 — replicas/template only,
        so external controllers' fields survive)."""
        obj.metadata.owner_references = [{
            "kind": "Workspace", "name": owner.metadata.name,
            "uid": owner.metadata.uid}]
        existing = self.store.try_get(obj.kind, obj.metadata.namespace,
                                      obj.metadata.name)
        if existing is None:
            self.store.create(obj)
            return
        if obj.kind == "StatefulSet":
            def mutate(cur):
                cur.spec["replicas"] = obj.spec["replicas"]
                # keep a live image upgrade (annotation path) sticky
                new_tmpl = obj.spec["template"]
                cur_img = cur.spec["template"]["spec"]["containers"][0].get("image")
                new_tmpl["spec"]["containers"][0]["image"] = cur_img or \
                    new_tmpl["spec"]["containers"][0]["image"]
                cur.spec["template"] = new_tmpl
            update_with_retry(self.store, obj.kind, obj.metadata.namespace,
                              obj.metadata.name, mutate)
        elif obj.kind == "Service" and existing.spec != obj.spec:
            # Services drift too (ports/selector edits must reconcile
            # back); clusterIP-style immutable fields aren't modeled
            # in-process, so the rendered spec wins wholesale.  The
            # equality gate keeps no-drift resyncs write-free (no
            # resourceVersion churn / spurious MODIFIED events).
            def mutate_svc(cur):
                cur.spec = dict(obj.spec)
            update_with_retry(self.store, obj.kind, obj.metadata.namespace,
                              obj.metadata.name, mutate_svc)

    def _set_cond(self, ws: Workspace, type_: str, status: str, reason: str,
                  message: str) -> bool:
        """Upsert the condition; True when the STATUS transitioned
        (the event-worthy edge — reason/message churn is not)."""
        changed = {"v": False}

        def mutate(o):
            prev = get_condition(o.status.conditions, type_)
            changed["v"] = prev is None or prev.status != status
            set_condition(o.status.conditions, Condition(
                type=type_, status=status, reason=reason, message=message,
                observed_generation=o.metadata.generation))
        update_with_retry(self.store, "Workspace", ws.metadata.namespace,
                          ws.metadata.name, mutate)
        return changed["v"]

    def _finalize(self, ws: Workspace) -> Result:
        try:
            md, plan, slice_spec = self._plan(ws)
            req = ProvisionRequest(
                owner_name=ws.metadata.name,
                owner_namespace=ws.metadata.namespace,
                slice_spec=slice_spec, num_slices=plan.num_slices)
            self.provisioner.deprovision(req)
        except Exception:
            logger.exception("deprovision during finalize failed; continuing")
        for kind in ("StatefulSet", "Service", "Job"):
            for obj in self.store.list(kind, ws.metadata.namespace):
                if any(ref.get("name") == ws.metadata.name
                       for ref in obj.metadata.owner_references):
                    self.store.delete(kind, obj.metadata.namespace,
                                      obj.metadata.name)
        if FINALIZER in ws.metadata.finalizers:
            def strip(o):
                if FINALIZER in o.metadata.finalizers:
                    o.metadata.finalizers.remove(FINALIZER)
            update_with_retry(self.store, "Workspace", ws.metadata.namespace,
                              ws.metadata.name, strip)
        return Result()
