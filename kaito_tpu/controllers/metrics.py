"""Control-plane observability: manager metrics + debug HTTP server.

The controller-runtime freebies the reference gets from kubebuilder
(``controller_runtime_reconcile_total`` et al.), rebuilt on our
dependency-free metrics layer: reconcile counters/durations per
controller, resync and watch-restart counters, per-CR condition-state
gauges, emitted-Event counters, and a ``RingTracer`` of reconcile
spans so a slow reconcile is diagnosable at ``/debug/trace`` exactly
the way a slow request is on the engine server.

``make_manager_server`` serves ``/metrics``, ``/debug/trace``,
``/debug/fleet`` (when a ``FleetTelemetry`` plane is attached) and
``/healthz`` on ``--metrics-port`` (the port the Helm chart already
exposes as the manager's ``metrics`` containerPort).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kaito_tpu.engine.metrics import Counter, Gauge, Histogram, Registry
from kaito_tpu.utils.tracing import RingTracer, chrome_trace

logger = logging.getLogger(__name__)

# bucket spread for reconciles: sub-ms store round-trips up to
# multi-second full-plan passes
RECONCILE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_COND_STATE = {"True": 1.0, "False": 0.0}

# kinds whose per-CR condition state is worth a gauge series
_CONDITION_KINDS = ("Workspace", "InferenceSet")


class ManagerMetrics:
    """One Registry + tracer per manager process."""

    def __init__(self, trace_capacity: int = 8192):
        self.registry = Registry()
        r = self.registry
        self.reconcile_total = Counter(
            "kaito:controller_reconcile_total",
            "Reconcile outcomes per controller", r,
            labels=("controller", "result"))
        self.reconcile_duration = Histogram(
            "kaito:controller_reconcile_duration_seconds",
            "Reconcile wall time per controller", r,
            buckets=RECONCILE_BUCKETS, labels=("controller",))
        self.resync_total = Counter(
            "kaito:controller_resync_total",
            "Full periodic resync passes", r)
        self.watch_restarts = Counter(
            "kaito:controller_watch_restarts_total",
            "Watch stream reconnects per kind", r, labels=("kind",))
        self.workspace_condition = Gauge(
            "kaito:workspace_condition",
            "Workspace condition state (1=True, 0=False, -1=Unknown)", r,
            labels=("name", "type"))
        self.inferenceset_condition = Gauge(
            "kaito:inferenceset_condition",
            "InferenceSet condition state (1=True, 0=False, -1=Unknown)", r,
            labels=("name", "type"))
        self._cond_gauges = {"Workspace": self.workspace_condition,
                             "InferenceSet": self.inferenceset_condition}
        self.tracer = RingTracer(trace_capacity)

    def observe_reconcile(self, controller: str, result: str,
                          seconds: float) -> None:
        self.reconcile_total.inc(controller=controller, result=result)
        self.reconcile_duration.observe(seconds, controller=controller)

    def attach_event_counter(self, recorder) -> None:
        """Scrape-time counter over the store's EventRecorder — emitted
        Events become a queryable series without double bookkeeping."""

        def _counts() -> dict:
            out: dict[tuple, float] = {}
            for ev in recorder.events():
                key = (ev.type, ev.reason)
                out[key] = out.get(key, 0.0) + ev.count
            return out

        Gauge("kaito:controller_events_total",
              "Events recorded per type and reason", self.registry,
              labels=("type", "reason"), fn=_counts)

    def refresh_conditions(self, store) -> None:
        """Rebuild the per-CR condition gauges from a full listing
        (called once per resync; deleted CRs drop out)."""
        for kind in _CONDITION_KINDS:
            gauge = self._cond_gauges[kind]
            gauge.clear()
            try:
                objs = store.list(kind)
            except Exception:
                continue
            for obj in objs:
                for c in getattr(obj.status, "conditions", []) or []:
                    gauge.set(_COND_STATE.get(c.status, -1.0),
                              name=obj.metadata.name, type=c.type)


class ManagerHandler(BaseHTTPRequestHandler):
    metrics: ManagerMetrics   # injected by make_manager_server
    fleet = None              # FleetTelemetry, when the manager runs one
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        logger.debug("%s " + fmt, self.address_string(), *args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        mm = self.metrics
        if self.path == "/metrics":
            self._send(200, mm.registry.expose().encode(),
                       "text/plain; version=0.0.4")
        elif self.path.startswith("/debug/trace"):
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(self.path).query)
            tid = q.get("trace_id", [None])[0]
            payload = chrome_trace(mm.tracer.spans(tid))
            self._send(200, json.dumps(payload).encode(), "application/json")
        elif self.path == "/debug/fleet":
            if self.fleet is None:
                self._send(404, b'{"error": "fleet telemetry disabled"}',
                           "application/json")
            else:
                self._send(200, json.dumps(self.fleet.snapshot()).encode(),
                           "application/json")
        elif self.path == "/healthz":
            self._send(200, b'{"status": "ok"}', "application/json")
        else:
            self._send(404, b'{"error": "no route"}', "application/json")


def make_manager_server(metrics: ManagerMetrics, host: str = "0.0.0.0",
                        port: int = 8080, fleet=None) -> ThreadingHTTPServer:
    handler = type("Handler", (ManagerHandler,),
                   {"metrics": metrics, "fleet": fleet})
    return ThreadingHTTPServer((host, port), handler)


def start_manager_server(metrics: ManagerMetrics, host: str = "0.0.0.0",
                         port: int = 8080,
                         fleet=None) -> Optional[ThreadingHTTPServer]:
    """Spawn the metrics server on a daemon thread (None on bind
    failure — observability must not take the control plane down)."""
    try:
        server = make_manager_server(metrics, host, port, fleet=fleet)
    except OSError:
        logger.exception("manager metrics server bind failed on :%s", port)
        return None
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="manager-metrics").start()
    logger.info("manager metrics on :%s (/metrics, /debug/trace%s)",
                server.server_address[1],
                ", /debug/fleet" if fleet is not None else "")
    return server
