"""Closed-loop autoscaler: ScalingSignal -> replicas -> warm capacity.

The actuation half of ROADMAP item 1.  ``runtime/fleet.py`` (PR 7) is
the read side — per-CR ``ScalingSignal`` conditions and
``recommended_replicas`` hints; this controller is the consumer that
contract promised.  The reference KAITO delegates scaling to HPA/KEDA
via the scale subresource; a TPU-native operator owns the loop because
scale-up is gated on multi-minute slice boot (so capacity must be
provisioned AHEAD of the replica) and scale-down must not strand
in-flight decodes (so the victim drains THROUGH the EPP first).

One ``tick()`` per manager resync, after ``fleet.apply_signals()``,
actuating through three existing layers:

1. **Replicas** — sustained ``pressure|saturated`` raises
   ``spec.replicas`` toward the fleet's ``recommended_replicas``
   (bounded by ``autoscale.maxReplicas`` and ``nodeCountLimit``);
   sustained ``idle`` lowers it to ``minReplicas`` or zero.  The
   InferenceSetReconciler does the actual child create/delete.
2. **Warm TPU capacity** — the moment the signal enters ``pressure``
   the NEXT replica's NodePools are rendered through the provisioner
   (``provision/karpenter.py``), so replica boot is not serialized
   behind slice boot.  Warm pools whose replica never materialized are
   GC'd after the signal has stayed out of pressure for
   ``warmPoolGcS``.
3. **EPP drain** — scale-down first annotates the victim
   (``kaito-tpu.io/draining``); the set's EPP re-renders with
   ``--drain-backend`` (picker stops scoring it, in-flight requests
   finish) and only after ``drainGraceS`` does ``spec.replicas`` drop,
   letting the reconciler delete the drained victim first.

Scale-to-zero keeps the EPP front alive: arrivals keep ticking
``kaito:router_requests_received_total`` even with zero backends, and
a non-zero received rate wakes the set immediately (no stabilization —
the cold start is expensive enough already).

Per-direction stabilization windows + cooldowns + pending-drain
cancellation make signal oscillation cheap: a flap cancels the drain
and unmarks the victims instead of thrashing replicas.

Everything is observable — ``kaito:autoscaler_*`` gauges/counters on
the manager registry, ``ScalingUp/ScalingDown/ScaleToZero/
WarmPoolProvisioned/WarmPoolReclaimed`` Events, and an
``AutoscalerActive`` condition — and the whole subsystem sits behind
the ``autoscaler`` feature gate (off by default).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kaito_tpu.api.inferenceset import AutoscalePolicy, InferenceSet
from kaito_tpu.api.meta import Condition, condition_true, get_condition, set_condition
from kaito_tpu.api.workspace import (
    ANNOTATION_DRAINING,
    COND_INFERENCE_READY,
    LABEL_CREATED_BY_INFERENCESET,
    Workspace,
)
from kaito_tpu.controllers.inferenceset import make_child_workspace
from kaito_tpu.controllers.runtime import Store, update_with_retry
from kaito_tpu.k8s.events import record_event
from kaito_tpu.provision.karpenter import LABEL_OWNER
from kaito_tpu.provision.provisioner import ProvisionRequest
from kaito_tpu.runtime.fleet import (
    SIGNAL_IDLE,
    SIGNAL_PRESSURE,
    SIGNAL_SATURATED,
)

logger = logging.getLogger(__name__)

COND_AUTOSCALER_ACTIVE = "AutoscalerActive"
# warm pools carry this label until their replica materializes (then
# it is stripped — the pool is owned for real) or GC deletes them
LABEL_WARM_FOR = "kaito-tpu.io/warm-pool-for"

EVENT_SCALING_UP = "ScalingUp"
EVENT_SCALING_DOWN = "ScalingDown"
EVENT_SCALE_TO_ZERO = "ScaleToZero"
EVENT_WARM_PROVISIONED = "WarmPoolProvisioned"
EVENT_WARM_RECLAIMED = "WarmPoolReclaimed"

_UNBOUNDED = 1 << 30


@dataclass
class _SetState:
    """Per-InferenceSet actuation memory (in-process, rebuilt cheaply
    after a manager restart — worst case one extra stabilization
    window before the next action)."""

    last_scale_up_t: float = 0.0
    last_scale_down_t: float = 0.0
    # an initiated-but-uncommitted scale-down: victims are draining
    # through the EPP until the deadline, then spec.replicas drops
    pending_target: Optional[int] = None
    pending_deadline: float = 0.0
    pending_victims: list[str] = field(default_factory=list)
    # last-tick observability snapshot (metric gauges read these)
    desired: int = 0
    draining: int = 0
    warm_pools: int = 0
    phase: str = "Observing"


class AutoscalerController:
    """Not a per-object Reconciler: one ``tick()`` sweeps every
    InferenceSet whose ``spec.autoscale.enabled`` is set, reading the
    fleet plane's already-evaluated signals (the manager runs the tick
    right after ``fleet.apply_signals()``)."""

    kind = "InferenceSet"

    def __init__(self, store: Store, fleet, provisioner=None,
                 time_fn: Callable[[], float] = time.monotonic):
        from kaito_tpu.engine.metrics import Counter

        self.store = store
        self.fleet = fleet
        self.provisioner = provisioner
        self.time_fn = time_fn
        self._state: dict[tuple, _SetState] = {}
        # registry-less until register_metrics; counting always works
        self.m_scale_events = Counter(
            "kaito:autoscaler_scale_events_total",
            "Committed scale actions (direction: up|down|zero|wake)",
            None, labels=("name", "direction"))

    # -- metrics -------------------------------------------------------

    def register_metrics(self, registry) -> None:
        from kaito_tpu.engine.metrics import Gauge

        def per_set(attr):
            def _fn():
                return {(k[2],): float(getattr(st, attr))
                        for k, st in self._state.items()}
            return _fn

        Gauge("kaito:autoscaler_desired_replicas",
              "spec.replicas as last actuated/observed per InferenceSet",
              registry, labels=("name",), fn=per_set("desired"))
        Gauge("kaito:autoscaler_draining_replicas",
              "Victim replicas currently draining through the EPP",
              registry, labels=("name",), fn=per_set("draining"))
        Gauge("kaito:autoscaler_warm_pools",
              "Warm NodePools provisioned ahead of their replica",
              registry, labels=("name",), fn=per_set("warm_pools"))
        registry.register(self.m_scale_events)

    def _count_event(self, name: str, direction: str) -> None:
        self.m_scale_events.inc(name=name, direction=direction)

    # -- the loop ------------------------------------------------------

    def tick(self) -> None:
        live = set()
        for iset in self.store.list("InferenceSet"):
            key = ("InferenceSet", iset.metadata.namespace,
                   iset.metadata.name)
            live.add(key)
            try:
                self._reconcile_set(key, iset)
            except Exception:
                logger.exception("autoscaler pass failed for %s/%s",
                                 key[1], key[2])
        for key in list(self._state):
            if key not in live:
                del self._state[key]

    def _reconcile_set(self, key: tuple, iset: InferenceSet) -> None:
        pol: AutoscalePolicy = iset.spec.autoscale
        if not pol.enabled:
            self._state.pop(key, None)
            cur = get_condition(iset.status.conditions,
                                COND_AUTOSCALER_ACTIVE)
            if cur is not None and cur.status != "False":
                self._set_condition(iset, "False", "Disabled",
                                    "spec.autoscale.enabled is false")
            return
        pol.default()
        st = self._state.setdefault(key, _SetState())
        now = self.time_fn()
        ns, name = key[1], key[2]
        children = self._children(iset)
        cur = iset.spec.replicas
        st.desired = cur
        st.draining = len(st.pending_victims) if st.pending_target \
            is not None else 0
        st.warm_pools = len(self._warm_pools(iset))

        sig = self.fleet.signal(key)
        agg = self.fleet.last_aggregate(key)

        # -- scale-to-zero wake: first queued request at the EPP wins
        # over every window/cooldown (cold start costs enough already)
        if cur == 0 and agg.get("received_rate", 0.0) > 0.0:
            target = max(1, pol.min_replicas)
            self._write_replicas(iset, target)
            st.last_scale_up_t = now
            st.desired = target
            self._count_event(name, "wake")
            record_event(self.store, iset, "Normal", EVENT_SCALING_UP,
                         f"waking from zero to {target} replica(s): "
                         f"requests queued at the EPP")
            self._set_condition(iset, "True", "Waking",
                                "scale-from-zero on queued requests")
            st.phase = "Waking"
            return

        # -- minReplicas enforcement (a parked zero under scale-to-zero
        # is the one legal sub-minimum state)
        floor_now = max(1, pol.min_replicas)
        if cur < floor_now and not (pol.scale_to_zero and cur == 0):
            self._write_replicas(iset, floor_now)
            st.desired = floor_now
            self._count_event(name, "up")
            record_event(self.store, iset, "Normal", EVENT_SCALING_UP,
                         f"raising replicas to minReplicas={floor_now}")
            self._set_condition(iset, "True", "EnforcingMinimum",
                                f"spec.replicas below minReplicas "
                                f"{floor_now}")
            st.phase = "EnforcingMinimum"
            return

        if sig is None:
            self._set_condition(iset, "True", "Observing",
                                "no fleet telemetry evaluated yet")
            st.phase = "Observing"
            return
        state, since, decision = sig
        dwell = now - since

        if state in (SIGNAL_PRESSURE, SIGNAL_SATURATED):
            self._cancel_pending_down(iset, st, "signal left idle")
            # warm capacity the moment pressure is entered — replica
            # boot must not serialize behind slice boot
            self._ensure_warm(iset, pol, children)
            st.warm_pools = len(self._warm_pools(iset))
            cap = self._replica_cap(iset, pol, children)
            target = min(max(decision.recommended_replicas, cur + 1), cap)
            if target <= cur:
                self._set_condition(
                    iset, "True", "AtCapacity",
                    f"{state} sustained but replica cap {cap} reached")
                st.phase = "AtCapacity"
                return
            if dwell < pol.scale_up_stabilization_s:
                self._set_condition(
                    iset, "True", "Stabilizing",
                    f"{state} for {dwell:.0f}s of "
                    f"{pol.scale_up_stabilization_s:.0f}s stabilization")
                st.phase = "Stabilizing"
                return
            if now - st.last_scale_up_t < pol.scale_up_cooldown_s:
                self._set_condition(iset, "True", "CoolingDown",
                                    "scale-up cooldown in effect")
                st.phase = "CoolingDown"
                return
            self._write_replicas(iset, target)
            st.last_scale_up_t = now
            st.desired = target
            self._count_event(name, "up")
            record_event(self.store, iset, "Normal", EVENT_SCALING_UP,
                         f"sustained {state}: {cur} -> {target} "
                         f"replica(s) (recommended "
                         f"{decision.recommended_replicas})")
            self._set_condition(iset, "True", "ScalingUp",
                                f"scaling up to {target} on {state}")
            st.phase = "ScalingUp"
            return

        if state == SIGNAL_IDLE:
            self._maybe_gc_warm(iset, pol, dwell)
            target = pol.floor()
            if target >= cur:
                self._set_condition(iset, "True",
                                    "Idle" if cur else "ScaledToZero",
                                    f"idle at floor ({cur} replica(s))")
                st.phase = "Idle"
                return
            # commit an initiated drain once its grace elapsed
            if st.pending_target is not None:
                if now >= st.pending_deadline:
                    self._commit_scale_down(iset, st, name)
                else:
                    self._set_condition(
                        iset, "True", "Draining",
                        f"{len(st.pending_victims)} replica(s) draining "
                        f"through the EPP")
                    st.phase = "Draining"
                return
            need_dwell = max(pol.idle_grace_s,
                             pol.scale_down_stabilization_s)
            if dwell < need_dwell:
                self._set_condition(
                    iset, "True", "Stabilizing",
                    f"idle for {dwell:.0f}s of {need_dwell:.0f}s grace")
                st.phase = "Stabilizing"
                return
            if now - st.last_scale_down_t < pol.scale_down_cooldown_s:
                self._set_condition(iset, "True", "CoolingDown",
                                    "scale-down cooldown in effect")
                st.phase = "CoolingDown"
                return
            self._begin_scale_down(iset, st, children, target, now, pol)
            return

        # nominal: no actuation; flap suppression + warm GC
        self._cancel_pending_down(iset, st, "signal back to nominal")
        self._maybe_gc_warm(iset, pol, dwell)
        st.warm_pools = len(self._warm_pools(iset))
        self._set_condition(iset, "True", "Nominal",
                            "fleet inside the nominal band")
        st.phase = "Nominal"

    # -- scale-down drain ----------------------------------------------

    def _begin_scale_down(self, iset: InferenceSet, st: _SetState,
                          children: list[Workspace], target: int,
                          now: float, pol: AutoscalePolicy) -> None:
        victims = self._pick_victims(children, len(children) - target)
        for v in victims:
            self._mark_draining(v, True)
        st.pending_target = target
        st.pending_deadline = now + pol.drain_grace_s
        st.pending_victims = [v.metadata.name for v in victims]
        st.draining = len(victims)
        record_event(self.store, iset, "Normal", EVENT_SCALING_DOWN,
                     f"draining {len(victims)} replica(s) toward "
                     f"{target} ({pol.drain_grace_s:.0f}s EPP grace)")
        self._set_condition(iset, "True", "Draining",
                            f"{len(victims)} replica(s) draining "
                            f"through the EPP")
        st.phase = "Draining"

    def _commit_scale_down(self, iset: InferenceSet, st: _SetState,
                           name: str) -> None:
        target = st.pending_target or 0
        self._write_replicas(iset, target)
        st.last_scale_down_t = self.time_fn()
        st.desired = target
        st.pending_target = None
        st.pending_victims = []
        st.draining = 0
        if target == 0:
            self._count_event(name, "zero")
            record_event(self.store, iset, "Normal", EVENT_SCALE_TO_ZERO,
                         "sustained idle: parking the set at zero "
                         "replicas (EPP front stays up)")
            self._set_condition(iset, "True", "ScaledToZero",
                                "parked at zero replicas; EPP front "
                                "stays up for wake-on-arrival")
            st.phase = "ScaledToZero"
        else:
            self._count_event(name, "down")
            record_event(self.store, iset, "Normal", EVENT_SCALING_DOWN,
                         f"drain complete: replicas -> {target}")
            self._set_condition(iset, "True", "ScalingDown",
                                f"scaled down to {target}")
            st.phase = "ScalingDown"

    def _cancel_pending_down(self, iset: InferenceSet, st: _SetState,
                             why: str) -> None:
        """Flap suppression: a pending drain whose trigger vanished is
        cancelled — victims are unmarked, nothing thrashes."""
        if st.pending_target is None:
            return
        for ws_name in st.pending_victims:
            ws = self.store.try_get("Workspace", iset.metadata.namespace,
                                    ws_name)
            if ws is not None:
                self._mark_draining(ws, False)
        logger.info("autoscaler: cancelled pending scale-down of %s (%s)",
                    iset.metadata.name, why)
        st.pending_target = None
        st.pending_victims = []
        st.draining = 0

    def _pick_victims(self, children: list[Workspace],
                      count: int) -> list[Workspace]:
        """Not-ready replicas first (no traffic to drain), then the
        highest index (youngest, coldest caches)."""
        def order(ws):
            try:
                idx = int(ws.metadata.name.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                idx = 0
            return (condition_true(ws.status.conditions,
                                   COND_INFERENCE_READY), -idx)
        return sorted(children, key=order)[: max(0, count)]

    def _mark_draining(self, ws: Workspace, flag: bool) -> None:
        def mutate(o):
            if flag:
                o.metadata.annotations[ANNOTATION_DRAINING] = "true"
            else:
                o.metadata.annotations.pop(ANNOTATION_DRAINING, None)
        try:
            update_with_retry(self.store, "Workspace",
                              ws.metadata.namespace, ws.metadata.name,
                              mutate)
        except Exception:
            logger.debug("drain mark failed for %s", ws.metadata.name,
                         exc_info=True)

    # -- warm pools ----------------------------------------------------

    def _ensure_warm(self, iset: InferenceSet, pol: AutoscalePolicy,
                     children: list[Workspace]) -> None:
        """Render the NodePools of the next ``warmPool`` replicas the
        moment pressure is entered, so the slices are booting while the
        stabilization window (and the Workspace create) still runs.
        The pools carry the real would-be replica names — when the
        Workspace materializes, the workspace reconciler's provision
        call finds them already there (and already warming)."""
        if self.provisioner is None or pol.warm_pool <= 0:
            return
        cap = self._replica_cap(iset, pol, children)
        used = {c.metadata.name for c in children}
        budget = max(0, min(pol.warm_pool, cap - len(children)))
        picked = []
        i = 0
        while len(picked) < budget:
            candidate = f"{iset.metadata.name}-{i}"
            i += 1
            if candidate not in used:
                picked.append((i - 1, candidate))
        for idx, owner in picked:
            try:
                ws = make_child_workspace(iset, idx)
                from kaito_tpu.controllers.workspace import plan_workspace

                _, plan, slice_spec = plan_workspace(self.store, ws)
            except Exception:
                logger.debug("warm plan failed for %s", owner,
                             exc_info=True)
                continue
            req = ProvisionRequest(
                owner_name=owner, owner_namespace=iset.metadata.namespace,
                slice_spec=slice_spec,
                num_slices=plan.num_slices * ws.resource.count,
                extra_labels=dict(ws.resource.label_selector))
            missing = any(
                self.store.try_get("NodePool", "", f"{owner}-slice-{k}")
                is None for k in range(req.num_slices))
            self.provisioner.provision(req)
            self._label_warm(iset, owner)
            if missing:
                record_event(
                    self.store, iset, "Normal", EVENT_WARM_PROVISIONED,
                    f"provisioned warm NodePool(s) for next replica "
                    f"{owner} ({req.num_slices} slice(s), topology "
                    f"{slice_spec.topology})")

    def _label_warm(self, iset: InferenceSet, owner: str) -> None:
        for pool in self.store.list("NodePool",
                                    labels={LABEL_OWNER: owner}):
            if pool.metadata.labels.get(LABEL_WARM_FOR):
                continue

            def mutate(p):
                p.metadata.labels[LABEL_WARM_FOR] = iset.metadata.name
            try:
                update_with_retry(self.store, "NodePool", "",
                                  pool.metadata.name, mutate)
            except Exception:
                pass

    def _warm_pools(self, iset: InferenceSet) -> list:
        """Warm pools = labelled for this set AND their replica
        Workspace still absent.  Pools whose replica materialized are
        owned for real: the warm label is stripped."""
        out = []
        for pool in self.store.list(
                "NodePool", labels={LABEL_WARM_FOR: iset.metadata.name}):
            owner = pool.metadata.labels.get(LABEL_OWNER, "")
            if owner and self.store.try_get(
                    "Workspace", iset.metadata.namespace, owner) is not None:
                def mutate(p):
                    p.metadata.labels.pop(LABEL_WARM_FOR, None)
                try:
                    update_with_retry(self.store, "NodePool", "",
                                      pool.metadata.name, mutate)
                except Exception:
                    pass
                continue
            out.append(pool)
        return out

    def _maybe_gc_warm(self, iset: InferenceSet, pol: AutoscalePolicy,
                       dwell: float) -> None:
        """Sustained non-pressure reclaims warm pools whose replica
        never materialized (the pressure that provisioned them
        resolved without the scale-up committing)."""
        if dwell < pol.warm_pool_gc_s:
            return
        reclaimed = []
        for pool in self._warm_pools(iset):
            self.store.delete("NodePool", "", pool.metadata.name)
            reclaimed.append(pool.metadata.name)
        if reclaimed:
            record_event(self.store, iset, "Normal", EVENT_WARM_RECLAIMED,
                         f"reclaimed {len(reclaimed)} warm NodePool(s): "
                         f"{', '.join(sorted(reclaimed))}")

    # -- shared plumbing -----------------------------------------------

    def _children(self, iset: InferenceSet) -> list[Workspace]:
        return self.store.list(
            "Workspace", iset.metadata.namespace,
            labels={LABEL_CREATED_BY_INFERENCESET: iset.metadata.name})

    def _replica_cap(self, iset: InferenceSet, pol: AutoscalePolicy,
                     children: list[Workspace]) -> int:
        cap = pol.max_replicas or _UNBOUNDED
        if iset.spec.node_count_limit:
            per = self._nodes_per_replica(iset, children)
            cap = min(cap, iset.spec.node_count_limit // per)
        return cap

    def _nodes_per_replica(self, iset: InferenceSet,
                           children: list[Workspace]) -> int:
        observed = [c.status.target_node_count for c in children
                    if c.status.target_node_count > 0]
        if observed:
            return max(observed)
        try:
            from kaito_tpu.controllers.workspace import plan_workspace

            ws = make_child_workspace(iset, 0)
            _, plan, _ = plan_workspace(self.store, ws)
            return max(1, plan.num_hosts * ws.resource.count)
        except Exception:
            return 1

    def _write_replicas(self, iset: InferenceSet, target: int) -> None:
        def mutate(o):
            o.spec.replicas = target
        update_with_retry(self.store, "InferenceSet",
                          iset.metadata.namespace, iset.metadata.name,
                          mutate)

    def _set_condition(self, iset: InferenceSet, status: str, reason: str,
                       message: str) -> None:
        """Write ``AutoscalerActive`` only on CHANGE (same zero-churn
        rule as the fleet plane's ScalingSignal writes)."""
        obj = self.store.try_get("InferenceSet", iset.metadata.namespace,
                                 iset.metadata.name)
        if obj is None:
            return
        cur = get_condition(obj.status.conditions, COND_AUTOSCALER_ACTIVE)
        if cur is not None and cur.status == status \
                and cur.reason == reason:
            return

        def mutate(o):
            set_condition(o.status.conditions, Condition(
                type=COND_AUTOSCALER_ACTIVE, status=status,
                reason=reason, message=message))
        try:
            update_with_retry(self.store, "InferenceSet",
                              iset.metadata.namespace, iset.metadata.name,
                              mutate)
        except Exception:
            logger.debug("AutoscalerActive write failed", exc_info=True)
