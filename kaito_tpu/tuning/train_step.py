"""Sharded training step.

The compute core of the tuning path (the reference delegates this to
``accelerate launch ... fine_tuning.py`` + HF Trainer,
``presets/workspace/tuning/text-generation/fine_tuning.py``): a jitted
forward/backward/update over the planner's mesh — dp/fsdp for batch,
tensor for megatron-style weight sharding, sequence for long-context
ring attention, expert for MoE — with per-layer rematerialization.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from kaito_tpu.engine.model import TransformerLM
from kaito_tpu.parallel.sharding import TRAIN_RULES, PartitionRules


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: dict
    opt_state: object
    step: jax.Array


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: jax.Array) -> jax.Array:
    """Masked next-token CE. logits [B,T,V] fp32; targets/mask [B,T]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def make_train_step(model: TransformerLM, optimizer: optax.GradientTransformation):
    """Build the jittable (state, batch) -> (state, metrics) step.

    batch: {"tokens": [B, T+1] int32, "mask": [B, T] float}; predicts
    tokens[:, 1:] from tokens[:, :-1].
    """

    def loss_fn(params, batch):
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
        logits = model.forward_train(params, inputs)
        return cross_entropy_loss(logits, targets, batch["mask"])

    def train_step(state: TrainState, batch) -> tuple[TrainState, dict]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(params=params, opt_state=opt_state,
                               step=state.step + 1)
        grad_norm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    return train_step


def param_shardings(model: TransformerLM, mesh: Mesh,
                    rules: PartitionRules = TRAIN_RULES, params=None):
    """NamedShardings for every param from its logical axes.

    When ``params`` is given, shardings follow ITS structure: leaves
    absent from the logical-axes tree (lora factors, quantized-weight
    sub-dicts) replicate — they are tiny or already per-layer stacked.
    """
    axes = model.param_logical_axes()
    if params is None:
        return jax.tree.map(
            lambda ax: NamedSharding(mesh, rules.spec(ax)),
            axes, is_leaf=lambda x: isinstance(x, tuple))

    def spec_for(path, leaf):
        node = axes
        for part in path:
            k = getattr(part, "key", None)
            if isinstance(node, dict) and k in node:
                node = node[k]
            else:
                return NamedSharding(mesh, P())
        if isinstance(node, tuple) and len(node) == getattr(leaf, "ndim", -1):
            return NamedSharding(mesh, rules.spec(node))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_train_state(model: TransformerLM, state: TrainState, mesh: Mesh,
                      rules: PartitionRules = TRAIN_RULES) -> TrainState:
    """Place params + optimizer state on the mesh (optimizer moments
    share the param sharding; scalars replicate)."""
    p_sh = param_shardings(model, mesh, rules, params=state.params)

    def place(x, sh):
        return jax.device_put(x, sh)

    params = jax.tree.map(place, state.params, p_sh)
    opt_state = _shard_opt_state(state.opt_state, state.params, p_sh, mesh)
    return TrainState(params=params, opt_state=opt_state,
                      step=jax.device_put(state.step, NamedSharding(mesh, P())))


def _shard_opt_state(opt_state, params, p_sh, mesh):
    """Shard optimizer-state leaves that mirror the param tree."""
    p_leaves = jax.tree.leaves(params)
    sh_leaves = jax.tree.leaves(p_sh, is_leaf=lambda x: isinstance(x, NamedSharding))
    shape_to_sh = {}
    for leaf, sh in zip(p_leaves, sh_leaves):
        shape_to_sh.setdefault(leaf.shape, sh)

    def place(x):
        if hasattr(x, "shape") and x.shape in shape_to_sh and x.ndim > 0:
            return jax.device_put(x, shape_to_sh[x.shape])
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree.map(place, opt_state)


def data_sharding(mesh: Mesh, rules: PartitionRules = TRAIN_RULES):
    return {
        "tokens": NamedSharding(mesh, rules.spec(("batch", None))),
        "mask": NamedSharding(mesh, rules.spec(("batch", None))),
    }
