from kaito_tpu.tuning.train_step import TrainState, make_train_step, shard_train_state  # noqa: F401
