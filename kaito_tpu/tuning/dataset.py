"""Dataset loading & formatting for tuning jobs.

Parity with the reference's dataset handling
(``presets/workspace/tuning/text-generation/cli.py`` DatasetConfig +
``fine_tuning.py`` formatting): jsonl/json/plain-text files from the
data dir, instruction/response or messages formats, tokenize, pack into
fixed-length examples with loss masks.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np


@dataclass
class DatasetConfig:
    data_dir: str = ""
    instruction_column: str = "instruction"
    response_column: str = "response"
    messages_column: str = "messages"
    context_column: str = "context"
    max_seq_len: int = 512
    train_split: float = 0.95
    shuffle_seed: int = 0


def _iter_records(data_dir: str) -> Iterator[dict]:
    for fname in sorted(os.listdir(data_dir)):
        path = os.path.join(data_dir, fname)
        if fname.endswith(".jsonl"):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield json.loads(line)
        elif fname.endswith(".json"):
            with open(path) as f:
                data = json.load(f)
            if isinstance(data, list):
                yield from data
        elif fname.endswith(".txt"):
            with open(path) as f:
                for para in f.read().split("\n\n"):
                    if para.strip():
                        yield {"text": para.strip()}


def format_record(rec: dict, cfg: DatasetConfig) -> tuple[str, str]:
    """Returns (prompt, response); response tokens carry the loss."""
    if cfg.messages_column in rec:
        msgs = rec[cfg.messages_column]
        prompt_parts, response = [], ""
        for m in msgs:
            if m.get("role") == "assistant":
                response = m.get("content", "")
            else:
                prompt_parts.append(f"<|{m.get('role','user')}|>\n{m.get('content','')}")
        return "\n".join(prompt_parts) + "\n<|assistant|>\n", response
    if cfg.instruction_column in rec:
        ctx = rec.get(cfg.context_column, "")
        prompt = rec[cfg.instruction_column] + (f"\n{ctx}" if ctx else "") + "\n"
        return prompt, str(rec.get(cfg.response_column, ""))
    return "", str(rec.get("text", ""))


def build_examples(tokenizer, cfg: DatasetConfig):
    """Tokenize + pad to max_seq_len. Returns dict of numpy arrays:
    tokens [N, T+1] and mask [N, T] (loss on response tokens only)."""
    eos = tokenizer.eos_token_id
    T = cfg.max_seq_len
    toks_out, mask_out = [], []
    for rec in _iter_records(cfg.data_dir):
        prompt, response = format_record(rec, cfg)
        p_ids = tokenizer.encode(prompt) if prompt else []
        r_ids = [t for t in tokenizer.encode(response)
                 if t != tokenizer.bos_token_id]
        ids = (p_ids + r_ids)[: T]
        if eos is not None and len(ids) < T:
            ids = ids + [eos]
        if len(ids) < 2:
            continue
        row = np.zeros(T + 1, np.int32)
        row[: len(ids)] = ids
        # loss mask over predicted positions: response tokens only
        mask = np.zeros(T, np.float32)
        start = max(len(p_ids) - 1, 0)
        mask[start: len(ids) - 1] = 1.0
        toks_out.append(row)
        mask_out.append(mask)
    if not toks_out:
        raise ValueError(f"no training records found in {cfg.data_dir}")
    tokens = np.stack(toks_out)
    masks = np.stack(mask_out)
    rng = np.random.RandomState(cfg.shuffle_seed)
    order = rng.permutation(len(tokens))
    tokens, masks = tokens[order], masks[order]
    n_train = max(1, int(len(tokens) * cfg.train_split))
    return ({"tokens": tokens[:n_train], "mask": masks[:n_train]},
            {"tokens": tokens[n_train:], "mask": masks[n_train:]})


def batches(data: dict, batch_size: int, seed: int = 0,
            drop_last: bool = False) -> Iterator[dict]:
    n = len(data["tokens"])
    rng = np.random.RandomState(seed)
    order = rng.permutation(n)
    for i in range(0, n, batch_size):
        idx = order[i: i + batch_size]
        if len(idx) < batch_size:
            if drop_last or len(idx) == 0:
                return
            # pad the final batch by repetition to keep shapes static
            idx = np.concatenate([idx, order[: batch_size - len(idx)]])
        yield {"tokens": data["tokens"][idx], "mask": data["mask"][idx]}
