"""Tuning job CLI — the in-pod entrypoint rendered by
``kaito_tpu.manifests.tuning_job`` (reference counterpart:
``accelerate launch ... fine_tuning.py`` with parsed dataclass args,
``presets/workspace/tuning/text-generation/{cli,parser}.py``)."""

from __future__ import annotations

import argparse
import logging

from kaito_tpu.tuning.lora import LoraConfig
from kaito_tpu.tuning.trainer import TrainConfig, Trainer


def parse_args(argv=None) -> TrainConfig:
    ap = argparse.ArgumentParser(prog="kaito-tpu-tune")
    ap.add_argument("--model", required=True)
    ap.add_argument("--method", default="lora", choices=["lora", "qlora", "full"])
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--mesh", default="")
    ap.add_argument("--config-file", default="")
    ap.add_argument("--learning-rate", type=float, default=2e-4)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=512)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--max-steps", type=int, default=0)
    ap.add_argument("--lora-r", type=int, default=8)
    ap.add_argument("--lora-alpha", type=int, default=16)
    ap.add_argument("--lora-targets", default="q,k,v,o")
    ap.add_argument("--checkpoint-every", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dtype", default="")
    args = ap.parse_args(argv)

    cfg = TrainConfig(
        model=args.model, method=args.method, data_dir=args.data_dir,
        output_dir=args.output_dir, learning_rate=args.learning_rate,
        batch_size=args.batch_size, max_seq_len=args.max_seq_len,
        num_epochs=args.num_epochs, max_steps=args.max_steps,
        checkpoint_every=args.checkpoint_every, seed=args.seed,
        lora=LoraConfig(r=args.lora_r, alpha=args.lora_alpha,
                        targets=tuple(t for t in args.lora_targets.split(",") if t)))
    if args.dtype:
        cfg.dtype = args.dtype
    if args.config_file:
        import yaml

        with open(args.config_file) as f:
            overrides = (yaml.safe_load(f) or {}).get("training", {})
        for k, v in overrides.items():
            k = k.replace("-", "_")
            if hasattr(cfg, k):
                setattr(cfg, k, v)
    return cfg


def main(argv=None):
    from kaito_tpu.utils.platform import apply_platform_env

    apply_platform_env()
    logging.basicConfig(level=logging.INFO)
    cfg = parse_args(argv)
    import jax

    if jax.devices()[0].platform not in ("cpu",) and not cfg.dtype:
        cfg.dtype = "bfloat16"
    result = Trainer(cfg).train()
    logging.info("training complete: %s", result)


if __name__ == "__main__":
    main()
