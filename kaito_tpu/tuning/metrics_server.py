"""Tuning metrics sidecar.

Parity: the reference's training-side metrics server
(``presets/workspace/tuning/text-generation/metrics_server.py:112``)
reporting progress on :5000 — ours serves the trainer's metrics file as
Prometheus text + JSON, plus host utilization.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kaito_tpu.tuning.trainer import METRICS_FILE, SENTINEL


def render_metrics(m: dict, done: bool) -> str:
    """The sidecar's Prometheus payload (series live in the same
    ``kaito:`` namespace as the engine's — docs/observability.md — so
    one scrape config covers both; the exposition suite round-trips
    this through the shared parser)."""
    lines = [
        "# HELP kaito:tuning_step Last trainer optimizer step",
        "# TYPE kaito:tuning_step gauge",
        f"kaito:tuning_step {m.get('step', 0)}",
        "# HELP kaito:tuning_loss Last reported training loss",
        "# TYPE kaito:tuning_loss gauge",
        f"kaito:tuning_loss {m.get('loss', 0.0)}",
        "# HELP kaito:tuning_tokens_per_second Trainer throughput",
        "# TYPE kaito:tuning_tokens_per_second gauge",
        f"kaito:tuning_tokens_per_second "
        f"{m.get('tokens_per_second', 0.0)}",
        "# HELP kaito:tuning_completed 1 once the job sentinel "
        "file exists",
        "# TYPE kaito:tuning_completed gauge",
        f"kaito:tuning_completed {1 if done else 0}",
    ]
    return "\n".join(lines) + "\n"


class Handler(BaseHTTPRequestHandler):
    results_dir = ""

    def log_message(self, *a):
        pass

    def _read(self) -> dict:
        try:
            with open(os.path.join(self.results_dir, METRICS_FILE)) as f:
                return json.load(f)
        except Exception:
            return {}

    def do_GET(self):
        if self.path == "/health":
            body = b'{"status": "ok"}'
            ctype = "application/json"
        elif self.path == "/metrics":
            m = self._read()
            done = os.path.exists(os.path.join(self.results_dir, SENTINEL))
            body = render_metrics(m, done).encode()
            ctype = "text/plain; version=0.0.4"
        elif self.path == "/progress":
            body = json.dumps(self._read()).encode()
            ctype = "application/json"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--results-dir", required=True)
    args = ap.parse_args(argv)
    handler = type("H", (Handler,), {"results_dir": args.results_dir})
    ThreadingHTTPServer(("0.0.0.0", args.port), handler).serve_forever()


if __name__ == "__main__":
    main()
