"""LoRA: low-rank adapter parameters over the stacked layer trees.

The JAX/TPU counterpart of the reference's PEFT usage
(``presets/workspace/tuning/text-generation/cli.py`` ExtLoraConfig +
``fine_tuning.py`` get_peft_model): adapter factors live as extra keys
in the layer stacks (``q_lora_a``/``q_lora_b`` ...), the model applies
them at the projection sites inside the layer scan (engine/nn.py
lora_delta), and only these keys train — the base stays frozen (and may
be int8 for QLoRA).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kaito_tpu.engine.model import TransformerLM

DEFAULT_TARGETS = ("q", "k", "v", "o")
ALL_TARGETS = ("q", "k", "v", "o", "gate", "up", "down")


@dataclass
class LoraConfig:
    r: int = 8
    alpha: int = 16
    targets: tuple[str, ...] = DEFAULT_TARGETS
    dropout: float = 0.0     # applied by the trainer on the lora path

    @property
    def scaling(self) -> float:
        return self.alpha / self.r


def add_lora_params(model: TransformerLM, params: dict, cfg: LoraConfig,
                    key: jax.Array) -> dict:
    """Return params with lora factors added to each layer stack.
    A ~ N(0, 1/r) on the input side, B = 0 (delta starts at zero)."""
    out = dict(params)
    for g in model.groups:
        stack = dict(params[g.name])
        specs = model._layer_specs(g.moe)
        for t in cfg.targets:
            if t not in specs:
                continue
            in_dim, out_dim = specs[t][0]
            ka = jax.random.fold_in(key, hash((g.name, t)) % 2**31)
            stack[f"{t}_lora_a"] = (
                jax.random.normal(ka, (g.count, in_dim, cfg.r), model.dtype)
                / np.sqrt(cfg.r))
            stack[f"{t}_lora_b"] = jnp.zeros((g.count, cfg.r, out_dim), model.dtype)
        out[g.name] = stack
    model.lora_scaling = cfg.scaling
    return out


def is_lora_path(path) -> bool:
    return any("lora" in str(getattr(p, "key", p)) for p in path)


def lora_mask(params: dict) -> dict:
    """Pytree of bools: True for trainable (lora) leaves — feeds
    optax.masked so the base stays frozen."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: is_lora_path(path), params)


def extract_adapter(params: dict) -> dict:
    """Only the lora leaves (the artifact we ship)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out: dict = {}
    for path, leaf in flat:
        keys = [str(getattr(p, "key", p)) for p in path]
        if any("lora" in k for k in keys):
            out["/".join(keys)] = np.asarray(leaf)
    return out


def apply_adapter(params: dict, adapter: dict) -> dict:
    """Insert saved lora leaves back into a param tree."""
    out = jax.tree.map(lambda x: x, params)  # fresh containers, shared leaves
    for flat_key, value in adapter.items():
        keys = flat_key.split("/")
        node = out
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = jnp.asarray(value)
    return out


def merge_lora(model: TransformerLM, params: dict) -> dict:
    """Fold deltas into the base weights for serving without lora
    compute: W' = W + scaling * A @ B. Removes the lora keys."""
    scaling = model.lora_scaling
    out = dict(params)
    for g in model.groups:
        stack = dict(out[g.name])
        for t in ALL_TARGETS:
            a = stack.pop(f"{t}_lora_a", None)
            b = stack.pop(f"{t}_lora_b", None)
            if a is None or b is None or t not in stack:
                continue
            base = stack[t]
            delta = jnp.einsum("lir,lro->lio", a, b) * scaling
            if isinstance(base, dict):  # quantized base: dequant + merge
                w = base["q8"].astype(delta.dtype) * base["scale"][..., None, :]
                stack[t] = w + delta
            else:
                stack[t] = base + delta
        out[g.name] = stack
    return out


# -- adapter artifact io ----------------------------------------------------

ADAPTER_WEIGHTS = "adapter.msgpack"
ADAPTER_CONFIG = "adapter_config.json"


def save_adapter(path: str, params: dict, cfg: LoraConfig, base_model: str):
    from flax import serialization

    os.makedirs(path, exist_ok=True)
    adapter = extract_adapter(params)
    with open(os.path.join(path, ADAPTER_WEIGHTS), "wb") as f:
        f.write(serialization.to_bytes(adapter))
    with open(os.path.join(path, ADAPTER_CONFIG), "w") as f:
        json.dump({"base_model": base_model, "r": cfg.r, "alpha": cfg.alpha,
                   "targets": list(cfg.targets), "format": "kaito-tpu-lora-v1"},
                  f, indent=2)


def load_adapter(path: str) -> tuple[dict, LoraConfig, str]:
    from flax import serialization

    with open(os.path.join(path, ADAPTER_CONFIG)) as f:
        meta = json.load(f)
    with open(os.path.join(path, ADAPTER_WEIGHTS), "rb") as f:
        adapter = serialization.msgpack_restore(f.read())
    cfg = LoraConfig(r=meta["r"], alpha=meta["alpha"],
                     targets=tuple(meta["targets"]))
    return adapter, cfg, meta.get("base_model", "")
