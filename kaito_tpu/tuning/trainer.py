"""The tuning trainer: LoRA/QLoRA/full fine-tuning on TPU meshes.

Replaces the reference's ``accelerate launch ... fine_tuning.py`` + HF
Trainer path (SURVEY.md §3.2): jitted fwd/bwd/update over the planner's
mesh, masked optimizer (only lora leaves train for lora/qlora), int8
base for qlora, Orbax checkpointing with resume — the checkpoint story
the reference lacks (its CheckpointCallback is commented out,
``cli.py:242-255``) — progress metrics to a JSON file the sidecar
serves, and the completion sentinel the pusher waits on.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kaito_tpu.engine.model import TransformerLM
from kaito_tpu.engine.tokenizer import load_tokenizer
from kaito_tpu.models.registry import get_model_by_name
from kaito_tpu.tuning import dataset as ds
from kaito_tpu.tuning.lora import LoraConfig, add_lora_params, lora_mask, save_adapter
from kaito_tpu.tuning.quant import quantize_base
from kaito_tpu.tuning.train_step import TrainState, cross_entropy_loss

logger = logging.getLogger(__name__)

SENTINEL = "fine_tuning_completed.txt"
METRICS_FILE = "training_metrics.json"


@dataclass
class TrainConfig:
    model: str = "tiny-llama-test"
    method: str = "lora"                  # lora | qlora | full
    data_dir: str = ""
    output_dir: str = ""
    lora: LoraConfig = field(default_factory=LoraConfig)
    learning_rate: float = 2e-4
    weight_decay: float = 0.0
    batch_size: int = 4
    max_seq_len: int = 512
    num_epochs: int = 1
    max_steps: int = 0                    # 0 = epochs decide
    warmup_steps: int = 10
    checkpoint_every: int = 50
    seed: int = 0
    dtype: str = "float32"


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None):
        self.cfg = cfg
        self.md = get_model_by_name(cfg.model)
        self.model = TransformerLM(self.md.arch, dtype=jnp.dtype(cfg.dtype))
        self.tokenizer = load_tokenizer(self.md.hf_id, self.md.arch.vocab_size)
        self.mesh = mesh
        if mesh is not None and mesh.shape.get("sequence", 1) > 1:
            self.model.ring = (mesh, "sequence")

        key = jax.random.PRNGKey(cfg.seed)
        params = self.model.init_params(key)
        if cfg.method in ("lora", "qlora"):
            if cfg.method == "qlora":
                params = quantize_base(self.model, params)
            params = add_lora_params(self.model, params, cfg.lora,
                                     jax.random.fold_in(key, 1))
            mask = lora_mask(params)
        else:
            mask = jax.tree.map(lambda _: True, params)

        # partition by leaf index: grads are taken only w.r.t. trainable
        # leaves, so frozen int8 bases never meet value_and_grad
        flat, self._treedef = jax.tree_util.tree_flatten(params)
        mask_flat = jax.tree_util.tree_leaves(mask)
        self._train_idx = tuple(i for i, m in enumerate(mask_flat) if m)

        schedule = optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, cfg.warmup_steps,
            max(cfg.max_steps or 1000, cfg.warmup_steps + 1))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adamw(schedule, weight_decay=cfg.weight_decay))
        train_leaves = [flat[i] for i in self._train_idx]
        self.state = TrainState(params=params,
                                opt_state=self.optimizer.init(train_leaves),
                                step=jnp.zeros((), jnp.int32))
        if self.mesh is not None:
            from kaito_tpu.tuning.train_step import shard_train_state

            self.state = shard_train_state(self.model, self.state, self.mesh)
        self._step_fn = jax.jit(self._make_step(), donate_argnums=(0,))

    def _make_step(self):
        model, optimizer = self.model, self.optimizer
        treedef, train_idx = self._treedef, self._train_idx

        def loss_fn(train_leaves, all_leaves, batch):
            leaves = list(all_leaves)
            for i, leaf in zip(train_idx, train_leaves):
                leaves[i] = leaf
            params = jax.tree_util.tree_unflatten(treedef, leaves)
            logits = model.forward_train(params, batch["tokens"][:, :-1])
            return cross_entropy_loss(logits, batch["tokens"][:, 1:],
                                      batch["mask"])

        def step(state: TrainState, batch):
            flat = jax.tree_util.tree_leaves(state.params)
            train = [flat[i] for i in train_idx]
            loss, grads = jax.value_and_grad(loss_fn)(train, flat, batch)
            updates, opt_state = optimizer.update(grads, state.opt_state, train)
            new_train = optax.apply_updates(train, updates)
            leaves = list(flat)
            for i, leaf in zip(train_idx, new_train):
                leaves[i] = leaf
            params = jax.tree_util.tree_unflatten(treedef, leaves)
            return (TrainState(params=params, opt_state=opt_state,
                               step=state.step + 1),
                    {"loss": loss, "grad_norm": optax.global_norm(grads)})

        return step

    # -- checkpointing (Orbax) -----------------------------------------

    def _ckpt_dir(self) -> str:
        return os.path.join(self.cfg.output_dir, "checkpoints")

    def save_checkpoint(self, step: int) -> None:
        import orbax.checkpoint as ocp

        path = os.path.abspath(os.path.join(self._ckpt_dir(), str(step)))
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(path, {"params": self.state.params,
                              "opt_state": self.state.opt_state,
                              "step": np.asarray(step)}, force=True)
        logger.info("checkpoint saved at step %d", step)

    def restore_latest(self) -> int:
        import orbax.checkpoint as ocp

        d = self._ckpt_dir()
        if not os.path.isdir(d):
            return 0
        steps = sorted((int(s) for s in os.listdir(d) if s.isdigit()),
                       reverse=True)
        for step in steps:
            try:
                with ocp.PyTreeCheckpointer() as ckptr:
                    restored = ckptr.restore(os.path.abspath(os.path.join(d, str(step))))
                self.state = TrainState(
                    params=jax.tree.map(jnp.asarray, restored["params"]),
                    opt_state=jax.tree.map(jnp.asarray, restored["opt_state"]),
                    step=jnp.asarray(step, jnp.int32))
                logger.info("resumed from checkpoint step %d", step)
                return step
            except Exception:
                logger.exception("failed restoring step %d; trying older", step)
        return 0

    # -- the loop -------------------------------------------------------

    def _write_metrics(self, payload: dict) -> None:
        if not self.cfg.output_dir:
            return
        os.makedirs(self.cfg.output_dir, exist_ok=True)
        tmp = os.path.join(self.cfg.output_dir, METRICS_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, os.path.join(self.cfg.output_dir, METRICS_FILE))

    def train(self) -> dict:
        cfg = self.cfg
        dcfg = ds.DatasetConfig(data_dir=cfg.data_dir,
                                max_seq_len=cfg.max_seq_len)
        train_data, eval_data = ds.build_examples(self.tokenizer, dcfg)
        logger.info("dataset: %d train / %d eval examples",
                    len(train_data["tokens"]), len(eval_data["tokens"]))

        start_step = self.restore_latest()
        step = start_step
        t0 = time.monotonic()
        losses: list[float] = []
        done = False
        for epoch in range(cfg.num_epochs):
            for batch in ds.batches(train_data, cfg.batch_size,
                                    seed=cfg.seed + epoch):
                if step < start_step:
                    step += 1
                    continue  # fast-forward through resumed steps
                jb = {k: jnp.asarray(v) for k, v in batch.items()}
                if self.mesh is not None:
                    from kaito_tpu.tuning.train_step import data_sharding

                    ds_sh = data_sharding(self.mesh)
                    jb = {k: jax.device_put(v, ds_sh[k]) for k, v in jb.items()}
                self.state, metrics = self._step_fn(self.state, jb)
                step += 1
                loss = float(metrics["loss"])
                losses.append(loss)
                if step % 10 == 0 or step == 1:
                    logger.info("step %d loss %.4f", step, loss)
                self._write_metrics({
                    "step": step, "loss": loss,
                    "tokens_per_second": cfg.batch_size * cfg.max_seq_len
                    * max(step - start_step, 1) / max(time.monotonic() - t0, 1e-6),
                    "epoch": epoch,
                })
                if cfg.checkpoint_every and step % cfg.checkpoint_every == 0:
                    self.save_checkpoint(step)
                if cfg.max_steps and step >= cfg.max_steps:
                    done = True
                    break
            if done:
                break

        result = {"steps": step, "final_loss": losses[-1] if losses else None,
                  "mean_last10": float(np.mean(losses[-10:])) if losses else None}
        if cfg.output_dir:
            os.makedirs(cfg.output_dir, exist_ok=True)
            if cfg.method in ("lora", "qlora"):
                save_adapter(os.path.join(cfg.output_dir, "adapter"),
                             self.state.params, cfg.lora, cfg.model)
            self.save_checkpoint(step)
            with open(os.path.join(cfg.output_dir, SENTINEL), "w") as f:
                f.write(json.dumps(result))
        return result
