"""Weight quantization for QLoRA bases.

The reference reaches for BitsAndBytes 4-bit (``cli.py``
QuantizationConfig); on TPU the sweet spot is int8 per-out-channel
symmetric quantization: the MXU has native int8 throughput, XLA fuses
the dequant into the matmul, and HBM holds half the bytes.  Weights
become ``{"q8": int8[in,out], "scale": f32[out]}`` leaves that
``engine.nn.linear`` consumes transparently.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kaito_tpu.engine.model import TransformerLM

QUANT_TARGETS = ("q", "k", "v", "o", "gate", "up", "down")


def quantize_weight(w: jax.Array) -> dict:
    """Per-out-channel symmetric int8 over the last dim.
    w: [..., in, out] -> q8 same shape + scale [..., out]."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return {"q8": q, "scale": scale}


def dequantize_weight(qt: dict, dtype=jnp.bfloat16) -> jax.Array:
    return (qt["q8"].astype(jnp.float32) * qt["scale"][..., None, :]).astype(dtype)


def quantize_base(model: TransformerLM, params: dict) -> dict:
    """Quantize the dense projection weights of every layer stack
    (embeddings, norms, MoE experts stay bf16 in round 1)."""
    out = dict(params)
    for g in model.groups:
        stack = dict(params[g.name])
        for t in QUANT_TARGETS:
            w = stack.get(t)
            if w is None or isinstance(w, dict):
                continue
            stack[t] = quantize_weight(w)
        out[g.name] = stack
    return out
