"""Logical-axis sharding rules: how parameter/activation dimensions map
onto mesh axes.

The engine and trainer annotate every array with *logical* axis names
("vocab", "heads", "intermediate", ...); these rules translate them to
``jax.sharding.PartitionSpec`` over the planned mesh.  This is the
GSPMD-native replacement for the reference's flag plumbing — instead of
telling vLLM ``--tensor-parallel-size``, the partitioning is carried by
the arrays themselves and XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence, Union

from jax.sharding import PartitionSpec

AxisAssignment = Union[None, str, tuple[str, ...]]


class PartitionRules:
    """Ordered logical-name → mesh-axis mapping."""

    def __init__(self, rules: Mapping[str, AxisAssignment]):
        self.rules = dict(rules)

    def assignment(self, logical: Optional[str]) -> AxisAssignment:
        if logical is None:
            return None
        return self.rules.get(logical)

    def spec(self, logical_axes: Sequence[Optional[str]]) -> PartitionSpec:
        return logical_to_pspec(logical_axes, self)


def logical_to_pspec(
    logical_axes: Sequence[Optional[str]], rules: PartitionRules
) -> PartitionSpec:
    parts: list[AxisAssignment] = []
    used: set[str] = set()
    for name in logical_axes:
        a = rules.assignment(name)
        if a is None:
            parts.append(None)
            continue
        axes = (a,) if isinstance(a, str) else tuple(a)
        fresh = tuple(x for x in axes if x not in used)
        used.update(fresh)
        if not fresh:
            parts.append(None)
        elif len(fresh) == 1:
            parts.append(fresh[0])
        else:
            parts.append(fresh)
    # Trim trailing Nones for canonical specs.
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


# Serving (inference): Megatron-style TP. Weights are sharded on the
# head/intermediate/vocab dimensions over the tensor axis; activations
# batch over data; expert stacks place over the expert axis (EP — the
# serving counterpart of the planner's tier-5 expert carve-out), with
# their intermediate dim still on tensor so TP and EP compose.
SERVE_RULES = PartitionRules({
    "batch": "data",
    "vocab": "tensor",
    "embed": None,
    "heads": "tensor",
    "kv_heads": "tensor",
    "head_dim": None,
    "intermediate": "tensor",
    "expert": "expert",
    "layers": None,
    "kv_pages": None,
    "seq": None,
})

# Collective-compute overlap (docs/multichip.md): the decode linears
# whose CONTRACTION axis is tensor-sharded — attention-out contracts
# "heads", MLP-down contracts "intermediate" — are the row-parallel
# projections whose output all-reduce the pipelined ring decomposes.
ROW_PARALLEL_CONTRACTIONS: tuple[str, ...] = ("heads", "intermediate")


def ring_axis(rules: PartitionRules,
              contractions: Sequence[str] = ROW_PARALLEL_CONTRACTIONS
              ) -> Optional[str]:
    """Mesh axis the pipelined decode collectives ring over, or None.

    The overlap path replaces the row-parallel projections' implicit
    GSPMD all-reduce with explicit ``ppermute`` hops, so it needs ONE
    concrete mesh axis that shards every row-parallel contraction dim
    the same way.  Under SERVE_RULES that is "tensor"; rules that split
    the contractions across different axes (or don't shard them) have
    no ring and the caller keeps the unoverlapped path.
    """
    axes = set()
    for name in contractions:
        a = rules.assignment(name)
        if a is None:
            return None
        axes.update((a,) if isinstance(a, str) else tuple(a))
    if len(axes) != 1:
        return None
    return next(iter(axes))


# Training: FSDP shards the non-TP weight dimension; batch spreads over
# (data, fsdp); sequence axis shards the length dim for ring attention.
TRAIN_RULES = PartitionRules({
    "batch": ("data", "fsdp"),
    "vocab": "tensor",
    "embed": "fsdp",
    "heads": "tensor",
    "kv_heads": "tensor",
    "head_dim": None,
    "intermediate": "tensor",
    "expert": "expert",
    "layers": None,
    "seq": "sequence",
})
