"""Pipeline-parallel serving executor (shard_map + ppermute).

The serving side of the planner's tier 3 (``pipeline`` mesh axis) — the
TPU-native counterpart of the reference's multi-node vLLM serving
(``--pipeline-parallel-size`` + Ray executor,
/root/reference/pkg/model/interface.go:519-560).  Where the reference
splits layers across node boundaries and lets Ray drive per-stage
processes, here the model's scanned layer stack reshapes to
``[S, L/S, ...]`` and shards over the pipeline axis of one jitted SPMD
program; the paged KV cache shards the same way, so every stage owns
the KV pages for its own layers and no KV ever crosses a stage
boundary — only the [mb, 1, E] activations move, via ``ppermute``.

Decode runs the GPipe schedule: the decode batch splits into M
microbatches that stream through the stage ring in M + S - 1 ticks, so
at steady state every stage computes a different microbatch.  Prefill
flows one request through the ring (a single-request prefill is
inherently sequential; stages overlap across *ticks* instead).

TP composes *inside* each stage (the reference's tier 3 is exactly
TP-within-node × PP-across-nodes, interface.go:514-530): the mesh
carries a ``tensor`` axis alongside ``pipeline``, the staged weights
keep their Megatron shardings (SERVE_RULES) on that axis, and the
shard_map is *partial-manual* — only the pipeline axis is manual
(explicit ``ppermute`` ring); the tensor axis stays auto, so GSPMD
inserts the TP collectives inside each stage exactly as it does for
the flat-TP engine.

EP composes inside each stage the same way TP does (the expert axis
stays auto, so each stage's expert stacks place over its own devices),
and per-request LoRA stacks split alongside the layer stacks (no
merge-into-base under PP).

Scope: homogeneous single-group layer stacks (no MLA), global
attention (no sliding-window scan flags).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from kaito_tpu.engine.kv_cache import KVCache
from kaito_tpu.engine.model import TransformerLM
from kaito_tpu.parallel.pipeline import split_stage_params


def _shard_map(f, *, mesh, in_specs, out_specs, axis_names):
    """Partial-manual shard_map across jax versions: ``jax.shard_map``
    (axis_names/check_vma) where it exists, else the experimental
    entry, where manual-on-one-axis spells ``auto=`` (the complement
    set) and replication checking spells ``check_rep``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=axis_names,
                             check_vma=False)
    from jax.experimental.shard_map import shard_map

    auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False, auto=auto)


class PipelineServeExecutor:
    """Builds stage-sharded decode/prefill step functions for the engine."""

    def __init__(self, model: TransformerLM, mesh: Mesh,
                 num_microbatches: int = 4, axis: str = "pipeline"):
        if model.is_mla:
            raise ValueError("pipeline-parallel serving does not cover "
                             "MLA models yet")
        if model.arch.sliding_window:
            raise ValueError("pipeline-parallel serving v1 does not cover "
                             "sliding-window attention")
        if len(model.groups) != 1:
            raise ValueError(
                "pipeline-parallel serving needs a homogeneous layer "
                f"stack; {model.md_name if hasattr(model, 'md_name') else ''}"
                f" has {len(model.groups)} layer groups")
        self.model = model
        self.mesh = mesh
        self.axis = axis
        self.num_stages = mesh.shape[axis]
        self.tp = int(mesh.shape.get("tensor", 1))
        # EP composes inside each stage exactly like TP: the expert axis
        # stays on the AUTO side of the partial-manual shard_map, so
        # GSPMD places each stage's expert stacks over its own devices
        # (the flat engine's EP, per stage)
        self.ep = int(mesh.shape.get("expert", 1))
        (self.group,) = model.groups
        if model.arch.num_layers % self.num_stages:
            raise ValueError(f"{model.arch.num_layers} layers do not split "
                             f"into {self.num_stages} stages")
        self.num_microbatches = num_microbatches

    # ------------------------------------------------------------------
    # Layout
    # ------------------------------------------------------------------

    def _param_specs(self, staged_params: dict) -> dict:
        """shard_map in_specs: MANUAL axes only.  The stage dim of each
        layer stack is manual over the pipeline axis; everything else is
        unconstrained here — tensor sharding rides the arrays' own
        placements through the auto axis."""
        gname = self.group.name
        return {
            k: (jax.tree.map(lambda _: P(self.axis), v)
                if k in (gname, "serve_lora")
                else jax.tree.map(lambda _: P(), v))
            for k, v in staged_params.items()
        }

    def _placement_shardings(self, staged_params: dict) -> dict:
        """device_put shardings: pipeline on the stage dim AND the
        Megatron tensor axes from SERVE_RULES on the weight dims, so the
        auto (GSPMD) side of the partial-manual shard_map sees the same
        TP layout the flat-TP engine uses."""
        from kaito_tpu.parallel.sharding import SERVE_RULES

        gname = self.group.name
        axes = self.model.param_logical_axes()

        def leaf(ax, prefix=()):
            if self.tp * self.ep <= 1:
                return NamedSharding(
                    self.mesh, P(*prefix) if prefix else P())
            return NamedSharding(
                self.mesh, P(*prefix, *tuple(SERVE_RULES.spec(ax))))

        def entry(name, v, ax_tree, prefix=()):
            from kaito_tpu.engine.quant import (qtensor_kind,
                                                qtensor_logical_axes)

            ax = ax_tree[name]
            if isinstance(v, dict):     # QTensor {"q8"|"q4", "scale"}
                return {kk: leaf(aa, prefix)
                        for kk, aa in qtensor_logical_axes(
                            ax, qtensor_kind(v) or "int8").items()}
            return leaf(ax, prefix)

        out = {}
        for k, v in staged_params.items():
            if k == gname:
                out[k] = {name: entry(name, sub, axes[gname],
                                      prefix=(self.axis,))
                          for name, sub in v.items()}
            elif k == "serve_lora":
                # adapter factors: stage dim on pipeline, tiny factor
                # dims replicated (same as the flat engine's P() layout)
                out[k] = jax.tree.map(
                    lambda _: NamedSharding(self.mesh, P(self.axis)), v)
            elif k in axes:
                out[k] = entry(k, v, axes)
            else:
                out[k] = jax.tree.map(
                    lambda _: NamedSharding(self.mesh, P()), v)
        return out

    def stage_params(self, params: dict) -> dict:
        """[L, ...] layer stacks -> [S, L/S, ...] sharded over the
        pipeline axis (and the tensor axis per SERVE_RULES); top-level
        params keep their TP sharding and replicate over pipeline."""
        staged = split_stage_params(self.model, params, self.num_stages)
        return jax.device_put(staged, self._placement_shardings(staged))

    def stage_cache(self, cache: KVCache) -> KVCache:
        """[L, pages, ps, H, D] -> [S, L/S, pages, ps, H, D] sharded over
        the pipeline axis (each stage owns its layers' KV), with the
        kv-head dim on tensor when it divides (same rule as the flat-TP
        engine's _cache_sharding)."""
        S = self.num_stages
        spec = [self.axis, None, None, None, None, None]
        if self.tp > 1 and self.model.arch.kv_cache_heads > 1 \
                and self.model.arch.kv_cache_heads % self.tp == 0:
            spec[4] = "tensor"
        sh = NamedSharding(self.mesh, P(*spec))

        def split(a):
            return jax.device_put(
                a.reshape((S, a.shape[0] // S) + a.shape[1:]), sh)

        return KVCache(k=split(cache.k), v=split(cache.v))

    def _local_view(self, params: dict, ck, cv):
        """Inside shard_map: strip the stage dim from this stage's shard."""
        gname = self.group.name
        local_params = {**params,
                        gname: jax.tree.map(lambda v: v[0], params[gname])}
        if "serve_lora" in params:
            local_params["serve_lora"] = jax.tree.map(
                lambda v: v[0], params["serve_lora"])
        return local_params, ck[0], cv[0]

    # ------------------------------------------------------------------
    # Decode (GPipe microbatching)
    # ------------------------------------------------------------------

    def build_decode_fn(self):
        model, axis = self.model, self.axis
        S, M = self.num_stages, self.num_microbatches
        E = model.arch.hidden_size
        V = model.arch.vocab_size
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def local_decode(params, ck, cv, tokens, positions, page_tables,
                         active, adapter_ids):
            p = jax.lax.axis_index(axis)
            local_params, ck_l, cv_l = self._local_view(params, ck, cv)
            B = tokens.shape[0]
            mb = B // M
            pos = positions.reshape(M, mb)
            pts = page_tables.reshape(M, mb, -1)
            act = active.reshape(M, mb)
            aids = adapter_ids.reshape(M, mb)
            # embed once per microbatch (only stage 0 consumes it; the
            # gather is cheap enough to not gate on p == 0)
            x0_all = model._embed(local_params,
                                  tokens.reshape(M, mb)[:, :, None])

            def tick(carry, t):
                recv, ck_l, cv_l, acc = carry
                i_rel = t - p
                valid = (i_rel >= 0) & (i_rel < M)
                i = jnp.clip(i_rel, 0, M - 1)
                x_in = jnp.where(p == 0, x0_all[i], recv)
                cache_l = KVCache(k=ck_l, v=cv_l)
                # invalid (warm-up/drain) ticks mask active so their
                # garbage KV lands on the null page
                x_out, cache_l = model._run_layers(
                    local_params, cache_l, x_in, "decode",
                    positions=pos[i][:, None], page_tables=pts[i],
                    lengths=pos[i] + 1, true_lens=None,
                    active=act[i] & valid, adapter_ids=aids[i])
                ck_l, cv_l = cache_l.k, cache_l.v
                # final-norm + vocab projection only on the last stage's
                # valid ticks — everywhere else the accumulator stays 0
                use = valid & (p == S - 1)
                lg = jax.lax.cond(
                    use,
                    lambda x: model._logits(
                        local_params,
                        model._norm(x, local_params, "final_norm")[:, 0]
                    ).astype(jnp.float32),
                    lambda x: jnp.zeros((mb, V), jnp.float32),
                    x_out)
                acc = acc.at[i].set(jnp.where(use, lg, acc[i]))
                sent = jax.lax.ppermute(x_out, axis, fwd)
                return (sent, ck_l, cv_l, acc), None

            recv0 = jnp.zeros((mb, 1, E), model.dtype)
            acc0 = jnp.zeros((M, mb, V), jnp.float32)
            (_, ck_l, cv_l, acc), _ = jax.lax.scan(
                tick, (recv0, ck_l, cv_l, acc0), jnp.arange(S + M - 1))
            # only the last stage wrote logits; psum replicates them
            logits = jax.lax.psum(acc, axis)
            return ck_l[None], cv_l[None], logits.reshape(B, V)

        ax = self.axis
        sharded = None

        def decode(params, cache, tokens, positions, page_tables, active,
                   adapter_ids=None):
            nonlocal sharded
            if sharded is None:
                specs = self._param_specs(params)
                sharded = _shard_map(
                    local_decode, mesh=self.mesh,
                    in_specs=(specs, P(ax), P(ax), P(), P(), P(), P(), P()),
                    out_specs=(P(ax), P(ax), P()),
                    axis_names={ax})
            if adapter_ids is None:
                adapter_ids = jnp.zeros(tokens.shape[:1], jnp.int32)
            k, v, logits = sharded(params, cache.k, cache.v, tokens,
                                   positions, page_tables, active,
                                   adapter_ids)
            return KVCache(k=k, v=v), logits

        return decode

    # ------------------------------------------------------------------
    # Prefill (one request through the ring)
    # ------------------------------------------------------------------

    def build_prefill_fn(self, with_context: bool):
        model, axis = self.model, self.axis
        S = self.num_stages
        E = model.arch.hidden_size
        V = model.arch.vocab_size
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def local_prefill(params, ck, cv, tokens, true_lens, page_tables,
                          start_pos, adapter_ids):
            p = jax.lax.axis_index(axis)
            local_params, ck_l, cv_l = self._local_view(params, ck, cv)
            B, T = tokens.shape
            rel = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
            positions = rel + start_pos[:, None] if with_context else rel
            x0 = model._embed(local_params, tokens)

            def tick(carry, t):
                recv, ck_l, cv_l, acc = carry
                valid = t == p               # stage p's turn at tick p
                x_in = jnp.where(p == 0, x0, recv)
                # inactive ticks zero true_lens: garbage KV -> null page
                tl = jnp.where(valid, true_lens, 0)
                cache_l = KVCache(k=ck_l, v=cv_l)
                x_out, cache_l = model._run_layers(
                    local_params, cache_l, x_in, "prefill",
                    positions=positions, page_tables=page_tables,
                    lengths=tl, true_lens=tl, active=None,
                    start_pos=start_pos if with_context else None,
                    adapter_ids=adapter_ids)
                ck_l, cv_l = cache_l.k, cache_l.v
                use = valid & (p == S - 1)

                def final(x):
                    h = model._norm(x, local_params, "final_norm")
                    last = jnp.take_along_axis(
                        h, jnp.maximum(true_lens - 1, 0)[:, None, None]
                        .astype(jnp.int32), axis=1)[:, 0]
                    return model._logits(local_params,
                                         last).astype(jnp.float32)

                lg = jax.lax.cond(
                    use, final, lambda x: jnp.zeros((B, V), jnp.float32),
                    x_out)
                acc = jnp.where(use, lg, acc)
                sent = jax.lax.ppermute(x_out, axis, fwd)
                return (sent, ck_l, cv_l, acc), None

            recv0 = jnp.zeros((B, T, E), model.dtype)
            acc0 = jnp.zeros((B, V), jnp.float32)
            (_, ck_l, cv_l, acc), _ = jax.lax.scan(
                tick, (recv0, ck_l, cv_l, acc0), jnp.arange(S))
            logits = jax.lax.psum(acc, axis)
            return ck_l[None], cv_l[None], logits

        ax = self.axis
        sharded = None

        def prefill(params, cache, tokens, true_lens, page_tables,
                    start_pos=None, adapter_ids=None):
            nonlocal sharded
            if sharded is None:
                specs = self._param_specs(params)
                sharded = _shard_map(
                    local_prefill, mesh=self.mesh,
                    in_specs=(specs, P(ax), P(ax), P(), P(), P(), P(), P()),
                    out_specs=(P(ax), P(ax), P()),
                    axis_names={ax})
            if start_pos is None:
                start_pos = jnp.zeros((tokens.shape[0],), jnp.int32)
            if adapter_ids is None:
                adapter_ids = jnp.zeros((tokens.shape[0],), jnp.int32)
            k, v, logits = sharded(params, cache.k, cache.v, tokens,
                                   true_lens, page_tables, start_pos,
                                   adapter_ids)
            return KVCache(k=k, v=v), logits

        return prefill
