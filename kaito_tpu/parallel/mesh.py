"""Device-mesh construction and multi-host rendezvous.

Replaces the reference's Ray cluster bootstrap
(``pkg/model/interface.go:534`` buildMultiNodeRayCommand +
``multi-node-serving.sh``): on TPU the distributed runtime is JAX's own
— worker 0 is the coordinator (the StatefulSet-ordinal-0 pod, reachable
via the headless service DNS exactly like the reference's Ray leader),
every process calls ``jax.distributed.initialize``, and GSPMD
collectives replace NCCL groups.
"""

from __future__ import annotations

import math
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from kaito_tpu.parallel.plan import MeshSpec


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    """Materialize a MeshSpec onto real devices.

    Axis sizes must multiply to the device count; ``mesh_utils`` lays
    the innermost (tensor) axis along physically contiguous ICI rings.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if spec.num_devices != n:
        raise ValueError(
            f"mesh {spec} wants {spec.num_devices} devices, have {n}")
    try:
        dev_array = mesh_utils.create_device_mesh(spec.shape, devices=devices)
    except (ValueError, AssertionError):
        dev_array = np.asarray(devices).reshape(spec.shape)
    return Mesh(dev_array, spec.names)


def fit_mesh_spec(spec: MeshSpec, num_devices: int) -> MeshSpec:
    """Clamp a planned mesh to an available device count, preserving the
    tensor axis first (tests and dry-runs run on fewer virtual devices
    than the plan's slice).  Axes shrink along their DIVISORS (a 6-wide
    axis steps 6→3→1, not 6→3→1-via-floor-halving with silent
    remainders), and any degradation is logged."""
    import logging

    sizes = dict(spec.axes)
    total = math.prod(sizes.values())
    if total == num_devices:
        return spec
    # Shrink axes outermost-first until the product fits.
    from kaito_tpu.parallel.plan import _largest_divisor_leq

    order = [n for n, _ in spec.axes]
    for name in order:
        while total > num_devices and sizes[name] > 1:
            s = sizes[name]
            # the largest divisor of s that brings the product within
            # the device budget in ONE step (never skipping a divisor
            # that fits exactly, e.g. fsdp=12 onto 4 devices -> 4)
            cap = max(1, s * num_devices // total)
            d = _largest_divisor_leq(s, cap) if cap < s else s
            if d == s:
                d = _largest_divisor_leq(s, s - 1)
            sizes[name] = d
            total = total // s * d
    # Grow data axis if devices remain.
    if total < num_devices and num_devices % total == 0:
        sizes["data"] = sizes.get("data", 1) * (num_devices // total)
        total = num_devices
    fitted = MeshSpec(axes=tuple((n, sizes[n]) for n, _ in spec.axes))
    if fitted.axes != spec.axes:
        logging.getLogger(__name__).warning(
            "mesh %s does not fit %d devices; degraded to %s",
            spec, num_devices, fitted)
    return fitted


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous from pod ordinals.

    Mirrors the reference's leader bootstrap: pod-0's headless-service
    DNS is the coordinator (``pkg/utils/common.go:229`` computes
    ``<ws>-0.<ws>-headless.<ns>.svc.cluster.local`` for Ray; we reuse the
    same convention for the JAX coordinator).  On GKE TPU slices the
    defaults come from the injected ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``
    env; explicit args win (for tests).
    """
    if num_processes is None:
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        num_processes = len(hostnames.split(",")) if hostnames else 1
    if num_processes <= 1:
        return
    if process_id is None:
        process_id = int(os.environ.get("TPU_WORKER_ID", "0"))
    if coordinator_address is None:
        # manifests inject KAITO_COORDINATOR (pod-0 headless DNS); fall
        # back to hostname-derived for bare GKE TPU slices
        coordinator_address = os.environ.get("KAITO_COORDINATOR", "")
        if not coordinator_address:
            host = os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")[0]
            coordinator_address = f"{host}:8476"
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
