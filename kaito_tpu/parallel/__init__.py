from kaito_tpu.parallel.plan import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPELINE,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
    MeshSpec,
    ParallelPlan,
    plan_parallelism,
)
from kaito_tpu.parallel.sharding import (  # noqa: F401
    PartitionRules,
    SERVE_RULES,
    TRAIN_RULES,
    logical_to_pspec,
)
