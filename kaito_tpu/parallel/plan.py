"""Parallelism planner: model + chip generation → JAX device mesh spec.

The TPU-native redesign of the reference's parallelism tiering
(``pkg/model/interface.go:500`` configureParallelism): where the
reference picks ``--data-parallel-size``/``--tensor-parallel-size``/
``--pipeline-parallel-size`` flags for vLLM and bootstraps Ray, we emit
a named device-mesh spec (data/fsdp/expert/sequence/tensor axes, plus a
pipeline axis over DCN for multi-slice) that the engine and trainer jit
over with GSPMD shardings.

Tiering, TPU-first (SURVEY.md §2.3 "TPU-native mapping"):

1. model fits one chip           -> pure DP (data axis = chips)
2. model fits one slice          -> TP over ICI across the whole slice
                                    (TPU ICI makes slice-wide TP viable
                                    where GPUs needed PP between hosts)
3. model exceeds largest slice   -> PP over DCN between slices, TP inside
4. long-context training/serving -> sequence axis (ring attention over ICI)
5. MoE                           -> expert axis carved out of the TP group
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

from kaito_tpu.estimator.estimator import SliceEstimate, estimate_slice, weight_bytes
from kaito_tpu.models.metadata import ModelMetadata
from kaito_tpu.sku.catalog import TPUChipSpec, topology_chips

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_SEQUENCE = "sequence"
AXIS_TENSOR = "tensor"
AXIS_PIPELINE = "pipeline"

# Mesh axis order: outermost (DCN-adjacent) first, tensor innermost so
# TP collectives ride the fastest contiguous ICI rings.
MESH_AXIS_ORDER = (AXIS_PIPELINE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_SEQUENCE, AXIS_TENSOR)


@dataclass(frozen=True)
class MeshSpec:
    """Named logical mesh. Sizes multiply to the device count."""

    axes: tuple[tuple[str, int], ...]

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(s for _, s in self.axes)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    def size(self, name: str) -> int:
        for n, s in self.axes:
            if n == name:
                return s
        return 1

    def __str__(self) -> str:
        return "x".join(f"{n}:{s}" for n, s in self.axes)


def make_mesh_spec(**sizes: int) -> MeshSpec:
    """Build a MeshSpec in canonical axis order, keeping size-1 axes so
    jitted code can reference every axis name unconditionally."""
    axes = tuple((name, int(sizes.get(name, 1))) for name in MESH_AXIS_ORDER)
    return MeshSpec(axes=axes)


@dataclass(frozen=True)
class ParallelPlan:
    """Everything the workload generator and engine need to lay the
    model out on TPU hardware."""

    model: str
    chip: TPUChipSpec
    topology: str                # topology of ONE slice
    num_slices: int              # >1 => pipeline over DCN
    mesh: MeshSpec               # global mesh including pipeline axis
    estimate: SliceEstimate
    max_model_len: int
    workload: str                # "serve" | "train"
    notes: tuple[str, ...] = ()

    @property
    def chips_per_slice(self) -> int:
        return topology_chips(self.topology)

    @property
    def total_chips(self) -> int:
        return self.chips_per_slice * self.num_slices

    @property
    def num_hosts(self) -> int:
        return self.chip.hosts_for_topology(self.topology) * self.num_slices


def _largest_divisor_leq(n: int, cap: int) -> int:
    """Largest divisor of n that is <= cap."""
    best = 1
    for d in range(1, int(math.isqrt(n)) + 1):
        if n % d == 0:
            for cand in (d, n // d):
                if cand <= cap and cand > best:
                    best = cand
    return best


def _choose_tp(md: ModelMetadata, chips: int, needed: int) -> tuple[int, bool]:
    """Smallest TP degree that (a) divides the chip count, (b) gives the
    model enough HBM (>= ``needed`` chips per group), preferring degrees
    that divide the query-head count.  Returns (tp, padded_heads)."""
    heads = md.arch.num_heads
    divisors = [d for d in range(1, chips + 1) if chips % d == 0]
    for d in divisors:
        if d >= needed and heads % d == 0:
            return d, False
    for d in divisors:  # model must fit: accept head padding
        if d >= needed:
            return d, True
    return chips, heads % chips != 0


def plan_parallelism(
    md: ModelMetadata,
    chip: TPUChipSpec,
    *,
    workload: str = "serve",
    max_model_len: Optional[int] = None,
    target_chips: Optional[int] = None,
    kv_dtype_bytes: int = 2,
    quantization: Optional[str] = None,
    max_pipeline_stages: int = 8,
    cp_autocarve: bool = False,
) -> ParallelPlan:
    """Plan mesh + slice shape for a model on a chip generation.

    ``target_chips`` (user's requested capacity, the analogue of the
    Workspace ``resource.count`` x instance size) raises the floor; the
    planner never returns fewer chips than the model needs.

    ``cp_autocarve`` opts the SERVE path into carving a sequence axis
    (ring-attention context-parallel prefill) at >= 32k context.  It
    defaults OFF on measured evidence: BENCH_r05 shows
    ``cp_speedup_seq4_vs_chunked = 0.68`` — CP prefill LOSES to chunked
    prefill on the current kernel, so auto-carving would spend chips to
    get slower.  Flip the default only once a benchmark round measures
    ``cp_speedup_vs_chunked >= 1.0`` on real hardware (the train-path
    carve is unaffected: ring attention there overlaps with grad
    compute and is not subject to this evidence gate).
    """
    ctx = max_model_len or md.max_model_len
    notes: list[str] = []

    single = None
    try:
        single = estimate_slice(
            md, chip, max_model_len=ctx, kv_dtype_bytes=kv_dtype_bytes,
            quantization=quantization, min_chips=target_chips or 1)
    except ValueError:
        pass

    if single is not None:
        num_slices = 1
        est = single
    else:
        # Tier 3: pipeline over DCN. Each stage holds layers/k, so the
        # per-slice requirement shrinks ~linearly in the stage count.
        est = None
        num_slices = 0
        for k in range(2, max_pipeline_stages + 1):
            if md.arch.num_layers % k != 0:
                continue
            stage_md = md.with_overrides(
                arch=_scale_layers(md.arch, md.arch.num_layers // k))
            try:
                est = estimate_slice(
                    stage_md, chip, max_model_len=ctx,
                    kv_dtype_bytes=kv_dtype_bytes, quantization=quantization)
                num_slices = k
                notes.append(f"pipeline over DCN: {k} stages of {md.arch.num_layers // k} layers")
                break
            except ValueError:
                continue
        if est is None:
            raise ValueError(
                f"model {md.name!r} does not fit {max_pipeline_stages} "
                f"pipeline stages of the largest {chip.generation} slice")

    chips = est.num_chips
    # TP degree is driven by what the model *needs*, not by total
    # capacity: surplus chips become data parallelism (tier 1) instead of
    # widening TP past its useful point (reference tiering:
    # interface.go:500-532 picks DP when the model fits a fraction of the
    # hardware).
    if num_slices == 1:
        from kaito_tpu.estimator.estimator import estimate_chip_count

        needed = estimate_chip_count(
            md, chip, max_model_len=ctx, kv_dtype_bytes=kv_dtype_bytes,
            quantization=quantization)
    else:
        needed = chips
    tp, padded = _choose_tp(md, chips, min(chips, needed))
    if padded:
        notes.append(f"tp={tp} does not divide {md.arch.num_heads} heads: engine pads heads")
    leftover = chips // tp

    expert = 1
    seq = 1
    if workload == "train":
        # FSDP everything that is not TP; carve sequence axis for long ctx.
        if ctx >= 32768 and leftover >= 2:
            seq = 2
            while seq * 2 <= leftover and ctx // (seq * 2) >= 8192:
                seq *= 2
            leftover //= seq
            notes.append(f"sequence parallelism (ring attention) degree {seq}")
        if md.arch.num_experts > 0 and leftover >= 2:
            expert = _largest_divisor_leq(leftover, min(leftover, md.arch.num_experts))
            leftover //= expert
            notes.append(f"expert parallelism degree {expert}")
        mesh = make_mesh_spec(pipeline=num_slices, fsdp=leftover, expert=expert,
                              sequence=seq, tensor=tp)
    else:
        # Serving: long contexts first carve a sequence axis (ring
        # attention CP prefill — TTFT for a 32k+ prompt scales ~1/seq
        # while decode stays TP); the rest becomes independent
        # data-parallel engine replicas (tier 1 when tp == 1).
        # (single-slice only: the pipeline serving executor owns its
        # mesh and has no sequence axis — carving one there would
        # reserve chips the engine never uses)
        # opt-in only (cp_autocarve): see the evidence gate in the
        # docstring — BENCH_r05 measured CP prefill at 0.68x chunked
        if cp_autocarve and ctx >= 32768 and leftover >= 2 \
                and num_slices == 1 \
                and md.arch.attention_kind.value != "MLA":
            seq = 2
            while seq * 2 <= leftover and ctx // (seq * 2) >= 8192:
                seq *= 2
            leftover //= seq
            notes.append(f"context-parallel prefill (ring attention) degree {seq}")
        mesh = make_mesh_spec(pipeline=num_slices, data=leftover,
                              sequence=seq, tensor=tp)
        if leftover > 1:
            notes.append(f"data parallel serving: {leftover} engine groups of tp={tp}")

    if tp > md.arch.num_kv_heads and md.arch.num_kv_heads > 0:
        notes.append(
            f"tp={tp} exceeds kv_heads={md.arch.num_kv_heads}: KV heads replicate "
            f"{tp // md.arch.num_kv_heads}x")

    return ParallelPlan(
        model=md.name,
        chip=chip,
        topology=est.topology,
        num_slices=num_slices,
        mesh=mesh,
        estimate=est,
        max_model_len=ctx,
        workload=workload,
        notes=tuple(notes),
    )


def _scale_layers(arch, num_layers: int):
    from dataclasses import replace

    return replace(arch, num_layers=num_layers)
