"""Pipeline-parallel training step (GPipe schedule under shard_map).

The PP tier the planner emits for models beyond the largest slice
(mesh ``pipeline`` axis over DCN).  Layers split into contiguous
stages, one per pipeline rank; microbatches stream through the ring
with ``ppermute`` hand-offs, so at steady state every stage computes a
different microbatch — the classic GPipe schedule with M + P - 1 ticks.
Stage 0 embeds, the last stage computes logits/loss; everything is
differentiable (grads flow back through the permutes), so one
``jax.grad`` over the wrapped loss trains the whole pipeline.

Scope (v1): dense single-group models (no MoE/MLA), full-length packed
batches; composes with the tensor axis via the model's own GSPMD
shardings inside each stage.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from kaito_tpu.engine.model import TransformerLM
from kaito_tpu.tuning.train_step import cross_entropy_loss


def split_stage_params(model: TransformerLM, params: dict, num_stages: int) -> dict:
    """Reshape the scanned layer stacks [L, ...] -> [P, L/P, ...] so the
    leading axis shards over the pipeline mesh axis.  The per-request
    LoRA stacks (``serve_lora``, [L, n_adapters+1, ...]) ride the same
    layer scan and split identically, so multi-adapter serving keeps
    working under PP (no merge-into-base)."""
    (group,) = model.groups  # single homogeneous group (v1 scope)
    L = model.arch.num_layers
    if L % num_stages:
        raise ValueError(f"{L} layers do not split into {num_stages} stages")

    def split(v):
        return v.reshape((num_stages, L // num_stages) + v.shape[1:])

    out = dict(params)
    out[group.name] = {
        k: jax.tree.map(split, sub)
        for k, sub in params[group.name].items()}
    if "serve_lora" in params:
        out["serve_lora"] = {
            g: jax.tree.map(split, sub)
            for g, sub in params["serve_lora"].items()}
    return out


def merge_stage_params(model: TransformerLM, params: dict) -> dict:
    (group,) = model.groups
    out = dict(params)
    out[group.name] = {
        k: jax.tree.map(lambda v: v.reshape((-1,) + v.shape[2:]), sub)
        for k, sub in params[group.name].items()}
    return out


def _stage_apply(model: TransformerLM, stack: dict, x: jax.Array) -> jax.Array:
    """Run this stage's layers over activations [mb, T, E]."""
    T = x.shape[1]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), x.shape[:2])
    true_lens = jnp.full((x.shape[0],), T, jnp.int32)

    def body(h, p):
        h = model._layer_train(h, p, None, False, positions=positions,
                               true_lens=true_lens)
        return h, None

    x, _ = jax.lax.scan(body, x, stack)
    return x


def pipeline_loss_fn(model: TransformerLM, mesh: Mesh, num_microbatches: int,
                     axis: str = "pipeline"):
    """Build loss(params_staged, batch) running the GPipe schedule."""
    num_stages = mesh.shape[axis]
    (group,) = model.groups

    def local_loss(stage_stack, embed, final_norm, head, tokens, mask):
        # inside shard_map: stage_stack [1, L/P, ...]
        p_idx = jax.lax.axis_index(axis)
        stack = jax.tree.map(lambda v: v[0], stage_stack)
        M = num_microbatches
        B = tokens.shape[0]
        mb = B // M
        inputs = tokens[:, :-1].reshape(M, mb, -1)
        targets = tokens[:, 1:].reshape(M, mb, -1)
        masks = mask.reshape(M, mb, -1)
        T = inputs.shape[-1]
        E = model.arch.hidden_size

        fwd_perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def tick(carry, t):
            recv, loss_acc, denom_acc = carry
            mb_here = t - p_idx                  # microbatch this stage sees
            valid = (mb_here >= 0) & (mb_here < M)
            mb_idx = jnp.clip(mb_here, 0, M - 1)

            x_in = jnp.where(
                p_idx == 0,
                model._embed({"embed": embed}, inputs[mb_idx]),
                recv)
            x_out = _stage_apply(model, stack, x_in)

            # last stage: loss for its microbatch
            def final(x):
                h = model._norm(x, {"final_norm": final_norm}, "final_norm")
                logits = model._logits({"embed": head, "lm_head": head}, h)
                m = masks[mb_idx]
                lp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(
                    lp, targets[mb_idx][..., None], axis=-1)[..., 0]
                return jnp.sum(nll * m), jnp.sum(m)

            l_num, l_den = final(x_out)
            is_last = p_idx == num_stages - 1
            use = valid & is_last
            loss_acc = loss_acc + jnp.where(use, l_num, 0.0)
            denom_acc = denom_acc + jnp.where(use, l_den, 0.0)

            sent = jax.lax.ppermute(x_out, axis, fwd_perm)
            return (sent, loss_acc, denom_acc), None

        recv0 = jnp.zeros((mb, T, E), model.dtype)
        (recv, loss_sum, denom), _ = jax.lax.scan(
            tick, (recv0, jnp.float32(0.0), jnp.float32(0.0)),
            jnp.arange(num_stages + M - 1))
        # only the last stage holds the loss; share it with everyone
        loss_sum = jax.lax.psum(loss_sum, axis)
        denom = jax.lax.psum(denom, axis)
        return loss_sum / jnp.maximum(denom, 1.0)

    sharded = jax.shard_map(
        local_loss,
        mesh=mesh,
        in_specs=(P(axis), P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )

    def loss(params_staged, batch):
        stage_stack = params_staged[group.name]
        head = params_staged.get("lm_head", params_staged["embed"])
        return sharded(stage_stack, params_staged["embed"],
                       params_staged["final_norm"], head,
                       batch["tokens"], batch["mask"])

    return loss
