"""Ring attention: sequence-parallel exact attention over the mesh.

The long-context capability the reference never built (SURVEY.md §2.3:
no SP/CP/ring anywhere; long context is delegated to vLLM's KV budget).
Sequence shards live on different chips; each of the ``n`` ring steps
computes one block of the softmax against the locally-held KV shard
while ``ppermute`` rotates KV shards around the ICI ring — attention
memory stays O(T/n) per chip and the transfers overlap with the block
matmuls.  Causality is handled per-block: a KV block from a later shard
is skipped, the diagonal block is causally masked, earlier blocks attend
fully.

Pure-collective implementation (lax.ppermute under shard_map) — XLA
schedules the overlap; a pallas RDMA variant is the planned follow-up.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from kaito_tpu.engine.attention import NEG_INF, _gqa_expand


def _ring_local(q, k, v, *, axis_name: str, scale: float, causal: bool):
    """Local shard computation. q/k/v: [B, T_loc, H(kv), D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    groups = H // k.shape[2]
    scores_dtype = jnp.float32

    q_scaled = (q * scale).astype(q.dtype)
    t_local = jnp.arange(T)

    def block(q_, k_, v_, src, m, l, acc):
        kx = _gqa_expand(k_, groups)
        vx = _gqa_expand(v_, groups)
        s = jnp.einsum("bthd,bshd->bhts", q_, kx,
                       preferred_element_type=scores_dtype)
        if causal:
            q_pos = idx * T + t_local[:, None]
            k_pos = src * T + t_local[None, :]
            mask = k_pos <= q_pos
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # all-masked blocks keep m at NEG_INF; guard the exp
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhts,bshd->bthd", p.astype(vx.dtype), vx,
                        preferred_element_type=scores_dtype)
        acc_new = acc * jnp.moveaxis(alpha, 1, 2) + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((B, H, T, 1), NEG_INF, scores_dtype)
    l0 = jnp.zeros((B, H, T, 1), scores_dtype)
    acc0 = jnp.zeros((B, T, H, D), scores_dtype)

    def body(i, carry):
        k_cur, v_cur, m, l, acc = carry
        src = jax.lax.rem(idx - i + n, n)
        m, l, acc = block(q_scaled, k_cur, v_cur, src, m, l, acc)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(0, n, body, (k, v, m0, l0, acc0))
    l = jnp.maximum(jnp.moveaxis(l, 1, 2), 1e-30)   # [B, T, H, 1]
    return (acc / l).astype(q.dtype)


def ring_attention(
    q: jax.Array,            # [B, T, H, D] sharded on T over `axis`
    k: jax.Array,            # [B, T, Hkv, D]
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sequence",
    *,
    scale: float,
    causal: bool = True,
) -> jax.Array:
    """shard_map wrapper: exact attention over the sequence axis."""
    fn = jax.shard_map(
        functools.partial(_ring_local, axis_name=axis, scale=scale,
                          causal=causal),
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )
    return fn(q, k, v)
