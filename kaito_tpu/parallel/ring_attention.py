"""Ring attention: sequence-parallel exact attention over the mesh.

The long-context capability the reference never built (SURVEY.md §2.3:
no SP/CP/ring anywhere; long context is delegated to vLLM's KV budget).
Sequence shards live on different chips; each of the ``n`` ring steps
computes one block of the softmax against the locally-held KV shard
while ``ppermute`` rotates KV shards around the ICI ring — attention
memory stays O(T/n) per chip and the transfers overlap with the block
matmuls.  Causality is handled per-block: a KV block from a later shard
is skipped, the diagonal block is causally masked, earlier blocks attend
fully.

Used by BOTH training (long-context packed batches,
``tuning/trainer.py``) and serving (context-parallel single-shot
prefill, ``engine/model.py`` mode ``prefill_cp`` — the serving-side CP
the reference delegates away to vLLM's ``--max-model-len`` budget,
``pkg/model/interface.go:308-312``).  ``head_axis`` composes CP with
tensor parallelism: heads stay sharded over the TP axis through the
ring, so a (sequence x tensor) mesh runs both parallelisms in one
shard_map.

Pure-collective implementation (lax.ppermute under shard_map) — XLA
schedules the overlap; a pallas RDMA variant is the planned follow-up.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from kaito_tpu.engine.attention import NEG_INF, _gqa_expand


def _ring_local(q, k, v, sliding_window=None, *, axis_name: str,
                scale: float, causal: bool, logit_softcap=None,
                q_tile: int = 0):
    """Local shard computation. q/k/v: [B, T_loc, H(kv), D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    groups = H // k.shape[2]
    scores_dtype = jnp.float32

    q_scaled = (q * scale).astype(q.dtype)
    t_local = jnp.arange(T)

    def block(q_, k_, v_, q_pos, src, m, l, acc):
        """One [Tq, T] score block with online-softmax accumulation.
        q_pos: [Tq] ABSOLUTE positions of the query rows."""
        kx = _gqa_expand(k_, groups)
        vx = _gqa_expand(v_, groups)
        s = jnp.einsum("bthd,bshd->bhts", q_, kx,
                       preferred_element_type=scores_dtype)
        if logit_softcap:
            s = jnp.tanh(s / logit_softcap) * logit_softcap
        k_pos = src * T + t_local[None, :]
        if causal:
            mask = k_pos <= q_pos[:, None]
            if sliding_window is not None:
                mask &= k_pos > q_pos[:, None] - sliding_window
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # all-masked blocks keep m at NEG_INF only until the diagonal
        # block (processed FIRST) seeds it; guard holds because every
        # causal query row attends at least to itself
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhts,bshd->bthd", p.astype(vx.dtype), vx,
                        preferred_element_type=scores_dtype)
        acc_new = acc * jnp.moveaxis(alpha, 1, 2) + pv
        return m_new, l_new, acc_new

    def ring(q_, q_pos):
        """Run the full ring for one query tile. q_: [B, Tq, H, D]."""
        Tq = q_.shape[1]
        m0 = jnp.full((B, H, Tq, 1), NEG_INF, scores_dtype)
        l0 = jnp.zeros((B, H, Tq, 1), scores_dtype)
        acc0 = jnp.zeros((B, Tq, H, D), scores_dtype)

        def body(i, carry):
            k_cur, v_cur, m, l, acc = carry
            src = jax.lax.rem(idx - i + n, n)
            m, l, acc = block(q_, k_cur, v_cur, q_pos, src, m, l, acc)
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return k_nxt, v_nxt, m, l, acc

        _, _, m, l, acc = jax.lax.fori_loop(0, n, body, (k, v, m0, l0, acc0))
        l = jnp.maximum(jnp.moveaxis(l, 1, 2), 1e-30)   # [B, Tq, H, 1]
        return (acc / l).astype(q.dtype)

    if not q_tile or T <= q_tile:
        return ring(q_scaled, idx * T + t_local)
    # long-context serving shapes: tile the query rows so the score
    # block is [Tq, T_loc] instead of [T_loc, T_loc] — peak attention
    # workspace is O(q_tile * T/n) per chip regardless of prompt length
    # (each tile still rotates the full ring; KV transfers repeat per
    # tile but stay overlapped with the block matmuls).  A non-aligned
    # local length runs its remainder rows as one short extra ring so
    # the memory bound holds for ANY bucket, not just tile multiples.
    nt, T0 = T // q_tile, (T // q_tile) * q_tile
    q_tiles = q_scaled[:, :T0].reshape(B, nt, q_tile, H, D).swapaxes(0, 1)
    pos = (idx * T + t_local)[:T0].reshape(nt, q_tile)

    def one(args):
        qt, pt = args
        return ring(qt, pt)

    out = jax.lax.map(one, (q_tiles, pos))          # [nt, B, q_tile, H, D]
    out = out.swapaxes(0, 1).reshape(B, T0, H, D)
    if T0 < T:
        rest = ring(q_scaled[:, T0:], (idx * T + t_local)[T0:])
        out = jnp.concatenate([out, rest], axis=1)
    return out


def ring_attention(
    q: jax.Array,            # [B, T, H, D] sharded on T over `axis`
    k: jax.Array,            # [B, T, Hkv, D]
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sequence",
    *,
    scale: float,
    causal: bool = True,
    sliding_window: Optional[jax.Array] = None,
    logit_softcap: Optional[float] = None,
    head_axis: Optional[str] = None,
    q_tile: int = 0,
) -> jax.Array:
    """shard_map wrapper: exact attention over the sequence axis.

    ``head_axis`` additionally shards the head dim (TP composition) —
    only valid when it divides BOTH the query and KV head counts.
    ``q_tile`` bounds the per-chip score-block workspace for long
    sequences (0 = whole shard in one block)."""
    if head_axis is not None:
        tp = mesh.shape[head_axis]
        if q.shape[2] % tp or k.shape[2] % tp:
            raise ValueError(
                f"head_axis={head_axis!r} (size {tp}) must divide query "
                f"heads {q.shape[2]} and KV heads {k.shape[2]}")
    spec = P(None, axis, head_axis)
    local = functools.partial(_ring_local, axis_name=axis, scale=scale,
                              causal=causal, logit_softcap=logit_softcap,
                              q_tile=q_tile)
    # a sliding window may be a TRACED per-layer scalar (scan flag), so
    # it rides as an explicit replicated operand, never a closure capture
    in_specs = (spec, spec, spec) + ((P(),) if sliding_window is not None
                                    else ())
    fn = jax.shard_map(local, mesh=mesh, in_specs=in_specs,
                       out_specs=spec, check_vma=False)
    if sliding_window is not None:
        return fn(q, k, v, jnp.asarray(sliding_window))
    return fn(q, k, v)
