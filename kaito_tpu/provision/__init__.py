from kaito_tpu.provision.provisioner import NodeProvisioner, ProvisionRequest  # noqa: F401
from kaito_tpu.provision.karpenter import KarpenterTPUProvisioner  # noqa: F401
from kaito_tpu.provision.byo import BYOProvisioner  # noqa: F401
from kaito_tpu.provision.fake import FakeCloud  # noqa: F401


def new_node_provisioner(kind: str, store):
    """Factory (reference: ``pkg/nodeprovision/manager/factory.go:66``)."""
    if kind == "karpenter":
        return KarpenterTPUProvisioner(store)
    if kind == "byo":
        return BYOProvisioner(store)
    raise ValueError(f"unknown node provisioner {kind!r} (karpenter|byo)")
