"""Karpenter provisioner for GKE TPU slices.

The TPU-native re-design of ``pkg/nodeprovision/karpenter``
(provisioner.go:311/:460, nodepool.go:96): one ``karpenter.sh/v1
NodePool`` per workspace slice with TPU requirements —
``cloud.google.com/gke-tpu-accelerator`` + ``gke-tpu-topology`` +
machine type — replicas = number of hosts in the slice, drift budget
closed (0) by default and opened to 1 by the drift controller.

Readiness follows the reference's snapshot design
(``provisioner.go:391-489`` nodeReadinessSnapshot + EnsureNodesReady):
one point-in-time :class:`NodeReadinessSnapshot` per reconcile counts
ready slice nodes, ready BYO ``preferredNodes`` covering part of the
want (``countCoveredNodes``, :245), and TPU device capacity
(``CheckIfNodePluginsReady`` — here the ``google.com/tpu`` allocatable
on each node).  The snapshot also powers per-slice status conditions
(``CollectNodeStatusInfo``, :538), provision-to-ready seconds (a
BASELINE.json headline metric), and the node-repair path (delete
persistently NotReady nodes so the pool replaces them).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.api.meta import ObjectMeta
from kaito_tpu.controllers.objects import Unstructured, is_node_ready
from kaito_tpu.controllers.runtime import Store, update_with_retry
from kaito_tpu.k8s.events import record_event
from kaito_tpu.provision.provisioner import ProvisionRequest
from kaito_tpu.sku.catalog import (
    LABEL_TPU_ACCELERATOR,
    LABEL_TPU_MACHINE,
    LABEL_TPU_TOPOLOGY,
)

LABEL_OWNER = "kaito-tpu.io/workspace"
LABEL_SLICE_INDEX = "kaito-tpu.io/slice-index"
ANNOTATION_PROVISION_START = "kaito-tpu.io/provision-start"
ANNOTATION_READY_AT = "kaito-tpu.io/ready-at"
TPU_RESOURCE = "google.com/tpu"

# a node NotReady this long (while its pool wants it) gets deleted so
# the pool replaces it — the repair analogue of Karpenter node
# auto-repair on NodeClaim health
DEFAULT_REPAIR_AFTER_S = 300.0


@dataclass
class SliceReadiness:
    """Point-in-time readiness of ONE slice's capacity."""

    index: int
    want: int
    pool_exists: bool
    ready_nodes: list[str] = field(default_factory=list)
    not_ready_nodes: list[str] = field(default_factory=list)
    byo_covered: list[str] = field(default_factory=list)
    capacity_short: list[str] = field(default_factory=list)  # no TPU alloc

    @property
    def ready(self) -> bool:
        return (self.pool_exists
                and len(self.ready_nodes) + len(self.byo_covered) >= self.want
                and not self.capacity_short)

    def message(self) -> str:
        parts = [f"slice {self.index}: "
                 f"{len(self.ready_nodes) + len(self.byo_covered)}"
                 f"/{self.want} ready"]
        if not self.pool_exists:
            parts.append("pool missing")
        if self.not_ready_nodes:
            parts.append(f"notReady={','.join(self.not_ready_nodes)}")
        if self.capacity_short:
            parts.append(f"noTPUCapacity={','.join(self.capacity_short)}")
        if self.byo_covered:
            parts.append(f"byo={len(self.byo_covered)}")
        return " ".join(parts)


@dataclass
class NodeReadinessSnapshot:
    slices: list[SliceReadiness]

    @property
    def all_ready(self) -> bool:
        return all(s.ready for s in self.slices)

    @property
    def ready_nodes(self) -> list[str]:
        out: set[str] = set()
        for s in self.slices:
            out.update(s.ready_nodes)
            out.update(s.byo_covered)
        return sorted(out)

    def condition(self) -> dict:
        """One workspace-status condition summarizing every slice (the
        CollectNodeStatusInfo analogue)."""
        if self.all_ready:
            return {"status": "True", "reason": "NodesReady",
                    "message": f"{len(self.ready_nodes)} nodes ready"}
        return {"status": "False", "reason": "NodeClaimNotReady",
                "message": "; ".join(s.message() for s in self.slices
                                     if not s.ready)}


class KarpenterTPUProvisioner:
    name = "karpenter"

    def __init__(self, store: Store, repair_after_s: float = DEFAULT_REPAIR_AFTER_S):
        self.store = store
        self.repair_after_s = repair_after_s

    # ------------------------------------------------------------------

    def _pool_name(self, req: ProvisionRequest, idx: int) -> str:
        return f"{req.owner_name}-slice-{idx}"

    def render_nodepool(self, req: ProvisionRequest, idx: int) -> dict:
        """The NodePool spec rendered for a real cluster (and stored as
        Unstructured in-process)."""
        s = req.slice_spec
        labels = {
            LABEL_OWNER: req.owner_name,
            LABEL_SLICE_INDEX: str(idx),
            **req.extra_labels,
        }
        return {
            "replicas": s.num_hosts,
            "disruption": {"budgets": [{"nodes": "0"}]},  # drift closed
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "requirements": [
                        {"key": LABEL_TPU_ACCELERATOR, "operator": "In",
                         "values": [s.chip.accelerator_label]},
                        {"key": LABEL_TPU_TOPOLOGY, "operator": "In",
                         "values": [s.topology]},
                        {"key": LABEL_TPU_MACHINE, "operator": "In",
                         "values": [s.machine_type] if s.machine_type else []},
                    ],
                    "taints": [{"key": "google.com/tpu", "value": "present",
                                "effect": "NoSchedule"}],
                },
            },
        }

    # -- NodeProvisioner -----------------------------------------------

    def provision(self, req: ProvisionRequest) -> None:
        for idx in range(req.num_slices):
            name = self._pool_name(req, idx)
            if self.store.try_get("NodePool", "", name) is None:
                pool = Unstructured(
                    "NodePool",
                    ObjectMeta(name=name, namespace="",
                               labels={LABEL_OWNER: req.owner_name},
                               annotations={
                                   ANNOTATION_PROVISION_START:
                                   f"{time.time():.3f}"}),
                    spec=self.render_nodepool(req, idx))
                self.store.create(pool)
                record_event(self.store, pool, "Normal",
                             "ProvisioningStarted",
                             f"created NodePool {name} for "
                             f"{req.owner_namespace}/{req.owner_name} "
                             f"({req.slice_spec.num_hosts} host(s), "
                             f"topology {req.slice_spec.topology})")

    def _byo_covered(self, req: ProvisionRequest) -> list[str]:
        """Ready preferredNodes with the right accelerator label AND
        live TPU capacity count toward the want (reference
        countCoveredNodes, provisioner.go:245-309)."""
        covered = []
        accel = req.slice_spec.chip.accelerator_label
        for name in req.preferred_nodes:
            n = self.store.try_get("Node", "", name)
            if n is None or not is_node_ready(n):
                continue
            if n.metadata.labels.get(LABEL_TPU_ACCELERATOR) == accel \
                    and self._has_tpu_capacity(n):
                covered.append(name)
        return covered

    @staticmethod
    def _has_tpu_capacity(n: Unstructured) -> bool:
        """TPU device capacity check (the GPU-plugin-readiness
        analogue): when the node advertises allocatable, it must carry
        google.com/tpu chips; nodes without an allocatable map (fakes,
        freshly registered) pass on their Ready condition alone."""
        alloc = n.status.get("allocatable")
        if not isinstance(alloc, dict):
            return True
        return int(str(alloc.get(TPU_RESOURCE, "0"))) > 0

    def build_readiness_snapshot(self, req: ProvisionRequest
                                 ) -> NodeReadinessSnapshot:
        byo = self._byo_covered(req)
        slices = []
        for idx in range(req.num_slices):
            pool = self.store.try_get("NodePool", "",
                                      self._pool_name(req, idx))
            sr = SliceReadiness(index=idx, want=req.slice_spec.num_hosts,
                                pool_exists=pool is not None,
                                byo_covered=list(byo) if idx == 0 else [])
            nodes = self.store.list("Node", labels={
                LABEL_OWNER: req.owner_name, LABEL_SLICE_INDEX: str(idx)})
            now = time.time()
            for n in nodes:
                if not is_node_ready(n):
                    sr.not_ready_nodes.append(n.metadata.name)
                    self._stamp_not_ready(n, now)
                elif not self._has_tpu_capacity(n):
                    sr.capacity_short.append(n.metadata.name)
                else:
                    sr.ready_nodes.append(n.metadata.name)
                    self._clear_not_ready(n)
            slices.append(sr)
        return NodeReadinessSnapshot(slices=slices)

    def _stamp_not_ready(self, n: Unstructured, now: float) -> None:
        if "notReadySince" in n.status:
            return

        def mutate(o, now=now):
            o.status["notReadySince"] = now

        try:
            update_with_retry(self.store, "Node", "", n.metadata.name, mutate)
        except Exception:
            pass   # races with node deletion are benign

    def _clear_not_ready(self, n: Unstructured) -> None:
        """A recovered node's outage clock resets — otherwise a later
        brief blip would read as one long outage and repair would
        delete a healthy-but-flapping host immediately."""
        if "notReadySince" not in n.status:
            return

        def mutate(o):
            o.status.pop("notReadySince", None)

        try:
            update_with_retry(self.store, "Node", "", n.metadata.name, mutate)
        except Exception:
            pass

    def ensure_ready_snapshot(self, req: ProvisionRequest
                              ) -> NodeReadinessSnapshot:
        """One snapshot per reconcile: readiness decision, node list,
        status condition, and ready-at stamping all derive from it
        (callers must not rebuild it — each build is a full Node/Pool
        list against the store)."""
        snap = self.build_readiness_snapshot(req)
        if snap.all_ready:
            self._stamp_ready(req)
        return snap

    def ensure_ready(self, req: ProvisionRequest) -> tuple[bool, list[str]]:
        snap = self.ensure_ready_snapshot(req)
        return snap.all_ready, snap.ready_nodes

    def _stamp_ready(self, req: ProvisionRequest) -> None:
        """Record first-all-ready time per pool (provision-to-ready
        seconds is a BASELINE.json headline metric)."""
        for idx in range(req.num_slices):
            name = self._pool_name(req, idx)
            pool = self.store.try_get("NodePool", "", name)
            if pool is None or ANNOTATION_READY_AT in pool.metadata.annotations:
                continue

            def mutate(p):
                p.metadata.annotations[ANNOTATION_READY_AT] = \
                    f"{time.time():.3f}"

            update_with_retry(self.store, "NodePool", "", name, mutate)

    def provision_seconds(self, req: ProvisionRequest) -> Optional[float]:
        """Seconds from NodePool creation to first all-ready, maxed
        over the request's slices (None until ready)."""
        worst = None
        for idx in range(req.num_slices):
            pool = self.store.try_get("NodePool", "",
                                      self._pool_name(req, idx))
            if pool is None:
                return None
            ann = pool.metadata.annotations
            if ANNOTATION_READY_AT not in ann \
                    or ANNOTATION_PROVISION_START not in ann:
                return None
            dt = float(ann[ANNOTATION_READY_AT]) \
                - float(ann[ANNOTATION_PROVISION_START])
            worst = dt if worst is None else max(worst, dt)
        return worst

    def repair_unhealthy(self, req: ProvisionRequest) -> list[str]:
        """Node repair: delete nodes NotReady longer than
        ``repair_after_s`` while their pool still wants them — the pool
        (cloud) replaces them.  Returns the deleted node names."""
        deleted = []
        now = time.time()
        for idx in range(req.num_slices):
            for n in self.store.list("Node", labels={
                    LABEL_OWNER: req.owner_name,
                    LABEL_SLICE_INDEX: str(idx)}):
                if is_node_ready(n):
                    continue
                since = n.status.get("notReadySince")
                if since is None or now - float(since) < self.repair_after_s:
                    continue
                self.store.delete("Node", "", n.metadata.name)
                deleted.append(n.metadata.name)
                record_event(self.store, n, "Warning", "NodeRepaired",
                             f"deleted NotReady node {n.metadata.name} "
                             f"after {now - float(since):.0f}s; pool will "
                             f"replace it")
        return deleted

    def deprovision(self, req: ProvisionRequest) -> None:
        for pool in self.store.list("NodePool",
                                    labels={LABEL_OWNER: req.owner_name}):
            self.store.delete("NodePool", "", pool.metadata.name)

    def node_selector(self, req: ProvisionRequest) -> dict[str, str]:
        sel = dict(req.slice_spec.node_selector())
        sel[LABEL_OWNER] = req.owner_name
        return sel

    def set_drift_budget(self, req: ProvisionRequest, allow: bool) -> None:
        for pool in self.store.list("NodePool",
                                    labels={LABEL_OWNER: req.owner_name}):
            def mutate(p, allow=allow):
                p.spec["disruption"]["budgets"] = [
                    {"nodes": "1" if allow else "0"}]

            update_with_retry(self.store, "NodePool", "", pool.metadata.name,
                              mutate)
