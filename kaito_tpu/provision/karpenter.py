"""Karpenter provisioner for GKE TPU slices.

The TPU-native re-design of ``pkg/nodeprovision/karpenter``
(provisioner.go:311/:460, nodepool.go:96): one ``karpenter.sh/v1
NodePool`` per workspace with TPU requirements —
``cloud.google.com/gke-tpu-accelerator`` + ``gke-tpu-topology`` +
machine type — replicas = number of hosts in the slice, drift budget
closed (0) by default and opened to 1 by the drift controller.
"""

from __future__ import annotations

from kaito_tpu.api.meta import ObjectMeta
from kaito_tpu.controllers.objects import Unstructured, is_node_ready
from kaito_tpu.controllers.runtime import Store
from kaito_tpu.provision.provisioner import ProvisionRequest
from kaito_tpu.sku.catalog import (
    LABEL_TPU_ACCELERATOR,
    LABEL_TPU_MACHINE,
    LABEL_TPU_TOPOLOGY,
)

LABEL_OWNER = "kaito-tpu.io/workspace"
LABEL_SLICE_INDEX = "kaito-tpu.io/slice-index"


class KarpenterTPUProvisioner:
    name = "karpenter"

    def __init__(self, store: Store):
        self.store = store

    # ------------------------------------------------------------------

    def _pool_name(self, req: ProvisionRequest, idx: int) -> str:
        return f"{req.owner_name}-slice-{idx}"

    def render_nodepool(self, req: ProvisionRequest, idx: int) -> dict:
        """The NodePool spec rendered for a real cluster (and stored as
        Unstructured in-process)."""
        s = req.slice_spec
        labels = {
            LABEL_OWNER: req.owner_name,
            LABEL_SLICE_INDEX: str(idx),
            **req.extra_labels,
        }
        return {
            "replicas": s.num_hosts,
            "disruption": {"budgets": [{"nodes": "0"}]},  # drift closed
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "requirements": [
                        {"key": LABEL_TPU_ACCELERATOR, "operator": "In",
                         "values": [s.chip.accelerator_label]},
                        {"key": LABEL_TPU_TOPOLOGY, "operator": "In",
                         "values": [s.topology]},
                        {"key": LABEL_TPU_MACHINE, "operator": "In",
                         "values": [s.machine_type] if s.machine_type else []},
                    ],
                    "taints": [{"key": "google.com/tpu", "value": "present",
                                "effect": "NoSchedule"}],
                },
            },
        }

    # -- NodeProvisioner -----------------------------------------------

    def provision(self, req: ProvisionRequest) -> None:
        for idx in range(req.num_slices):
            name = self._pool_name(req, idx)
            if self.store.try_get("NodePool", "", name) is None:
                self.store.create(Unstructured(
                    "NodePool",
                    ObjectMeta(name=name, namespace="",
                               labels={LABEL_OWNER: req.owner_name}),
                    spec=self.render_nodepool(req, idx)))

    def ensure_ready(self, req: ProvisionRequest) -> tuple[bool, list[str]]:
        ready_nodes: list[str] = []
        all_ready = True
        for idx in range(req.num_slices):
            name = self._pool_name(req, idx)
            pool = self.store.try_get("NodePool", "", name)
            if pool is None:
                return False, []
            nodes = self.store.list("Node", labels={
                LABEL_OWNER: req.owner_name, LABEL_SLICE_INDEX: str(idx)})
            ready = [n for n in nodes if is_node_ready(n)]
            want = req.slice_spec.num_hosts
            if len(ready) < want:
                all_ready = False
            ready_nodes.extend(n.metadata.name for n in ready)
        return all_ready, sorted(ready_nodes)

    def deprovision(self, req: ProvisionRequest) -> None:
        for pool in self.store.list("NodePool",
                                    labels={LABEL_OWNER: req.owner_name}):
            self.store.delete("NodePool", "", pool.metadata.name)

    def node_selector(self, req: ProvisionRequest) -> dict[str, str]:
        sel = dict(req.slice_spec.node_selector())
        sel[LABEL_OWNER] = req.owner_name
        return sel

    def set_drift_budget(self, req: ProvisionRequest, allow: bool) -> None:
        for pool in self.store.list("NodePool",
                                    labels={LABEL_OWNER: req.owner_name}):
            def mutate(p, allow=allow):
                p.spec["disruption"]["budgets"] = [
                    {"nodes": "1" if allow else "0"}]
            from kaito_tpu.controllers.runtime import update_with_retry

            update_with_retry(self.store, "NodePool", "", pool.metadata.name, mutate)
