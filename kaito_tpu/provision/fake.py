"""FakeCloud: a simulated Karpenter + cloud backend.

Watches NodePool objects in the store and materializes Node objects
with the right TPU labels after a configurable number of ticks — the
fake topology/provisioner backend SURVEY.md §4 calls out as the
reference's weakest testing area (its e2e needs a real cluster + GPU
quota).  Supports failure injection per pool.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.controllers.objects import Unstructured, node
from kaito_tpu.controllers.runtime import Store
from kaito_tpu.provision.karpenter import LABEL_OWNER, LABEL_SLICE_INDEX
from kaito_tpu.sku.catalog import (
    LABEL_TPU_ACCELERATOR,
    LABEL_TPU_MACHINE,
    LABEL_TPU_TOPOLOGY,
)


@dataclass
class FakeCloud:
    store: Store
    provision_delay_ticks: int = 0       # ticks before nodes appear
    fail_pools: set = field(default_factory=set)  # pool names that never come up
    _pending: dict = field(default_factory=dict)

    def tick(self) -> None:
        """Advance the simulated cloud one step."""
        for pool in self.store.list("NodePool"):
            name = pool.metadata.name
            if name in self.fail_pools:
                continue
            existing = {
                n.metadata.name
                for n in self.store.list("Node")
                if n.metadata.name.startswith(f"{name}-node-")
            }
            want = int(pool.spec.get("replicas", 1))
            if len(existing) >= want:
                continue
            waited = self._pending.get(name, 0)
            if waited < self.provision_delay_ticks:
                self._pending[name] = waited + 1
                continue
            tmpl = pool.spec.get("template", {})
            labels = dict(tmpl.get("metadata", {}).get("labels", {}))
            for r in tmpl.get("spec", {}).get("requirements", []):
                if r.get("values"):
                    labels[r["key"]] = r["values"][0]
            for i in range(want):
                node_name = f"{name}-node-{i}"
                if node_name in existing:
                    continue
                self.store.create(node(node_name, labels, ready=True))

        # kubelet sim: StatefulSets/Jobs on ready nodes come up
        for ss in self.store.list("StatefulSet"):
            want = int(ss.spec.get("replicas", 1))
            if ss.status.get("readyReplicas", 0) < want:
                def mark(o, want=want):
                    o.status["readyReplicas"] = want
                from kaito_tpu.controllers.runtime import update_with_retry

                update_with_retry(self.store, "StatefulSet",
                                  ss.metadata.namespace, ss.metadata.name, mark)
        for dep in self.store.list("Deployment"):
            want = int(dep.spec.get("replicas", 1))
            if dep.status.get("readyReplicas", 0) < want:
                def mark(o, want=want):
                    o.status["readyReplicas"] = want
                from kaito_tpu.controllers.runtime import update_with_retry

                update_with_retry(self.store, "Deployment",
                                  dep.metadata.namespace, dep.metadata.name, mark)
        for job in self.store.list("Job"):
            if not job.status.get("succeeded") and not job.status.get("failed"):
                def mark(o):
                    o.status["succeeded"] = 1
                from kaito_tpu.controllers.runtime import update_with_retry

                update_with_retry(self.store, "Job", job.metadata.namespace,
                                  job.metadata.name, mark)

        # garbage-collect nodes of deleted pools (cloud reclaim)
        pools = {p.metadata.name for p in self.store.list("NodePool")}
        for n in self.store.list("Node"):
            owner_pool = n.metadata.name.rsplit("-node-", 1)[0]
            if "-node-" in n.metadata.name and owner_pool not in pools:
                self.store.delete("Node", "", n.metadata.name)

    def mark_drifted(self, node_name: str) -> None:
        """Failure/drift injection: flag a node as drifted (the drift
        controller reacts the way the reference reacts to Karpenter
        NodeClaim Drifted conditions)."""
        def mutate(n):
            n.status["drifted"] = True

        from kaito_tpu.controllers.runtime import update_with_retry

        update_with_retry(self.store, "Node", "", node_name, mutate)
