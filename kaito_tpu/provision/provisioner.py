"""NodeProvisioner interface.

Parity with the reference's pluggable provisioning backend
(``pkg/nodeprovision/provisioner.go:36-100``): ProvisionNodes /
EnsureNodesReady / DeleteNodes / BuildNodeSelector, re-expressed for
TPU slices — the unit of provisioning is a slice (NodePool whose nodes
carry ``gke-tpu-accelerator``/``gke-tpu-topology`` labels), not a VM
count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol

from kaito_tpu.sku.catalog import TPUSliceSpec


@dataclass
class ProvisionRequest:
    owner_name: str
    owner_namespace: str
    slice_spec: TPUSliceSpec
    num_slices: int = 1
    extra_labels: dict[str, str] = field(default_factory=dict)
    preferred_nodes: list[str] = field(default_factory=list)


class NodeProvisioner(Protocol):
    name: str

    def provision(self, req: ProvisionRequest) -> None:
        """Ensure capacity objects exist (idempotent)."""

    def ensure_ready(self, req: ProvisionRequest) -> tuple[bool, list[str]]:
        """Returns (all slices ready, ready node names)."""

    def deprovision(self, req: ProvisionRequest) -> None:
        """Tear down capacity for the owner."""

    def node_selector(self, req: ProvisionRequest) -> dict[str, str]:
        """Labels the workload pods must schedule onto."""

    def set_drift_budget(self, req: ProvisionRequest, allow: bool) -> None:
        """Open/close the rolling node-replacement budget (drift)."""
