"""BYO provisioner: no-op provisioning against user-labeled nodes.

Parity: ``pkg/nodeprovision/byo-provisioner/byo_provisioner.go:131`` —
nodes are matched purely by the workspace's labelSelector; the slice
shape is derived from the nodes' TPU labels
(sku.get_tpu_config_from_node_labels), never created.
"""

from __future__ import annotations

from kaito_tpu.controllers.objects import is_node_ready
from kaito_tpu.controllers.runtime import Store
from kaito_tpu.provision.provisioner import ProvisionRequest


class BYOProvisioner:
    name = "byo"

    def __init__(self, store: Store):
        self.store = store

    def provision(self, req: ProvisionRequest) -> None:
        return  # bring-your-own: nothing to create

    def ensure_ready(self, req: ProvisionRequest) -> tuple[bool, list[str]]:
        nodes = self.store.list("Node", labels=req.extra_labels or None)
        if req.preferred_nodes:
            nodes = [n for n in nodes if n.metadata.name in req.preferred_nodes] or nodes
        ready = sorted(n.metadata.name for n in nodes if is_node_ready(n))
        want = req.slice_spec.num_hosts * req.num_slices
        return len(ready) >= want, ready[:want] if len(ready) >= want else ready

    def deprovision(self, req: ProvisionRequest) -> None:
        return

    def node_selector(self, req: ProvisionRequest) -> dict[str, str]:
        return dict(req.extra_labels)

    def set_drift_budget(self, req: ProvisionRequest, allow: bool) -> None:
        return
