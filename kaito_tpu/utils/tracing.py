"""Dependency-free request tracing + engine flight recorder.

Two bounded recorders back the observability surface
(docs/observability.md):

- ``RingTracer`` holds request-phase **spans** (queue wait, admission,
  prefill chunks, KV import/export, host spill/restore, decode) in a
  fixed-capacity ring — recording is a deque append under a lock held
  for nanoseconds, so the engine hot loop never blocks on a scrape.
- ``StepTimeline`` is the engine **flight recorder**: one bounded
  record per scheduler step (wall time, running/waiting, prefill vs
  decode tokens, KV page usage, preemptions, shed/expired counts).

Both export as Chrome trace-event JSON (``/debug/trace`` and
``/debug/timeline``) loadable directly in Perfetto / chrome://tracing.

Trace identity rides the ``X-Request-Id`` header end to end: the DP
router generates/forwards it (accepting an inbound W3C ``traceparent``),
the engine stamps it on ``Request.trace_id``, the PD handoff carries it
in the staged-export meta, and the multihost abort broadcast tags its
spans with it.  Timestamps are ``time.monotonic()`` seconds; the Chrome
export converts to microseconds, which is all Perfetto needs (only
relative time matters inside one trace).
"""

from __future__ import annotations

import collections
import re
import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterable, Optional

__all__ = [
    "Span", "RingTracer", "StepTimeline",
    "chrome_trace", "timeline_trace", "format_span_tree",
    "decode_gap_summary",
    "parse_traceparent", "sanitize_request_id", "make_request_id",
]

# W3C trace-context: version "00" — 00-<32 hex trace id>-<16 hex span id>-<flags>
_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}$")
# characters allowed in a client-supplied request id (header-safe, log-safe)
_ID_UNSAFE_RE = re.compile(r"[^A-Za-z0-9._:\-]")
_MAX_ID_LEN = 128


def parse_traceparent(header: Optional[str]) -> Optional[str]:
    """Extract the 32-hex trace id from a W3C ``traceparent`` header,
    or None when absent/malformed (malformed headers are dropped, not
    errors — tracing must never fail a request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    tid = m.group(1)
    return tid if tid != "0" * 32 else None


def sanitize_request_id(value: Optional[str]) -> Optional[str]:
    """Clamp a client-supplied ``X-Request-Id`` to header/log-safe
    characters; None when nothing usable remains."""
    if not value:
        return None
    cleaned = _ID_UNSAFE_RE.sub("", value.strip())[:_MAX_ID_LEN]
    return cleaned or None


def make_request_id(prefix: str = "req") -> str:
    return f"{prefix}-{uuid.uuid4().hex[:16]}"


@dataclass
class Span:
    """One recorded phase: ``[t0, t0+dur]`` in monotonic seconds."""

    name: str
    trace_id: str
    t0: float
    dur: float
    attrs: dict = field(default_factory=dict)

    @property
    def t1(self) -> float:
        return self.t0 + self.dur


class RingTracer:
    """Bounded span recorder shared by the engine thread and HTTP
    handler threads.  The lock guards only a deque append / snapshot
    copy, so recording costs the hot loop effectively nothing."""

    def __init__(self, capacity: int = 8192):
        self._spans: "collections.deque[Span]" = collections.deque(
            maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        # ring-overflow evictions since start/clear: surfaced as
        # /debug/trace metadata so a missing span reads as overflow,
        # not as missing instrumentation
        self._dropped = 0

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def record(self, name: str, trace_id: str, t0: float, dur: float,
               **attrs) -> None:
        span = Span(name, trace_id, float(t0), max(0.0, float(dur)),
                    attrs or {})
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(span)

    @contextmanager
    def span(self, name: str, trace_id: str, **attrs):
        """Record the wrapped block as one span; an escaping exception
        is noted in the attrs and re-raised."""
        t0 = time.monotonic()
        try:
            yield attrs
        except BaseException as e:
            attrs["error"] = type(e).__name__
            raise
        finally:
            self.record(name, trace_id, t0, time.monotonic() - t0, **attrs)

    def spans(self, trace_id: Optional[str] = None) -> list[Span]:
        """Snapshot, oldest first; optionally filtered to one trace."""
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def chrome_trace(self, trace_id: Optional[str] = None) -> dict:
        return chrome_trace(self.spans(trace_id), dropped=self.dropped)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


def chrome_trace(spans: Iterable[Span],
                 dropped: Optional[int] = None) -> dict:
    """Chrome trace-event JSON: one complete ("X") event per span, one
    virtual thread per trace id (named via "M" metadata events), so
    Perfetto lays each request out on its own track.  ``dropped``
    (ring-overflow evictions) rides the top-level ``metadata`` key —
    Perfetto ignores it, diagnosers don't."""
    tids: dict[str, int] = {}
    events: list[dict] = []
    for s in sorted(spans, key=lambda s: (s.t0, -s.dur)):
        tid = tids.get(s.trace_id)
        if tid is None:
            tid = tids[s.trace_id] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid, "args": {"name": s.trace_id}})
        events.append({
            "name": s.name, "cat": "request", "ph": "X", "pid": 1,
            "tid": tid, "ts": int(s.t0 * 1e6), "dur": int(s.dur * 1e6),
            "args": {**s.attrs, "trace_id": s.trace_id},
        })
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if dropped is not None:
        doc["metadata"] = {"dropped": int(dropped)}
    return doc


def format_span_tree(spans: Iterable[Span]) -> str:
    """Indented text rendering of a span list, nested by interval
    containment — the slow-request log format.  Spans sort by start
    time (widest first on ties) so an enclosing "request" span parents
    its phases."""
    ordered = sorted(spans, key=lambda s: (s.t0, -s.dur))
    if not ordered:
        return "(no spans)"
    base = ordered[0].t0
    lines: list[str] = []
    stack: list[Span] = []
    for s in ordered:
        while stack and s.t1 > stack[-1].t1 + 1e-9:
            stack.pop()
        attrs = " ".join(f"{k}={v}" for k, v in s.attrs.items())
        lines.append("%s%-18s +%8.3fms %9.3fms%s" % (
            "  " * len(stack), s.name, (s.t0 - base) * 1e3, s.dur * 1e3,
            f"  [{attrs}]" if attrs else ""))
        stack.append(s)
    return "\n".join(lines)


class StepTimeline:
    """Bounded per-step flight recorder for the engine step loop."""

    def __init__(self, capacity: int = 4096):
        self._records: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._dropped = 0

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def add(self, t0: float, dur: float, **fields) -> None:
        rec = {"ts": float(t0), "dur": max(0.0, float(dur))}
        rec.update(fields)
        with self._lock:
            if len(self._records) == self._records.maxlen:
                self._dropped += 1
            self._records.append(rec)

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def chrome_trace(self) -> dict:
        return timeline_trace(self.records(), dropped=self.dropped)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def decode_gap_summary(records: Iterable[dict]) -> tuple[float, float]:
    """``(device_idle_pct, mean_gap_ms)`` over the timeline records
    that carry a ``dispatch_gap`` field (the async decode loop's
    per-step dispatch-gap span, docs/decode-loop.md).

    ``device_idle_pct`` is total gap time over total step wall time for
    decode steps — the fraction of the decode wall clock the device
    spent waiting on the host.  Both are 0.0 when the async loop is off
    (no record carries the field), so bench columns stay schema-stable
    either way."""
    gaps: list[float] = []
    wall = 0.0
    for rec in records:
        g = rec.get("dispatch_gap")
        if g is None or not rec.get("decode_steps", 0):
            continue
        gaps.append(float(g))
        wall += float(rec.get("dur", 0.0))
    if not gaps or wall <= 0.0:
        return 0.0, 0.0
    total_gap = sum(gaps)
    return (min(100.0, 100.0 * total_gap / wall),
            1e3 * total_gap / len(gaps))


def timeline_trace(records: Iterable[dict],
                   dropped: Optional[int] = None) -> dict:
    """Chrome trace-event JSON for the step timeline: an "X" slice per
    step (args carry the full record) plus "C" counter tracks for batch
    occupancy and KV page usage, so Perfetto graphs them over time.
    ``dropped`` rides ``metadata`` like chrome_trace's."""
    events: list[dict] = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": "engine.step"}}]
    for rec in sorted(records, key=lambda r: r.get("ts", 0.0)):
        ts = int(rec.get("ts", 0.0) * 1e6)
        events.append({
            "name": "engine.step", "cat": "engine", "ph": "X", "pid": 1,
            "tid": 0, "ts": ts, "dur": int(rec.get("dur", 0.0) * 1e6),
            "args": {k: v for k, v in rec.items() if k not in ("ts", "dur")},
        })
        events.append({"name": "batch", "ph": "C", "pid": 1, "tid": 0,
                       "ts": ts, "args": {
                           "running": rec.get("running", 0),
                           "waiting": rec.get("waiting", 0)}})
        events.append({"name": "kv_pages_used", "ph": "C", "pid": 1,
                       "tid": 0, "ts": ts,
                       "args": {"used": rec.get("kv_pages_used", 0)}})
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if dropped is not None:
        doc["metadata"] = {"dropped": int(dropped)}
    return doc
