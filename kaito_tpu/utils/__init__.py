from kaito_tpu.utils.quantity import Quantity, parse_quantity, format_quantity  # noqa: F401
