"""Incident flight recorder: bounded JSON bundles of every debug surface.

When an SLO page, an engine-fatal error, or a SIGTERM-with-in-flight-
requests fires, the evidence an operator needs — the span ring, the
step timeline, the last devprof window, the SLO burn snapshot, queue/
slot state — is normally gone by the time anyone attaches.  The flight
recorder snapshots all of it into one timestamped JSON bundle under
``--flight-dir`` at the moment of the trigger, so the black box
survives the pod.

Three automatic triggers (watched by :class:`FlightWatcher`):

- ``slo_page``     — any SLI's alert state transitions into ``page``
                     (deduped: one bundle per excursion, re-armed when
                     every SLI leaves ``page``),
- ``engine_fatal`` — the engine-fatal counter advances (the PR-1
                     failure-domain classification),
- ``sigterm``      — the server's SIGTERM handler calls
                     :meth:`FlightRecorder.record` directly when
                     requests are still in flight,

plus a manual one: ``POST /debug/flight``.

Bundles are bounded: beyond ``max_bundles`` the oldest (by mtime) are
pruned, LRU-style.  ``GET /debug/flight`` lists them; ``GET
/debug/flight/<name>`` fetches one.  The fleet scraper folds the
``kaito:flight_bundles_total`` gauge so the workspace controller can
surface a ``FlightRecorded`` Event the moment any replica writes one.

Everything here is dependency-free and engine-agnostic: the recorder
takes a ``collect`` callable returning the bundle body, and
:func:`engine_flight_snapshot` is the canonical collector over an
InferenceEngine + SLOWatchdog pair.  All writes are atomic
(tmp + rename) so a scrape never sees a torn bundle.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

SCHEMA = "kaito.flight/1"

# triggers (bundle["trigger"] and the filename tag)
TRIGGER_SLO_PAGE = "slo_page"
TRIGGER_ENGINE_FATAL = "engine_fatal"
TRIGGER_SIGTERM = "sigterm"
TRIGGER_MANUAL = "manual"

_SPAN_CAP = 2048      # newest spans kept per bundle
_STEP_CAP = 1024      # newest timeline records kept per bundle


def _safe(fn: Callable[[], object], fallback=None):
    """Debug surfaces must never take the incident path down."""
    try:
        return fn()
    except Exception as exc:      # pragma: no cover - defensive
        logger.warning("flight recorder surface failed: %s", exc)
        return fallback


class FlightRecorder:
    """Write bounded, timestamped JSON bundles under ``flight_dir``.

    ``collect`` returns the bundle body (the debug surfaces); the
    recorder adds the envelope (schema, trigger, reason, timestamps,
    sequence) and enforces the LRU bound.  Thread-safe: triggers can
    fire from the watcher thread, a handler thread, and the signal
    handler concurrently.
    """

    def __init__(self, flight_dir: str,
                 collect: Callable[[], dict],
                 max_bundles: int = 16,
                 time_fn: Callable[[], float] = time.time):
        if not flight_dir:
            raise ValueError("flight_dir must be a non-empty path")
        self.dir = flight_dir
        self.collect = collect
        self.max_bundles = max(1, int(max_bundles))
        self.time_fn = time_fn
        self._seq = 0
        self._lock = threading.Lock()
        self.bundles_total = 0
        os.makedirs(self.dir, exist_ok=True)

    # -- write ---------------------------------------------------------

    def record(self, trigger: str, reason: str = "") -> Optional[str]:
        """Snapshot every surface into one bundle; returns its name
        (or None if the write failed — incidents must not cascade)."""
        now = self.time_fn()
        with self._lock:
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        name = f"flight-{stamp}-{seq:04d}-{trigger}.json"
        bundle = {
            "schema": SCHEMA,
            "trigger": trigger,
            "reason": reason,
            "written_at": now,
            "written_at_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime(now)),
            "seq": seq,
        }
        body = _safe(self.collect, fallback={"collect_error": True})
        if isinstance(body, dict):
            bundle.update(body)
        try:
            path = os.path.join(self.dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, default=str)
            os.replace(tmp, path)
        except OSError as exc:
            logger.warning("flight bundle write failed: %s", exc)
            return None
        with self._lock:
            self.bundles_total += 1
        self._prune()
        logger.warning("flight bundle recorded: %s (trigger=%s%s)", name,
                       trigger, f", {reason}" if reason else "")
        return name

    def _prune(self) -> None:
        """LRU by mtime: keep the newest ``max_bundles`` bundles."""
        try:
            entries = []
            for n in os.listdir(self.dir):
                if n.startswith("flight-") and n.endswith(".json"):
                    p = os.path.join(self.dir, n)
                    entries.append((os.path.getmtime(p), p))
            entries.sort()
            for _, p in entries[:-self.max_bundles]:
                os.unlink(p)
        except OSError:      # pragma: no cover - fs race
            pass

    # -- read (the /debug/flight surface) ------------------------------

    def list(self) -> list[dict]:
        """Newest-first bundle index (name, bytes, mtime, trigger)."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            if not (n.startswith("flight-") and n.endswith(".json")):
                continue
            p = os.path.join(self.dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            # flight-<stamp>-<seq>-<trigger>.json
            trigger = n[:-5].split("-", 3)[-1] if n.count("-") >= 3 else ""
            out.append({"name": n, "bytes": st.st_size,
                        "mtime": st.st_mtime, "trigger": trigger})
        out.sort(key=lambda e: e["mtime"], reverse=True)
        return out

    def read(self, name: str) -> Optional[bytes]:
        """Fetch one bundle by name; traversal-safe (a name is a bare
        filename, never a path)."""
        if os.path.basename(name) != name or not (
                name.startswith("flight-") and name.endswith(".json")):
            return None
        try:
            with open(os.path.join(self.dir, name), "rb") as f:
                return f.read()
        except OSError:
            return None


class FlightWatcher:
    """Poll the SLO alert states and the engine-fatal counter; fire the
    recorder on transitions.  ``check()`` is the whole decision step and
    is directly drivable from tests; the thread just calls it on an
    interval.  The engine itself needs zero trigger wiring — the watcher
    observes the same surfaces an operator would.
    """

    def __init__(self, recorder: FlightRecorder,
                 slo_snapshot: Optional[Callable[[], dict]] = None,
                 fatal_count: Optional[Callable[[], int]] = None,
                 interval_s: float = 1.0):
        self.recorder = recorder
        self.slo_snapshot = slo_snapshot
        self.fatal_count = fatal_count
        self.interval_s = max(0.05, float(interval_s))
        self._paging = False           # dedupe: armed only outside page
        self._fatal_seen: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check(self) -> list[str]:
        """One poll; returns the names of any bundles written."""
        wrote = []
        if self.slo_snapshot is not None:
            snap = _safe(self.slo_snapshot, fallback={}) or {}
            alerts = snap.get("alerts") or {}
            paging = sorted(s for s, st in alerts.items() if st == "page")
            if paging and not self._paging:
                # one bundle per excursion into page, however many SLIs
                # join it while it lasts; re-armed when all leave
                name = self.recorder.record(
                    TRIGGER_SLO_PAGE, reason="paging: " + ", ".join(paging))
                if name:
                    wrote.append(name)
            self._paging = bool(paging)
        if self.fatal_count is not None:
            n = _safe(self.fatal_count, fallback=None)
            if n is not None:
                if self._fatal_seen is None:
                    self._fatal_seen = n   # baseline, not an incident
                elif n > self._fatal_seen:
                    name = self.recorder.record(
                        TRIGGER_ENGINE_FATAL,
                        reason=f"engine_fatal_total {self._fatal_seen} "
                               f"-> {n}")
                    if name:
                        wrote.append(name)
                    self._fatal_seen = n
        return wrote

    def start(self) -> None:
        if self._thread is not None:
            return

        def _run():
            while not self._stop.wait(self.interval_s):
                _safe(self.check)

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="kaito-flight-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


def config_fingerprint(cfg) -> dict:
    """Stable digest + full dump of the engine config, so two bundles
    from differently-configured replicas are distinguishable at a
    glance."""
    try:
        import dataclasses
        values = dataclasses.asdict(cfg)
    except Exception:
        values = {k: v for k, v in vars(cfg).items()
                  if not k.startswith("_")}
    blob = json.dumps(values, sort_keys=True, default=str)
    return {"sha256": hashlib.sha256(blob.encode()).hexdigest()[:16],
            "values": json.loads(blob)}


def engine_flight_snapshot(engine, slo=None, cfg=None) -> dict:
    """The canonical ``collect`` over an engine + watchdog: every debug
    surface the server exposes, flattened into one JSON-safe dict.
    Each surface is collected defensively — a wedged engine must still
    produce a (partial) bundle."""
    body: dict = {}
    engines = getattr(engine, "engines", None) or [engine]

    if slo is not None:
        body["slo"] = _safe(slo.snapshot)

    spans = []
    dropped = 0
    for e in engines:
        tracer = getattr(e, "tracer", None)
        if tracer is None:
            continue
        for s in _safe(tracer.spans, fallback=[]) or []:
            spans.append({"name": s.name, "trace_id": s.trace_id,
                          "t0": s.t0, "dur": s.dur, "attrs": s.attrs})
        dropped += int(getattr(tracer, "dropped", 0))
    body["spans"] = spans[-_SPAN_CAP:]
    body["spans_dropped"] = dropped + max(0, len(spans) - _SPAN_CAP)

    steps = []
    for e in engines:
        tl = getattr(e, "timeline", None)
        if tl is not None:
            steps.extend(_safe(tl.records, fallback=[]) or [])
    body["timeline"] = steps[-_STEP_CAP:]

    dp = next((getattr(e, "devprof", None) for e in engines
               if getattr(e, "devprof", None) is not None), None)
    body["devprof"] = _safe(dp.snapshot) if dp is not None else None

    body["queue"] = {
        "running": int(_safe(lambda: engine.num_running, fallback=0) or 0),
        "waiting": int(_safe(lambda: engine.num_waiting, fallback=0) or 0),
    }
    slots = []
    for e in engines:
        for i, slot in enumerate(getattr(e, "slots", []) or []):
            req = getattr(slot, "request", None)
            if req is None:
                continue
            slots.append(_safe(lambda r=req, s=slot, j=i: {
                "slot": j, "req_id": r.req_id, "trace_id": r.trace_id,
                "tenant": r.tenant, "adapter": r.adapter,
                "position": int(getattr(s, "position", 0)),
                "remaining": int(getattr(s, "remaining", 0)),
                "output_tokens": len(r.output_tokens),
            }))
    body["slots"] = [s for s in slots if s]

    counters = {}
    for e in engines:
        for k, v in (_safe(lambda e=e: dict(e.counters), fallback={})
                     or {}).items():
            counters[k] = counters.get(k, 0) + v
    body["counters"] = counters

    qos = getattr(engine, "qos", None)
    if qos is not None:
        body["qos_classes"] = _safe(
            lambda: sorted(getattr(qos, "classes", {}) or {}))

    if cfg is not None:
        body["config"] = _safe(lambda: config_fingerprint(cfg))
    return body
