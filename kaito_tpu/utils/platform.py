"""Make the JAX_PLATFORMS env var authoritative for entrypoints.

Some images pre-seed ``jax_platforms`` via a sitecustomize PJRT
registration (e.g. a TPU-tunnel plugin setting "axon,cpu"), which wins
over the environment variable.  Every standalone entrypoint (serving
server, tuning CLI, benchmark probe) calls this before touching a
device so ``JAX_PLATFORMS=cpu python -m kaito_tpu.engine.server ...``
means what it says — matching the reference's expectation that the
runtime honors its launcher's device selection
(presets/workspace/inference/vllm/inference_api.py device args).
"""

import os


def apply_platform_env() -> None:
    """If JAX_PLATFORMS is set, force jax's platform config to it."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception:   # backends already initialized: nothing to do
        pass
