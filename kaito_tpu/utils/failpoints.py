"""Named fault-injection points (failpoints) for chaos testing.

The serving stack's failure-domain isolation (scoped request failure in
the scheduler, breaker-based failover in the DP router, KV-handoff
retry budgets) is only trustworthy if each domain can be *made* to fail
on demand.  This registry gives every interesting failure site a stable
name; tests (or an operator, via ``KAITO_FAILPOINTS``) activate a named
point with an action and the instrumented code path misbehaves exactly
there — raise, delay, or corrupt bytes — while everything around it is
expected to stay healthy.

Instrumented sites (grep for ``FAILPOINTS.fire`` / ``FAILPOINTS.corrupt``):

==========================  ====================================================
name                        where it fires
==========================  ====================================================
``engine.step``             top of ``InferenceEngine.step`` (engine-fatal domain)
``engine.prefill``          per-request inside ``_advance_prefills``
``engine.kv_import``        per-slot inside ``_advance_imports`` (ctx: req_id)
``engine.spill``            host-KV spill in ``_spill_slot``
``pd.export_drain``         ``StagedExport`` D2H drain start
``pd.chunk``                ``StagedExport.get_chunk`` payload (corrupt site)
``router.forward``          DP router backend connect (ctx: backend url)
==========================  ====================================================

Activation is programmatic (``FAILPOINTS.activate(...)`` or the
``failpoint(...)`` context manager in tests) or via the environment::

    KAITO_FAILPOINTS="engine.kv_import=raise*1;router.forward=delay:0.2"

``name=ACTION[:ARG][*COUNT]`` entries separated by ``;``.  ACTION is
``raise`` | ``delay`` | ``corrupt``; ARG is the delay in seconds or the
raise message; COUNT limits how many times the point fires (-1 =
unlimited).  Inactive failpoints cost one dict lookup — safe to leave
in hot paths.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

ENV_VAR = "KAITO_FAILPOINTS"

ACTIONS = ("raise", "delay", "corrupt")


class FailpointError(RuntimeError):
    """Raised by an active ``raise`` failpoint.  Deliberately a plain
    RuntimeError subclass: instrumented code must NOT special-case it —
    the whole point is to exercise the production error paths."""

    def __init__(self, name: str, message: str = ""):
        super().__init__(message or f"failpoint {name!r} fired")
        self.failpoint = name


@dataclass
class _Point:
    name: str
    action: str = "raise"
    arg: Any = None                       # delay seconds / raise message
    count: int = -1                       # remaining fires; -1 = unlimited
    match: Dict[str, Any] = field(default_factory=dict)
    hits: int = 0

    def matches(self, ctx: Dict[str, Any]) -> bool:
        return all(ctx.get(k) == v for k, v in self.match.items())


class FailpointRegistry:
    """Thread-safe registry of named failure-injection points."""

    def __init__(self):
        self._lock = threading.Lock()
        self._points: Dict[str, _Point] = {}
        self._hits: Dict[str, int] = {}

    # -- activation -------------------------------------------------------
    def activate(self, name: str, action: str = "raise", *,
                 arg: Any = None, count: int = -1, **match) -> None:
        """Arm ``name``.  ``match`` keys restrict firing to calls whose
        context (``fire(name, req_id=...)``) carries equal values, so a
        test can fail ONE request's KV import while its neighbours on
        the same engine proceed."""
        if action not in ACTIONS:
            raise ValueError(f"unknown failpoint action {action!r}; "
                             f"expected one of {ACTIONS}")
        with self._lock:
            self._points[name] = _Point(name=name, action=action, arg=arg,
                                        count=count, match=dict(match))

    def deactivate(self, name: str) -> None:
        with self._lock:
            self._points.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._points.clear()
            self._hits.clear()

    def is_active(self, name: str) -> bool:
        with self._lock:
            return name in self._points

    def hits(self, name: str) -> int:
        with self._lock:
            return self._hits.get(name, 0)

    # -- firing -----------------------------------------------------------
    def _arm(self, name: str, ctx: Dict[str, Any]) -> Optional[_Point]:
        """Consume one fire of ``name`` if armed and matching."""
        with self._lock:
            p = self._points.get(name)
            if p is None or not p.matches(ctx):
                return None
            if p.count == 0:
                return None
            if p.count > 0:
                p.count -= 1
                if p.count == 0:
                    self._points.pop(name, None)
            p.hits += 1
            self._hits[name] = self._hits.get(name, 0) + 1
            return p

    def fire(self, name: str, **ctx) -> None:
        """Execute ``name`` if armed: raise FailpointError, sleep, or —
        for a ``corrupt`` point hit via ``fire`` — raise as well (bytes
        corruption needs the ``corrupt()`` entry point)."""
        if not self._points:               # fast path: nothing armed
            return
        p = self._arm(name, ctx)
        if p is None:
            return
        if p.action == "delay":
            time.sleep(float(p.arg or 0.05))
            return
        raise FailpointError(name, str(p.arg) if p.arg else "")

    def corrupt(self, name: str, data: bytes, **ctx) -> bytes:
        """Pass ``data`` through ``name``: an armed ``corrupt`` point
        flips bytes (checksum-detectable), ``delay`` sleeps, ``raise``
        raises; inactive points return the data untouched."""
        if not self._points:
            return data
        p = self._arm(name, ctx)
        if p is None:
            return data
        if p.action == "delay":
            time.sleep(float(p.arg or 0.05))
            return data
        if p.action == "raise":
            raise FailpointError(name, str(p.arg) if p.arg else "")
        if not data:
            return data
        mutated = bytearray(data)
        mutated[len(mutated) // 2] ^= 0xFF
        return bytes(mutated)

    # -- env --------------------------------------------------------------
    def load_env(self, spec: Optional[str] = None) -> None:
        """Parse ``name=action[:arg][*count]`` entries from ``spec`` (or
        the KAITO_FAILPOINTS environment variable)."""
        spec = os.environ.get(ENV_VAR, "") if spec is None else spec
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, _, rhs = entry.partition("=")
            rhs = rhs or "raise"
            count = -1
            if "*" in rhs:
                rhs, _, n = rhs.rpartition("*")
                count = int(n)
            action, _, arg = rhs.partition(":")
            self.activate(name.strip(), action.strip() or "raise",
                          arg=arg or None, count=count)


FAILPOINTS = FailpointRegistry()
FAILPOINTS.load_env()


@contextlib.contextmanager
def failpoint(name: str, action: str = "raise", *, arg: Any = None,
              count: int = -1, **match):
    """Scoped activation for tests: arms on entry, disarms on exit."""
    FAILPOINTS.activate(name, action, arg=arg, count=count, **match)
    try:
        yield FAILPOINTS
    finally:
        FAILPOINTS.deactivate(name)
