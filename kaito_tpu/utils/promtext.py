"""Prometheus text-exposition (0.0.4) parsing + invariant checks.

The strict mini-parser that used to live in
``tests/test_metrics_format.py``, promoted to library code so every
consumer of an exposition payload shares ONE implementation:

- the fleet telemetry plane (``kaito_tpu/runtime/fleet.py``) parses
  replica ``/metrics`` payloads with it;
- the exposition-format test suite round-trips every registry in the
  codebase (engine, router, EPP, manager, tuning) through it, so a
  label-escaping or histogram-invariant regression fails in one place.

``parse_exposition`` is deliberately STRICT — every non-comment line
must be a well-formed sample — because a payload our own toolkit
emitted should never need lenient parsing; leniency would hide exactly
the formatting regressions this module exists to catch.  Errors raise
``ValueError`` (callers that scrape over the network treat that as a
failed scrape).
"""

from __future__ import annotations

import math
import re

# one full sample line: name, optional {labels}, value
SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{.*\})? "
    r"(-?(?:[0-9.]+(?:e[+-]?[0-9]+)?|inf|nan))$",
    re.IGNORECASE)
_LE_RE = re.compile(r'le="([^"]*)"')
# one label assignment inside {...}; values may contain escaped
# backslash/quote/newline (the writer escapes exactly these three)
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')

Sample = tuple  # (name, labels_str, value)


def parse_exposition(text: str) -> list[Sample]:
    """Parse a full exposition payload.  Every non-comment, non-blank
    line must be a valid sample; returns ``[(name, labels_str,
    float_value)]`` (``labels_str`` is ``""`` for unlabelled samples).
    Raises ``ValueError`` on the first unparseable line."""
    samples: list[Sample] = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        m = SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        samples.append((m.group(1), m.group(2) or "", float(m.group(3))))
    return samples


def parse_labels(labels_str: str) -> dict[str, str]:
    """``'{a="x",le="+Inf"}'`` -> ``{"a": "x", "le": "+Inf"}`` with the
    writer's escapes (``\\\\``, ``\\"``, ``\\n``) undone."""
    out: dict[str, str] = {}
    for name, raw in _LABEL_RE.findall(labels_str or ""):
        out[name] = (raw.replace("\\n", "\n").replace('\\"', '"')
                     .replace("\\\\", "\\"))
    return out


def family_values(samples: list[Sample], name: str) -> list[float]:
    """Every sample value of one family (all label sets)."""
    return [v for n, _, v in samples if n == name]


def check_histograms(samples: list[Sample], require: bool = True) -> dict:
    """For every histogram family present: cumulative buckets must be
    monotone in ``le`` and the ``+Inf`` bucket must equal ``_count``.
    Returns ``{(family, labels_without_le): [(le, value), ...]}``;
    raises ``ValueError`` on any violation (or, when ``require``, on a
    payload with no histograms at all)."""
    series: dict[tuple, list] = {}
    for name, labels, value in samples:
        if not name.endswith("_bucket"):
            continue
        le_m = _LE_RE.search(labels)
        if le_m is None:
            raise ValueError(f"{name}{labels}: bucket without le label")
        le = le_m.group(1)
        rest = _LE_RE.sub("", labels).replace(",}", "}").replace("{,", "{")
        if rest == "{}":
            rest = ""                          # unlabelled family
        series.setdefault((name[:-len("_bucket")], rest), []).append(
            (math.inf if le == "+Inf" else float(le), value))
    if require and not series:
        raise ValueError("no histogram buckets in payload")
    counts = {(n, lbl): v for n, lbl, v in samples if n.endswith("_count")}
    for (fam, rest), buckets in series.items():
        buckets.sort()
        if buckets[-1][0] != math.inf:
            raise ValueError(f"{fam}: missing +Inf bucket")
        values = [v for _, v in buckets]
        if values != sorted(values):
            raise ValueError(f"{fam}{rest}: non-monotone buckets")
        count = counts.get((fam + "_count", rest))
        if count is None:
            raise ValueError(f"{fam}{rest}: missing _count")
        if buckets[-1][1] != count:
            raise ValueError(f"{fam}{rest}: +Inf != _count")
    return series
