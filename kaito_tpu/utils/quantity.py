"""Kubernetes-style resource quantities (``27.31Gi``, ``500Mi``, ``2k``).

The reference passes model/storage sizes around as k8s
``resource.Quantity`` strings (e.g. ``modelFileSize: 27.31Gi`` in
``presets/workspace/models/model_catalog.yaml``).  We keep the same
serialized surface so presets and manifests round-trip, but store bytes
as an int.
"""

from __future__ import annotations

import math
import re

_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}
_QTY_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*(Ki|Mi|Gi|Ti|Pi|Ei|k|M|G|T|P|E)?\s*$")


def parse_quantity(s: "str | int | float") -> int:
    """Parse a quantity string into bytes (or a bare count)."""
    if isinstance(s, (int, float)):
        return int(s)
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    value = float(m.group(1))
    suffix = m.group(2)
    if suffix is None:
        scale = 1
    elif suffix in _BINARY:
        scale = _BINARY[suffix]
    else:
        scale = _DECIMAL[suffix]
    return int(math.ceil(value * scale))


def format_quantity(n: int, binary: bool = True) -> str:
    """Render bytes as the largest clean binary suffix (2 decimals max)."""
    if n == 0:
        return "0"
    units = _BINARY if binary else _DECIMAL
    best = ""
    best_scale = 1
    for suffix, scale in units.items():
        if n >= scale and scale > best_scale:
            best, best_scale = suffix, scale
    value = n / best_scale
    if value == int(value):
        return f"{int(value)}{best}"
    return f"{value:.2f}{best}"


class Quantity:
    """A thin value type over bytes with k8s-style parsing/printing."""

    __slots__ = ("bytes",)

    def __init__(self, value: "str | int | float | Quantity"):
        if isinstance(value, Quantity):
            self.bytes = value.bytes
        else:
            self.bytes = parse_quantity(value)

    def __int__(self) -> int:
        return self.bytes

    def __eq__(self, other) -> bool:
        return int(self) == int(Quantity(other))

    def __lt__(self, other) -> bool:
        return self.bytes < Quantity(other).bytes

    def __le__(self, other) -> bool:
        return self.bytes <= Quantity(other).bytes

    def __add__(self, other) -> "Quantity":
        return Quantity(self.bytes + Quantity(other).bytes)

    def __mul__(self, factor: float) -> "Quantity":
        return Quantity(int(self.bytes * factor))

    def __repr__(self) -> str:
        return f"Quantity({format_quantity(self.bytes)})"

    def __str__(self) -> str:
        return format_quantity(self.bytes)

    def __hash__(self) -> int:
        return hash(self.bytes)
