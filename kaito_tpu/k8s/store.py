"""Store adapter over the Kubernetes API.

Implements the exact Store surface the reconcilers already use
(kaito_tpu/controllers/runtime.py) against a real API server, so the
whole controller layer becomes deployable without changes: the manager
constructs ``Manager(store=KubeStore(...))`` and every reconcile now
round-trips through the cluster (reference analogue:
``cmd/workspace/main.go:206`` ctrl.NewManager + its cached client).

Semantics mapping:
- resourceVersion conflicts -> HTTP 409 -> ConflictError (the retry
  helpers work unchanged)
- finalizer-gated deletion is native k8s behavior
- our CRDs declare the status subresource, so update() writes spec and
  status through their separate endpoints
- watch() fans server watch streams into the same callback signature
  the in-memory Store uses (event, kind, object)
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from kaito_tpu.api.meta import KaitoObject
from kaito_tpu.controllers.runtime import ConflictError, NotFoundError
from kaito_tpu.k8s.client import ApiError, KubeClient
from kaito_tpu.k8s.codec import (
    STATUS_SUBRESOURCE,
    from_wire,
    resource_path,
    to_wire,
)

logger = logging.getLogger(__name__)


class KubeStore:
    """Store-compatible adapter over a KubeClient."""

    def __init__(self, client: Optional[KubeClient] = None,
                 namespace: str = "default"):
        self.client = client or KubeClient()
        self.namespace = namespace
        self._watchers: list[Callable[[str, str, KaitoObject], None]] = []
        self._watch_stop = threading.Event()
        self._watch_threads: list[threading.Thread] = []
        # Events recorded by reconcilers mirror to the API server as
        # real v1.Event objects (and stay greppable in-memory too)
        from kaito_tpu.k8s.events import EventRecorder, KubeEventSink

        self.events = EventRecorder(
            sink=KubeEventSink(self.client, namespace=namespace))
        # manager metrics hook: called with the kind each time a watch
        # stream ends and the loop reconnects
        self.on_watch_restart: Optional[Callable[[str], None]] = None

    # -- CRUD ----------------------------------------------------------

    def _ns(self, obj_or_ns) -> str:
        if isinstance(obj_or_ns, str):
            return obj_or_ns or self.namespace
        return obj_or_ns.metadata.namespace or self.namespace

    def create(self, obj: KaitoObject) -> KaitoObject:
        wire = to_wire(obj)
        wire["metadata"].pop("resourceVersion", None)
        path = resource_path(obj.kind, self._ns(obj))
        try:
            out = self.client.request_json("POST", path, body=wire)
        except ApiError as e:
            if e.status == 409:
                raise ConflictError(str(e)) from None
            raise
        created = from_wire(out)
        if obj.kind in STATUS_SUBRESOURCE and wire.get("status"):
            # POST ignores status on subresource kinds; push it after
            try:
                wire_st = to_wire(created)
                wire_st["status"] = wire["status"]
                out = self.client.request_json(
                    "PUT", resource_path(obj.kind, self._ns(obj),
                                         obj.metadata.name, "status"),
                    body=wire_st)
                created = from_wire(out)
            except ApiError:
                logger.debug("status subresource write skipped", exc_info=True)
        return created

    def get(self, kind: str, namespace: str, name: str) -> KaitoObject:
        try:
            out = self.client.request_json(
                "GET", resource_path(kind, namespace or self.namespace, name))
        except ApiError as e:
            if e.status == 404:
                raise NotFoundError(f"{kind} {namespace}/{name} not found") \
                    from None
            raise
        return from_wire(out)

    def try_get(self, kind: str, namespace: str, name: str
                ) -> Optional[KaitoObject]:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             labels: Optional[dict[str, str]] = None) -> list[KaitoObject]:
        path = resource_path(kind, namespace)
        query = {}
        if labels:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(labels.items()))
        out = self.client.request_json("GET", path, query=query or None)
        items = []
        for item in out.get("items", []):
            item.setdefault("kind", kind)
            items.append(from_wire(item))
        return sorted(items, key=lambda o: o.metadata.name)

    def update(self, obj: KaitoObject) -> KaitoObject:
        wire = to_wire(obj)
        ns = self._ns(obj)
        path = resource_path(obj.kind, ns, obj.metadata.name)
        try:
            out = self.client.request_json("PUT", path, body=wire)
        except ApiError as e:
            if e.status == 409:
                raise ConflictError(str(e)) from None
            if e.status == 404:
                raise NotFoundError(str(e)) from None
            raise
        if obj.kind in STATUS_SUBRESOURCE and wire.get("status"):
            st_wire = dict(out)
            st_wire["status"] = wire["status"]
            try:
                out = self.client.request_json(
                    "PUT", path + "/status", body=st_wire)
            except ApiError as e:
                if e.status == 409:
                    raise ConflictError(str(e)) from None
                if e.status != 404:
                    raise
                # the main PUT finalized a deletion: nothing to update
        return from_wire(out)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            self.client.request_json(
                "DELETE", resource_path(kind, namespace or self.namespace,
                                        name))
        except ApiError as e:
            if e.status == 404:
                raise NotFoundError(f"{kind} {namespace}/{name} not found") \
                    from None
            raise

    # -- watch ---------------------------------------------------------

    def watch(self, fn: Callable[[str, str, KaitoObject], None]) -> None:
        self._watchers.append(fn)

    def _notify(self, event: str, kind: str, obj: KaitoObject) -> None:
        for fn in list(self._watchers):
            try:
                fn(event, kind, obj)
            except Exception:
                logger.exception("watch callback failed")

    def start_watching(self, kinds: list[str]) -> None:
        """Spawn one reconnecting watch stream per kind; events fan into
        the registered callbacks (informer analogue)."""
        for kind in kinds:
            t = threading.Thread(target=self._watch_loop, args=(kind,),
                                 daemon=True, name=f"watch-{kind}")
            t.start()
            self._watch_threads.append(t)

    def _watch_loop(self, kind: str) -> None:
        path = resource_path(kind, None)
        last_rv = {"rv": ""}
        while not self._watch_stop.is_set():
            def handler(evt_type: str, wire: dict, kind=kind):
                if not evt_type or not wire:
                    return
                # resume token: reconnects continue from the last seen
                # event instead of silently dropping the gap
                rv = (wire.get("metadata") or {}).get("resourceVersion", "")
                if rv:
                    last_rv["rv"] = rv
                wire.setdefault("kind", kind)
                self._notify(evt_type, kind, from_wire(wire))

            self.client.watch(path, handler, self._watch_stop,
                              resource_version=last_rv["rv"])
            if not self._watch_stop.is_set() and self.on_watch_restart:
                try:
                    self.on_watch_restart(kind)
                except Exception:
                    logger.debug("watch-restart hook failed", exc_info=True)
            self._watch_stop.wait(1.0)

    def stop_watching(self) -> None:
        self._watch_stop.set()
