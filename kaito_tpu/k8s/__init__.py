"""Real-cluster backend: wire codec, REST client, and a Store adapter
speaking the Kubernetes API (the counterpart of the reference's
controller-runtime client + pkg/k8sclient singletons)."""

from kaito_tpu.k8s.client import KubeClient
from kaito_tpu.k8s.codec import from_wire, to_wire
from kaito_tpu.k8s.store import KubeStore

__all__ = ["KubeClient", "KubeStore", "from_wire", "to_wire"]
