"""Kubernetes Event recording for the controller layer.

The in-process analogue of client-go's EventRecorder + correlator
(``k8s.io/client-go/tools/record``): reconcilers call
``record_event(store, obj, type, reason, message)`` on operator-visible
transitions, the recorder dedupes identical events into one entry with
a bumped ``count`` (the aggregation ``kubectl get events`` shows as
``x12``), keeps a bounded in-memory ring (the sink tests and the fake
store read), and — when constructed with a ``KubeEventSink`` — mirrors
each emission to the API server as a ``v1.Event``.

Event emission is strictly best-effort: a recorder failure must never
fail a reconcile, so every sink error is swallowed into a debug log.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from kaito_tpu.api.meta import now_iso

logger = logging.getLogger(__name__)

EVENT_NORMAL = "Normal"
EVENT_WARNING = "Warning"

DEFAULT_CAPACITY = 4096


@dataclass
class Event:
    """One deduplicated event series (count >= 1)."""

    kind: str
    namespace: str
    name: str
    type: str            # "Normal" | "Warning"
    reason: str          # CamelCase, greppable (e.g. "ProvisioningStarted")
    message: str
    uid: str = ""
    count: int = 1
    first_timestamp: str = field(default_factory=now_iso)
    last_timestamp: str = field(default_factory=now_iso)

    @property
    def dedupe_key(self) -> tuple:
        return (self.kind, self.namespace, self.name, self.type,
                self.reason, self.message)

    def to_wire(self, sink_namespace: str = "default",
                component: str = "kaito-tpu-manager") -> dict:
        """``v1.Event`` wire shape (events land in the involved
        object's namespace; cluster-scoped objects fall back to the
        sink's)."""
        ns = self.namespace or sink_namespace
        stable = hashlib.sha256(
            repr(self.dedupe_key).encode()).hexdigest()[:16]
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": f"{self.name}.{stable}", "namespace": ns},
            "involvedObject": {"kind": self.kind, "namespace": self.namespace,
                               "name": self.name, "uid": self.uid},
            "type": self.type,
            "reason": self.reason,
            "message": self.message,
            "count": self.count,
            "firstTimestamp": self.first_timestamp,
            "lastTimestamp": self.last_timestamp,
            "source": {"component": component},
        }


class EventRecorder:
    """Deduplicating bounded recorder; optionally mirrors to a sink."""

    def __init__(self, sink: "Optional[KubeEventSink]" = None,
                 capacity: int = DEFAULT_CAPACITY):
        self.sink = sink
        self.capacity = max(1, int(capacity))
        self._events: dict[tuple, Event] = {}   # insertion-ordered
        self._lock = threading.Lock()

    def event(self, obj, etype: str, reason: str, message: str) -> Event:
        """Record one occurrence against a typed object (anything with
        ``.kind`` and ``.metadata``)."""
        return self.eventf(obj.kind, obj.metadata.namespace,
                           obj.metadata.name, etype, reason, message,
                           uid=getattr(obj.metadata, "uid", ""))

    def eventf(self, kind: str, namespace: str, name: str, etype: str,
               reason: str, message: str, uid: str = "") -> Event:
        ev = Event(kind=kind, namespace=namespace, name=name, type=etype,
                   reason=reason, message=message, uid=uid)
        with self._lock:
            cur = self._events.get(ev.dedupe_key)
            if cur is not None:
                cur.count += 1
                cur.last_timestamp = now_iso()
                ev = cur
            else:
                self._events[ev.dedupe_key] = ev
                while len(self._events) > self.capacity:
                    self._events.pop(next(iter(self._events)))
        if self.sink is not None:
            try:
                self.sink.emit(ev)
            except Exception:
                logger.debug("event sink emit failed", exc_info=True)
        return ev

    def events(self, kind: Optional[str] = None,
               namespace: Optional[str] = None,
               name: Optional[str] = None,
               reason: Optional[str] = None) -> list[Event]:
        """Snapshot, oldest first, optionally filtered."""
        with self._lock:
            out = list(self._events.values())
        if kind is not None:
            out = [e for e in out if e.kind == kind]
        if namespace is not None:
            out = [e for e in out if e.namespace == namespace]
        if name is not None:
            out = [e for e in out if e.name == name]
        if reason is not None:
            out = [e for e in out if e.reason == reason]
        return out

    def for_object(self, obj) -> list[Event]:
        return self.events(kind=obj.kind, namespace=obj.metadata.namespace,
                           name=obj.metadata.name)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


class KubeEventSink:
    """Mirrors recorded events to the API server.

    First occurrence POSTs the ``v1.Event``; repeats PUT the same
    (stable-named) object with the bumped count, the way client-go's
    correlator patches the existing Event instead of flooding etcd.
    """

    def __init__(self, client, namespace: str = "default",
                 component: str = "kaito-tpu-manager"):
        self.client = client
        self.namespace = namespace
        self.component = component

    def emit(self, ev: Event) -> None:
        from kaito_tpu.k8s.client import ApiError

        wire = ev.to_wire(self.namespace, self.component)
        ns = wire["metadata"]["namespace"]
        base = f"/api/v1/namespaces/{ns}/events"
        try:
            if ev.count > 1:
                self.client.request_json(
                    "PUT", f"{base}/{wire['metadata']['name']}", body=wire)
            else:
                self.client.request_json("POST", base, body=wire)
        except ApiError as e:
            # count drifted vs the server (restart, races): converge by
            # the opposite verb, then give up quietly
            try:
                if e.status == 404:
                    self.client.request_json("POST", base, body=wire)
                elif e.status == 409:
                    self.client.request_json(
                        "PUT", f"{base}/{wire['metadata']['name']}",
                        body=wire)
            except ApiError:
                logger.debug("event write failed: %s", ev.reason,
                             exc_info=True)


def record_event(store, obj, etype: str, reason: str, message: str) -> None:
    """Record an event via the store's recorder, if it has one — the
    tolerant helper every reconciler/provisioner path calls (custom
    Store implementations without a recorder stay valid)."""
    rec = getattr(store, "events", None)
    if rec is None:
        return
    try:
        rec.event(obj, etype, reason, message)
    except Exception:
        logger.debug("event recording failed", exc_info=True)
