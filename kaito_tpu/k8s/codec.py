"""Typed kinds ⇄ Kubernetes wire JSON.

The reference gets this from apimachinery struct tags; here a generic
dataclass codec maps snake_case attributes to camelCase wire keys, so
the SAME typed objects the in-process Store serves round-trip through a
real API server (group ``kaito-tpu.io/v1``, the shapes in
``config/crd/``).  Anything that is not one of our kinds travels as
:class:`Unstructured` with its payload passed through verbatim.
"""

from __future__ import annotations

import dataclasses
import re
import typing
from typing import Any, Optional

from kaito_tpu.api import (
    InferenceSet,
    ModelMirror,
    MultiRoleInference,
    RAGEngine,
    Workspace,
)
from kaito_tpu.api.meta import KaitoObject, ObjectMeta
from kaito_tpu.controllers.objects import _API_VERSIONS, Unstructured
from kaito_tpu.controllers.runtime import ControllerRevision

GROUP_VERSION = "kaito-tpu.io/v1"

TYPED_KINDS = {c.kind: c for c in (
    Workspace, InferenceSet, RAGEngine, MultiRoleInference, ModelMirror)}

# kind -> (api path prefix, plural, namespaced)
RESOURCES: dict[str, tuple[str, str, bool]] = {
    "Workspace": ("/apis/kaito-tpu.io/v1", "workspaces", True),
    "InferenceSet": ("/apis/kaito-tpu.io/v1", "inferencesets", True),
    "RAGEngine": ("/apis/kaito-tpu.io/v1", "ragengines", True),
    "MultiRoleInference": ("/apis/kaito-tpu.io/v1",
                           "multiroleinferences", True),
    "ModelMirror": ("/apis/kaito-tpu.io/v1", "modelmirrors", False),
    "ControllerRevision": ("/apis/apps/v1", "controllerrevisions", True),
    "Node": ("/api/v1", "nodes", False),
    "Service": ("/api/v1", "services", True),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "PersistentVolumeClaim": ("/api/v1", "persistentvolumeclaims", True),
    "StatefulSet": ("/apis/apps/v1", "statefulsets", True),
    "Deployment": ("/apis/apps/v1", "deployments", True),
    "Job": ("/apis/batch/v1", "jobs", True),
    "NodePool": ("/apis/karpenter.sh/v1", "nodepools", False),
    "NodeClaim": ("/apis/karpenter.sh/v1", "nodeclaims", False),
    "InferencePool": ("/apis/inference.networking.x-k8s.io/v1",
                      "inferencepools", True),
}

# our CRDs declare the status subresource: spec and status update
# through different endpoints
STATUS_SUBRESOURCE = set(TYPED_KINDS)


def camel(s: str) -> str:
    # "id" follows the Go/k8s acronym convention on the wire
    # (reference: ragengine_types.go json:"modelID")
    parts = s.split("_")
    return parts[0] + "".join(
        "ID" if p == "id" else p.title() for p in parts[1:])


def _enc(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        out = {}
        for f in dataclasses.fields(v):
            val = getattr(v, f.name)
            if val is None:
                continue
            out[camel(f.name)] = _enc(val)
        return out
    if isinstance(v, list):
        return [_enc(x) for x in v]
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    return v


_MISSING = object()


def _dec_value(tp: Any, w: Any) -> Any:
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if w is None:
            return None
        return _dec_value(args[0], w)
    if origin in (list,):
        (elem,) = typing.get_args(tp) or (Any,)
        return [_dec_value(elem, x) for x in (w or [])]
    if origin in (dict,):
        args = typing.get_args(tp)
        val_t = args[1] if len(args) == 2 else Any
        return {k: _dec_value(val_t, x) for k, x in (w or {}).items()}
    if dataclasses.is_dataclass(tp):
        return _dec_dataclass(tp, w or {})
    if tp in (int, float, str, bool) and w is not None:
        return tp(w)
    return w


def _legacy_camel(s: str) -> str:
    """Pre-acronym spelling (``modelId``): read-compat for CRs
    persisted by builds before camel() learned the ID convention."""
    parts = s.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _dec_dataclass(cls: type, d: dict) -> Any:
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        w = d.get(camel(f.name), _MISSING)
        if w is _MISSING:
            w = d.get(_legacy_camel(f.name), _MISSING)
        if w is _MISSING:
            continue
        kwargs[f.name] = _dec_value(hints[f.name], w)
    return cls(**kwargs)


def meta_to_wire(m: ObjectMeta) -> dict:
    d: dict = {"name": m.name}
    if m.namespace:
        d["namespace"] = m.namespace
    if m.labels:
        d["labels"] = dict(m.labels)
    if m.annotations:
        d["annotations"] = dict(m.annotations)
    if m.finalizers:
        d["finalizers"] = list(m.finalizers)
    if m.owner_references:
        d["ownerReferences"] = list(m.owner_references)
    if m.uid:
        d["uid"] = m.uid
    if m.generation:
        d["generation"] = m.generation
    if m.resource_version:
        d["resourceVersion"] = str(m.resource_version)
    if m.creation_timestamp:
        d["creationTimestamp"] = m.creation_timestamp
    if m.deletion_timestamp:
        d["deletionTimestamp"] = m.deletion_timestamp
    return d


def meta_from_wire(d: dict) -> ObjectMeta:
    rv_raw = str(d.get("resourceVersion", "") or "0")
    rv = int(rv_raw) if rv_raw.isdigit() else abs(hash(rv_raw)) % 10**9
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
        finalizers=list(d.get("finalizers") or []),
        owner_references=list(d.get("ownerReferences") or []),
        uid=d.get("uid", ""),
        generation=int(d.get("generation", 1) or 1),
        resource_version=rv,
        creation_timestamp=d.get("creationTimestamp", ""),
        deletion_timestamp=d.get("deletionTimestamp"),
    )


def to_wire(obj: KaitoObject) -> dict:
    if isinstance(obj, ControllerRevision):
        return {"apiVersion": "apps/v1", "kind": obj.kind,
                "metadata": meta_to_wire(obj.metadata),
                "data": obj.data, "revision": obj.revision}
    if isinstance(obj, Unstructured):
        d = {"apiVersion": _API_VERSIONS.get(obj.kind, "v1"),
             "kind": obj.kind, "metadata": meta_to_wire(obj.metadata)}
        if obj.spec:
            d["spec"] = obj.spec
        if obj.status:
            d["status"] = obj.status
        return d
    d = {"apiVersion": GROUP_VERSION, "kind": obj.kind,
         "metadata": meta_to_wire(obj.metadata)}
    for name, v in vars(obj).items():
        if name in ("metadata", "kind") or v is None:
            continue
        d["status" if name == "status" else camel(name)] = _enc(v)
    return d


def from_wire(d: dict) -> KaitoObject:
    from kaito_tpu.api.conversion import convert_to_hub, is_legacy

    if is_legacy(d):
        # hub-and-spoke conversion (reference: ragengine_conversion.go)
        d = convert_to_hub(d)
    kind = d["kind"]
    meta = meta_from_wire(d.get("metadata", {}))
    if kind == "ControllerRevision":
        return ControllerRevision(meta, data=dict(d.get("data") or {}),
                                  revision=int(d.get("revision", 0) or 0))
    cls = TYPED_KINDS.get(kind)
    if cls is None:
        return Unstructured(kind, meta, spec=dict(d.get("spec") or {}),
                            status=dict(d.get("status") or {}))
    hints = typing.get_type_hints(cls.__init__)
    kwargs = {}
    for pname, ptype in hints.items():
        if pname in ("meta", "return"):
            continue
        w = d.get(camel(pname))
        if w is None:
            continue
        kwargs[pname] = _dec_value(ptype, w)
    obj = cls(meta, **kwargs)
    status_w = d.get("status")
    if status_w and dataclasses.is_dataclass(getattr(obj, "status", None)):
        obj.status = _dec_dataclass(type(obj.status), status_w)
    return obj


def resource_path(kind: str, namespace: Optional[str] = None,
                  name: Optional[str] = None,
                  subresource: str = "") -> str:
    """REST path for a kind (list/collection path when name is None)."""
    try:
        prefix, plural, namespaced = RESOURCES[kind]
    except KeyError:
        raise KeyError(f"kind {kind!r} has no registered REST mapping")
    if namespaced and namespace:
        path = f"{prefix}/namespaces/{namespace}/{plural}"
    else:
        path = f"{prefix}/{plural}"
    if name:
        path += f"/{name}"
    if subresource:
        path += f"/{subresource}"
    return path
