"""Minimal Kubernetes REST client (stdlib-only).

The counterpart of the reference's controller-runtime client
(`/root/reference/pkg/k8sclient/`, `cmd/workspace/main.go:206`): CRUD +
watch over HTTPS with bearer-token auth.  In-cluster configuration
follows the standard service-account contract
(KUBERNETES_SERVICE_HOST/_PORT + /var/run/secrets/kubernetes.io);
explicit base_url/token win for tests and out-of-cluster use.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterator, Optional

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class KubeClient:
    def __init__(self, base_url: str = "", token: str = "",
                 ca_path: str = "", insecure: bool = False):
        if not base_url:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "no base_url and not running in-cluster "
                    "(KUBERNETES_SERVICE_HOST unset)")
            base_url = f"https://{host}:{port}"
            token_file = os.path.join(SA_DIR, "token")
            if not token and os.path.exists(token_file):
                token = open(token_file).read().strip()
            if not ca_path and os.path.exists(os.path.join(SA_DIR, "ca.crt")):
                ca_path = os.path.join(SA_DIR, "ca.crt")
        self.base_url = base_url.rstrip("/")
        self.token = token
        if self.base_url.startswith("https"):
            self._ctx = ssl.create_default_context(
                cafile=ca_path or None)
            if insecure:
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE
        else:
            self._ctx = None

    # -- low-level -----------------------------------------------------

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None,
                 query: Optional[dict] = None,
                 timeout: float = 30.0):
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            return urllib.request.urlopen(req, timeout=timeout,
                                          context=self._ctx)
        except urllib.error.HTTPError as e:
            msg = e.read().decode(errors="replace")
            raise ApiError(e.code, msg) from None

    def request_json(self, method: str, path: str,
                     body: Optional[dict] = None,
                     query: Optional[dict] = None) -> dict:
        with self._request(method, path, body, query) as resp:
            return json.loads(resp.read())

    # -- watch ---------------------------------------------------------

    def watch(self, path: str, handler: Callable[[str, dict], None],
              stop: threading.Event,
              resource_version: str = "") -> None:
        """Stream watch events (JSON lines) until ``stop`` is set; the
        caller owns reconnect cadence via repeated calls."""
        query = {"watch": "true"}
        if resource_version:
            query["resourceVersion"] = resource_version
        try:
            with self._request("GET", path, query=query,
                               timeout=330.0) as resp:
                for line in resp:
                    if stop.is_set():
                        return
                    line = line.strip()
                    if not line:
                        continue
                    evt = json.loads(line)
                    handler(evt.get("type", ""), evt.get("object", {}))
        except (ApiError, OSError, json.JSONDecodeError) as e:
            if not stop.is_set():
                logger.warning("watch %s ended: %s", path, e)
