"""Production config fetching for preset auto-generation.

The reference queries the HF Hub API at reconcile time to generate
presets for unregistered models and ships a precomputed catalog
(`presets/workspace/generator/generator.go:805-830` GeneratePreset +
`presets/workspace/models/model_catalog.yaml`).  TPU-native shape:

- ``catalog_config(hf_id)`` — the committed catalog cache
  (``model_catalog.json``: recorded public ``config.json`` dicts), so
  popular models resolve with zero egress and air-gapped clusters
  still plan correctly.
- ``fetch_hf_config(hf_id)`` — stdlib HTTPS fetch of
  ``https://huggingface.co/<id>/resolve/main/config.json`` with
  ``HF_TOKEN``/``HUGGING_FACE_HUB_TOKEN`` auth and bounded retries.
- ``default_config_fetcher`` — catalog first, hub second; installed by
  the controller manager via :func:`install_default_fetcher` so
  ``get_model_by_name`` can materialize any ``org/model`` Workspace at
  reconcile time (reference: ``vllm_model.go:116-160``).
"""

from __future__ import annotations

import json
import logging
import os
import time
import urllib.error
import urllib.request
from typing import Mapping, Optional

logger = logging.getLogger(__name__)

_CATALOG_PATH = os.path.join(os.path.dirname(__file__), "model_catalog.json")
_catalog: Optional[dict] = None

HUB_URL = "https://huggingface.co/{hf_id}/resolve/main/config.json"

# negative cache: a reconcile loop must not re-block on an unresolvable
# model id every requeue (typo'd Workspaces requeue forever)
_NEG_TTL_S = 300.0
_neg_cache: dict[str, float] = {}


def _load_catalog() -> dict:
    """Catalog indexed by lowercased HF id (built once)."""
    global _catalog
    if _catalog is None:
        try:
            with open(_CATALOG_PATH) as f:
                raw = json.load(f)
            _catalog = {k.lower(): v for k, v in raw.items()
                        if isinstance(v, dict)}
        except Exception:
            logger.exception("model catalog unreadable at %s", _CATALOG_PATH)
            _catalog = {}
    return _catalog


def catalog_config(hf_id: str) -> Optional[Mapping]:
    """Recorded config for a catalogued model (case-insensitive id)."""
    entry = _load_catalog().get(hf_id.lower())
    return entry.get("config") if entry else None


def fetch_hf_config(hf_id: str, timeout: float = 15.0,
                    retries: int = 3) -> Optional[Mapping]:
    """GET the model's config.json from the HF Hub (None on failure).
    Honors ``HF_HUB_OFFLINE`` — air-gapped clusters fail fast instead
    of burning retry timeouts in the reconcile loop."""
    if os.environ.get("HF_HUB_OFFLINE", "") not in ("", "0"):
        logger.info("HF_HUB_OFFLINE set; not fetching %s", hf_id)
        return None
    url = HUB_URL.format(hf_id=hf_id)
    headers = {"User-Agent": "kaito-tpu/preset-generator"}
    token = os.environ.get("HF_TOKEN") \
        or os.environ.get("HUGGING_FACE_HUB_TOKEN")
    if token:
        headers["Authorization"] = f"Bearer {token}"
    for attempt in range(retries):
        try:
            req = urllib.request.Request(url, headers=headers)
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code in (401, 403, 404):
                logger.warning("hub config for %s: HTTP %d", hf_id, e.code)
                return None
            logger.warning("hub config for %s: HTTP %d (attempt %d)",
                           hf_id, e.code, attempt + 1)
        except Exception as e:
            logger.warning("hub config for %s: %s (attempt %d)",
                           hf_id, e, attempt + 1)
        if attempt + 1 < retries:
            time.sleep(min(2.0 ** attempt, 8.0))
    return None


def default_config_fetcher(hf_id: str) -> Optional[Mapping]:
    """Catalog cache first (zero egress), HF Hub second; failures are
    negative-cached (_NEG_TTL_S) so requeue storms fail fast."""
    cfg = catalog_config(hf_id)
    if cfg is not None:
        logger.info("preset config for %s served from the catalog cache",
                    hf_id)
        return cfg
    last_fail = _neg_cache.get(hf_id.lower())
    if last_fail is not None and time.monotonic() - last_fail < _NEG_TTL_S:
        return None
    cfg = fetch_hf_config(hf_id)
    if cfg is None:
        _neg_cache[hf_id.lower()] = time.monotonic()
    return cfg


def install_default_fetcher() -> None:
    """Wire :func:`default_config_fetcher` into the registry so
    unregistered ``org/model`` Workspaces auto-generate presets at
    reconcile time."""
    from kaito_tpu.models.registry import set_config_fetcher

    set_config_fetcher(default_config_fetcher)
    logger.info("preset auto-generation fetcher installed "
                "(catalog + HF hub)")
