"""Model preset registry.

The analogue of the reference's plugin registry + lookup
(``pkg/utils/plugin/plugin.go:37-133`` and ``GetModelByName``,
``presets/workspace/models/vllm_model.go:116``): presets register by
name; unknown names fall back to on-the-fly auto-generation from a HF
config fetched by an injectable hook (the reference hits the HF Hub API
directly at reconcile time).
"""

from __future__ import annotations

import threading
from typing import Callable, Mapping, Optional

from kaito_tpu.models.metadata import ModelMetadata

_lock = threading.Lock()
_registry: dict[str, ModelMetadata] = {}

# Optional hook: hf_id -> config.json dict (or None).  Installed by the
# controller when it has hub access; tests install fakes.
ConfigFetcher = Callable[[str], Optional[Mapping]]
_config_fetcher: Optional[ConfigFetcher] = None


def register_model(md: ModelMetadata, replace: bool = False) -> None:
    with _lock:
        if md.name in _registry and not replace:
            raise ValueError(f"model preset {md.name!r} already registered")
        _registry[md.name] = md


def is_valid_preset(name: str) -> bool:
    return name in _registry


def list_presets() -> list[str]:
    with _lock:
        return sorted(_registry)


def set_config_fetcher(fetcher: Optional[ConfigFetcher]) -> None:
    global _config_fetcher
    _config_fetcher = fetcher


def get_model_by_name(name: str) -> ModelMetadata:
    """Look up a preset; auto-generate for unregistered HF ids when a
    config fetcher is installed (reference behavior:
    ``vllm_model.go:116-160`` falls through to ``GeneratePreset``)."""
    with _lock:
        md = _registry.get(name)
        if md is None and "/" in name:
            # a Workspace may name the full HF id instead of the preset
            # short name; registered presets win over auto-generation
            # (their metadata carries curated file sizes/tags)
            low = name.lower()
            md = next((m for m in _registry.values()
                       if m.hf_id.lower() == low), None)
    if md is not None:
        return md
    if _config_fetcher is not None and "/" in name:
        cfg = _config_fetcher(name)
        if cfg is not None:
            from kaito_tpu.models.autogen import metadata_from_hf_config

            # register under the FULL id: a fork's basename must never
            # clobber a curated preset sharing the short name (manifests
            # and the engine both resolve the same full id)
            md = metadata_from_hf_config(name, cfg, name=name)
            register_model(md, replace=True)
            return md
    raise KeyError(
        f"unknown model {name!r}; not a built-in preset and no config "
        f"fetcher produced a HuggingFace config for it"
    )


def draft_compatibility_errors(target: ModelMetadata,
                               draft: ModelMetadata) -> list[str]:
    """Why ``draft`` cannot speculate for ``target`` (empty = ok).

    Speculative decoding emits the DRAFT's token ids verbatim once the
    target accepts them, so both presets must share one tokenizer.  The
    catalog carries no tokenizer files, so vocab-size equality is the
    enforced proxy (it is also exactly what ``load_tokenizer`` keys
    on); the engine re-checks at load time.
    """
    errs: list[str] = []
    if draft.runtime != "engine":
        errs.append(f"draft preset {draft.name!r} runs on the "
                    f"{draft.runtime!r} runtime; speculation needs the "
                    f"first-party engine")
    if draft.arch.vocab_size != target.arch.vocab_size:
        errs.append(
            f"draft preset {draft.name!r} vocab_size "
            f"{draft.arch.vocab_size} != target {target.name!r} "
            f"vocab_size {target.arch.vocab_size} (speculation requires "
            f"a shared tokenizer)")
    return errs


def resolve_speculative_draft(target: ModelMetadata,
                              annotation: str) -> str:
    """Resolve the ``kaito-tpu.io/speculative-draft`` annotation (or
    the ``--speculative-draft`` flag value) to a validated draft preset
    name.  ``""`` disables; ``"auto"`` takes the target preset's
    curated ``speculative_draft`` pairing (may be empty — serving then
    stays non-speculative).  Raises ``ValueError`` on an unknown preset
    or an incompatible pairing (surfaced as a controller condition).
    """
    name = (annotation or "").strip()
    if name == "auto":
        name = target.speculative_draft
    if not name:
        return ""
    try:
        draft = get_model_by_name(name)
    except KeyError:
        raise ValueError(
            f"speculative draft preset {name!r} is not in the model "
            f"catalog") from None
    errs = draft_compatibility_errors(target, draft)
    if errs:
        raise ValueError("; ".join(errs))
    return draft.name
