"""Preset generator CLI.

The counterpart of the reference's ``cmd/preset-generator/main.go``
(1-88): generate a preset for any HF model id and print the derived
metadata the operator plans with — bytes/token, estimated file size,
and the parallelism plan per TPU generation.

Usage::

    python -m kaito_tpu.models.preset_generator --model org/name
    python -m kaito_tpu.models.preset_generator --model org/name \
        --config-file recorded_config.json --chip v5e --json

Resolution order: --config-file > committed catalog > HF hub (needs
egress and, for gated models, HF_TOKEN).
"""

from __future__ import annotations

import argparse
import json
import sys

from kaito_tpu.models.autogen import metadata_from_hf_config
from kaito_tpu.models.hub import catalog_config, fetch_hf_config


def generate(hf_id: str, cfg: dict):
    md = metadata_from_hf_config(hf_id, cfg)
    a = md.arch
    out = {
        "name": md.name,
        "hf_id": md.hf_id,
        "architecture": (cfg.get("architectures") or [""])[0],
        "num_layers": a.num_layers,
        "hidden_size": a.hidden_size,
        "num_heads": a.num_heads,
        "num_kv_heads": a.num_kv_heads,
        "vocab_size": a.vocab_size,
        "max_model_len": md.max_model_len,
        "num_experts": a.num_experts,
        "param_count": a.param_count(),
        "kv_bytes_per_token_bf16": md.kv_bytes_per_token(2),
        "kv_bytes_per_token_int8": md.kv_bytes_per_token(1),
        "model_file_bytes": md.file_bytes,
        "speculative_draft": md.speculative_draft,
    }
    return md, out


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kaito-tpu-preset-generator")
    ap.add_argument("--model", required=True, help="HF id (org/name)")
    ap.add_argument("--config-file", default="",
                    help="local recorded config.json (skips catalog/hub)")
    ap.add_argument("--chip", default="v5e",
                    help="TPU generation for the plan preview")
    ap.add_argument("--kv-cache-dtype", default="bfloat16",
                    choices=["bfloat16", "int8"],
                    help="KV pool dtype assumed by the plan preview "
                         "(int8 halves KV bytes/token)")
    ap.add_argument("--quantization", default="",
                    choices=["", "int8", "int4"],
                    help="weight-only quantization assumed by the plan "
                         "preview (int8 halves, int4 ~quarters weight "
                         "bytes -> fewer chips; docs/quantization.md)")
    ap.add_argument("--cp-autocarve", action="store_true",
                    help="opt the plan preview into the >=32k serve CP "
                         "carve (evidence-gated off by default: BENCH_r05 "
                         "cp_speedup_vs_chunked=0.68)")
    ap.add_argument("--speculative-draft", default="",
                    help="draft preset for speculative decoding: a "
                         "catalog name, or 'auto' for the curated "
                         "pairing; validated against the target "
                         "(tokenizer/runtime compatibility)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.config_file:
        with open(args.config_file) as f:
            cfg = json.load(f)
    else:
        cfg = catalog_config(args.model) or fetch_hf_config(args.model)
    if cfg is None:
        print(f"error: no config for {args.model} (not in the catalog; "
              f"hub fetch failed or offline)", file=sys.stderr)
        return 1

    md, out = generate(args.model, cfg)

    # prefer the committed catalog entry when one matches: it carries
    # the curated speculative_draft pairing the autogen path can't know
    from kaito_tpu.models.registry import (get_model_by_name,
                                           resolve_speculative_draft)
    try:
        md = get_model_by_name(args.model)
        out["speculative_draft"] = md.speculative_draft
    except KeyError:
        pass
    if args.speculative_draft:
        try:
            out["speculative_draft"] = resolve_speculative_draft(
                md, args.speculative_draft)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1

    try:
        from kaito_tpu.estimator.estimator import weight_bytes
        from kaito_tpu.parallel.plan import plan_parallelism
        from kaito_tpu.sku.catalog import CHIP_CATALOG

        # weight-byte ladder the operator plans against (the int4 row
        # is why a 70B fits half the chips; docs/quantization.md)
        out["weight_bytes_bf16"] = weight_bytes(md, "bf16")
        out["weight_bytes_int8"] = weight_bytes(md, "int8")
        out["weight_bytes_int4"] = weight_bytes(md, "int4")
        chip = CHIP_CATALOG[args.chip]
        plan = plan_parallelism(
            md, chip,
            kv_dtype_bytes=1 if args.kv_cache_dtype == "int8" else 2,
            quantization=args.quantization or None,
            cp_autocarve=args.cp_autocarve)
        out["plan"] = {"chip": args.chip, "topology": plan.topology,
                       "num_slices": plan.num_slices,
                       "mesh": str(plan.mesh),
                       "notes": list(plan.notes)}
    except Exception as e:
        out["plan_error"] = str(e)

    if args.json:
        print(json.dumps(out, indent=2))
    else:
        for k, v in out.items():
            print(f"{k:28s} {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
