"""Model metadata: the structural facts the planner, estimator and the
serving engine need about a model.

This is the TPU-native analogue of the reference's model registry
(``pkg/model/interface.go:33-45`` ``Model``/``PresetParam`` and the
catalog entries in ``presets/workspace/models/model_catalog.yaml``):
a preset carries enough architecture detail to (a) estimate HBM
(weights + KV-cache bytes/token), (b) plan a device mesh, and (c)
actually instantiate the model in the JAX engine — the reference only
needed (a)+(b) because vLLM owned (c).
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass, field, replace
from typing import Optional


class AttentionKind(str, enum.Enum):
    """Attention family — drives the KV bytes/token formula (reference:
    ``presets/workspace/generator/generator.go:620`` calculateKVCacheTokenSize)."""

    MHA = "MHA"
    GQA = "GQA"
    MQA = "MQA"
    MLA = "MLA"  # DeepSeek-style latent attention: cache is kv_lora_rank+rope


@dataclass(frozen=True)
class ModelArch:
    """Engine-facing architecture description.

    One config-driven transformer implementation covers the llama /
    mistral / qwen2 / phi-3 / gemma / MoE families; the flags below are
    the union of what those need.
    """

    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    max_position_embeddings: int = 8192

    # nonlinearity / norms
    hidden_act: str = "silu"          # silu (swiglu) | gelu | gelu_tanh (geglu)
    gated_mlp: bool = True            # False: classic 2-matrix MLP (falcon, phi-2)
    rms_norm_eps: float = 1e-5
    norm_type: str = "rmsnorm"        # rmsnorm | layernorm
    norm_offset: bool = False         # gemma: weight = 1 + w
    pre_post_norm: bool = False       # gemma-2/3: extra post-attn/post-mlp norms
    parallel_residual: bool = False   # falcon/phi-2: x + attn(n(x)) + mlp(n(x))
    linear_bias: bool = False         # phi-2: biases on all projections

    # rotary embedding
    rope_theta: float = 10000.0
    partial_rotary_factor: float = 1.0
    rope_scaling: Optional[dict] = None   # {"rope_type": "llama3"|"linear"|"yarn", ...}

    # attention details
    qk_norm: bool = False             # gemma-3 / qwen-3: RMSNorm on q and k heads
    qkv_bias: bool = False            # qwen2
    attn_logit_softcap: Optional[float] = None   # gemma-2
    final_logit_softcap: Optional[float] = None  # gemma-2
    sliding_window: Optional[int] = None
    sliding_window_pattern: Optional[int] = None  # gemma-3: 1 global per N layers
    query_pre_attn_scalar: Optional[float] = None  # gemma override for 1/sqrt(d)

    # embeddings / head
    tie_word_embeddings: bool = False
    embedding_multiplier: Optional[float] = None  # gemma scales by sqrt(hidden)

    # MoE (mixtral/deepseek/gpt-oss style); dense model if num_experts == 0
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: Optional[int] = None
    num_shared_experts: int = 0
    moe_layer_start: int = 0          # deepseek: first k layers dense

    # MLA (deepseek v2/v3)
    kv_lora_rank: Optional[int] = None
    q_lora_rank: Optional[int] = None
    qk_rope_head_dim: Optional[int] = None
    qk_nope_head_dim: Optional[int] = None
    v_head_dim: Optional[int] = None

    @property
    def kv_cache_heads(self) -> int:
        """Head count of the KV cache: MLA caches ONE shared latent."""
        return 1 if self.attention_kind == AttentionKind.MLA else self.num_kv_heads

    @property
    def kv_cache_dim(self) -> int:
        """Per-head cache dim: MLA caches [kv_lora_rank + rope] latents."""
        if self.attention_kind == AttentionKind.MLA:
            return (self.kv_lora_rank or 0) + (self.qk_rope_head_dim or 0)
        return self.head_dim

    @property
    def attention_kind(self) -> AttentionKind:
        if self.kv_lora_rank:
            return AttentionKind.MLA
        if self.num_kv_heads == 1:
            return AttentionKind.MQA
        if self.num_kv_heads < self.num_heads:
            return AttentionKind.GQA
        return AttentionKind.MHA

    def param_count(self) -> int:
        """Estimate total parameter count from the architecture."""
        h = self.hidden_size
        embed = self.vocab_size * h * (1 if self.tie_word_embeddings else 2)
        if self.attention_kind == AttentionKind.MLA:
            # q: h->q_lora->heads*(nope+rope); kv: h->kv_lora(+rope); o
            qk = (self.qk_nope_head_dim or 0) + (self.qk_rope_head_dim or 0)
            q_in = self.q_lora_rank or h
            attn = (
                (h * q_in if self.q_lora_rank else 0)
                + q_in * self.num_heads * qk
                + h * ((self.kv_lora_rank or 0) + (self.qk_rope_head_dim or 0))
                + (self.kv_lora_rank or 0) * self.num_heads * ((self.qk_nope_head_dim or 0) + (self.v_head_dim or 0))
                + self.num_heads * (self.v_head_dim or 0) * h
            )
        else:
            attn = h * self.num_heads * self.head_dim + 2 * h * self.num_kv_heads * self.head_dim + self.num_heads * self.head_dim * h
        if self.num_experts > 0:
            inter = self.moe_intermediate_size or self.intermediate_size
            experts = self.num_experts + self.num_shared_experts
            mlp_moe = 3 * h * inter * experts + h * self.num_experts
            dense_layers = self.moe_layer_start
            moe_layers = self.num_layers - dense_layers
            mlp_total = moe_layers * mlp_moe + dense_layers * 3 * h * self.intermediate_size
        else:
            mlp_total = self.num_layers * 3 * h * self.intermediate_size
        norms = self.num_layers * 2 * h + h
        return embed + self.num_layers * attn + mlp_total + norms

    def kv_bytes_per_token(self, dtype_bytes: int = 2) -> int:
        """KV-cache bytes per token across all layers.

        GQA formula matches the reference
        (``pkg/model/interface.go:217``): ``2*layers*kv_heads*head_dim*dtype``.
        MLA caches the compressed latent + rope key instead.
        """
        if self.attention_kind == AttentionKind.MLA:
            per_layer = (self.kv_lora_rank or 0) + (self.qk_rope_head_dim or 0)
            return self.num_layers * per_layer * dtype_bytes
        return 2 * self.num_layers * self.num_kv_heads * self.head_dim * dtype_bytes


@dataclass(frozen=True)
class ModelMetadata:
    """A registered model preset (reference: one entry of
    ``model_catalog.yaml`` + ``PresetParam``)."""

    name: str                      # preset name, e.g. "llama-3.1-8b-instruct"
    hf_id: str                     # huggingface repo id
    arch: ModelArch
    weights_dtype_bytes: int = 2   # bf16 on TPU
    model_file_bytes: int = 0      # on-disk safetensors size; 0 = derive
    token_limit: int = 0           # max context; 0 = arch.max_position_embeddings
    download_auth_required: bool = False
    quantization: str = ""         # "", "int8", "mxfp4", ...
    tool_call_parser: str = ""
    reasoning_parser: str = ""
    chat_template: str = ""        # chat template preset name
    tags: tuple[str, ...] = ()
    # "engine" = the first-party JAX engine; "transformers" = the HF
    # fallback runtime for long-tail architectures (reference:
    # RuntimeName in pkg/model/interface.go + the text-generation
    # transformers runtime)
    runtime: str = "engine"
    # default draft preset for two-model speculative decoding; "" = no
    # curated pairing.  Resolved by the `kaito-tpu.io/speculative-draft:
    # auto` annotation; serving stays non-speculative unless asked
    speculative_draft: str = ""

    @property
    def file_bytes(self) -> int:
        if self.model_file_bytes:
            return self.model_file_bytes
        return self.arch.param_count() * self.weights_dtype_bytes

    @property
    def max_model_len(self) -> int:
        return self.token_limit or self.arch.max_position_embeddings

    def kv_bytes_per_token(self, dtype_bytes: int = 2) -> int:
        return self.arch.kv_bytes_per_token(dtype_bytes)

    def disk_storage_bytes(self) -> int:
        """Provisioned disk for weights: expand for download+load headroom,
        matching the reference's sizing rule (generator.go: size*2.5 + margin,
        rounded up to 10Gi steps)."""
        GiB = 2**30
        raw = int(self.file_bytes * 2.5) + 48 * GiB
        step = 10 * GiB
        return int(math.ceil(raw / step) * step)

    def with_overrides(self, **kw) -> "ModelMetadata":
        return replace(self, **kw)
