"""Build :class:`ModelMetadata` from a HuggingFace ``config.json`` dict.

The TPU-native analogue of the reference's preset auto-generator
(``presets/workspace/generator/generator.go:805`` GeneratePreset): the
reference queries the HF Hub at reconcile time for safetensors sizes and
``config.json`` and derives ``bytesPerToken``/``modelFileSize``; we do
the same derivation from a config dict.  Network fetch is injected by
the caller (the controller can mount a config or use a hub client), so
this module stays pure and unit-testable.
"""

from __future__ import annotations

from typing import Mapping, Optional

from kaito_tpu.models.metadata import ModelArch, ModelMetadata

# Architectures we can instantiate in the engine.  The analogue of the
# reference's vLLM arch allowlist (presets/workspace/models/
# vllm_model_arch_list.txt) — ours is what the config-driven JAX
# transformer supports.
SUPPORTED_ARCHITECTURES = {
    "LlamaForCausalLM",
    "MistralForCausalLM",
    "Qwen2ForCausalLM",
    "Qwen3ForCausalLM",
    "Phi3ForCausalLM",
    "PhiForCausalLM",
    "Gemma2ForCausalLM",
    "Gemma3ForCausalLM",
    "Gemma3ForConditionalGeneration",
    "MixtralForCausalLM",
    "DeepseekV2ForCausalLM",
    "DeepseekV3ForCausalLM",
    "FalconForCausalLM",
    "GptOssForCausalLM",
}


def _first(cfg: Mapping, *keys, default=None):
    for k in keys:
        if k in cfg and cfg[k] is not None:
            return cfg[k]
    return default


def arch_from_hf_config(cfg: Mapping) -> ModelArch:
    """Map a HF ``config.json`` dict onto :class:`ModelArch`."""
    # gemma-3 multimodal nests the LM under text_config
    if "text_config" in cfg and "num_hidden_layers" not in cfg:
        inner = dict(cfg["text_config"])
        inner.setdefault("architectures", cfg.get("architectures"))
        inner.setdefault("model_type", cfg.get("model_type"))
        cfg = inner

    archs = cfg.get("architectures") or []
    arch_name = archs[0] if archs else cfg.get("model_type", "")
    model_type = cfg.get("model_type", "").lower()

    hidden = int(_first(cfg, "hidden_size", "n_embd", default=0))
    layers = int(_first(cfg, "num_hidden_layers", "n_layer", default=0))
    heads = int(_first(cfg, "num_attention_heads", "n_head", default=0))
    kv_heads = int(_first(cfg, "num_key_value_heads", "num_kv_heads", default=heads) or heads)
    head_dim = int(_first(cfg, "head_dim", default=0) or (hidden // max(heads, 1)))
    inter = int(_first(cfg, "intermediate_size", "ffn_hidden_size", default=4 * hidden))
    vocab = int(_first(cfg, "vocab_size", default=32000))
    max_pos = int(_first(cfg, "max_position_embeddings", "n_positions", default=8192))

    act = str(_first(cfg, "hidden_act", "hidden_activation", "activation_function", default="silu"))
    if act in ("gelu_new", "gelu_fast", "gelu_pytorch_tanh"):
        act = "gelu_tanh"

    kw = dict(
        vocab_size=vocab,
        hidden_size=hidden,
        num_layers=layers,
        num_heads=heads,
        num_kv_heads=kv_heads,
        head_dim=head_dim,
        intermediate_size=inter,
        max_position_embeddings=max_pos,
        hidden_act=act,
        rms_norm_eps=float(_first(cfg, "rms_norm_eps", "layer_norm_epsilon", default=1e-5)),
        rope_theta=float(_first(cfg, "rope_theta", default=10000.0)),
        partial_rotary_factor=float(_first(cfg, "partial_rotary_factor", default=1.0)),
        rope_scaling=cfg.get("rope_scaling"),
        tie_word_embeddings=bool(cfg.get("tie_word_embeddings", False)),
        sliding_window=cfg.get("sliding_window"),
        qkv_bias=bool(_first(cfg, "attention_bias", "qkv_bias", default=False)),
    )

    if model_type in ("gemma", "gemma2", "gemma3", "gemma3_text"):
        kw.update(
            norm_offset=True,
            embedding_multiplier=hidden ** 0.5,
            query_pre_attn_scalar=float(_first(cfg, "query_pre_attn_scalar", default=head_dim)),
        )
        if model_type in ("gemma2", "gemma3", "gemma3_text"):
            kw["pre_post_norm"] = True
        if model_type == "gemma2":
            kw["attn_logit_softcap"] = _first(cfg, "attn_logit_softcapping", default=50.0)
            kw["final_logit_softcap"] = _first(cfg, "final_logit_softcapping", default=30.0)
        if model_type in ("gemma3", "gemma3_text"):
            kw["sliding_window_pattern"] = int(_first(cfg, "sliding_window_pattern", default=6))
            kw["qk_norm"] = True

    if model_type == "qwen2":
        kw["qkv_bias"] = True
    if model_type == "qwen3":
        kw["qk_norm"] = True

    if model_type in ("mixtral",):
        kw.update(
            num_experts=int(_first(cfg, "num_local_experts", default=8)),
            num_experts_per_tok=int(_first(cfg, "num_experts_per_tok", default=2)),
        )

    if model_type in ("gpt_oss",):
        kw.update(
            num_experts=int(_first(cfg, "num_local_experts", "num_experts", default=32)),
            num_experts_per_tok=int(_first(cfg, "num_experts_per_tok", "experts_per_token", default=4)),
            moe_intermediate_size=int(_first(cfg, "intermediate_size", default=2880)),
            # gpt-oss alternates sliding/full attention layer types
            sliding_window_pattern=2,
        )

    if model_type in ("deepseek_v2", "deepseek_v3"):
        kw.update(
            num_experts=int(_first(cfg, "n_routed_experts", default=0)),
            num_experts_per_tok=int(_first(cfg, "num_experts_per_tok", default=0)),
            moe_intermediate_size=_first(cfg, "moe_intermediate_size"),
            num_shared_experts=int(_first(cfg, "n_shared_experts", default=0)),
            moe_layer_start=int(_first(cfg, "first_k_dense_replace", default=0)),
            kv_lora_rank=_first(cfg, "kv_lora_rank"),
            q_lora_rank=_first(cfg, "q_lora_rank"),
            qk_rope_head_dim=_first(cfg, "qk_rope_head_dim"),
            qk_nope_head_dim=_first(cfg, "qk_nope_head_dim"),
            v_head_dim=_first(cfg, "v_head_dim"),
        )

    if model_type == "falcon":
        if bool(cfg.get("multi_query", False)) and "num_key_value_heads" not in cfg:
            kw["num_kv_heads"] = 1
        kw.update(gated_mlp=False, parallel_residual=bool(cfg.get("parallel_attn", True)),
                  norm_type="layernorm")

    if model_type == "phi":
        kw.update(gated_mlp=False, parallel_residual=True, norm_type="layernorm",
                  linear_bias=True)

    return ModelArch(**kw)


# Parser-mode derivation for generated presets (the reference's
# reasoning/tool maps, generator.go:45-160, restricted to families this
# engine serves).  The engine's chat route gates think-tag reasoning
# splitting on reasoning_parser; tool extraction is format-sniffing
# (hermes/mistral), with the parser NAME carried for contract parity.
_REASONING_BY_PREFIX = {
    "deepseek-r1": "deepseek_r1",
    "qwq-32b": "deepseek_r1",
    "deepseek-v3": "deepseek_v3",
    "qwen3": "qwen3",
}
_REASONING_BY_ARCH = {
    "DeepseekV3ForCausalLM": "deepseek_v3",
    "Qwen3ForCausalLM": "qwen3",
    "GptOssForCausalLM": "openai_gptoss",
}
_TOOLS_BY_PREFIX = {
    "deepseek-r1": "deepseek_v3",
    "deepseek-v3": "deepseek_v3",
    "mistral": "mistral",
    "ministral": "mistral",
    "qwen2.5": "hermes",
    "qwen3": "hermes",
    "phi-4-mini": "phi4_mini_json",
    "llama-3": "llama3_json",
    "meta-llama-3": "llama3_json",
}
_TOOLS_BY_ARCH = {
    "MistralForCausalLM": "mistral",
    "MixtralForCausalLM": "mistral",
    "LlamaForCausalLM": "llama3_json",
    "Qwen2ForCausalLM": "hermes",
    "Qwen3ForCausalLM": "hermes",
}


def derive_parsers(name: str, archs) -> tuple[str, str]:
    """(tool_call_parser, reasoning_parser) for a model, by name prefix
    first (most specific), architecture fallback."""
    low = name.lower()
    tool = next((v for k, v in _TOOLS_BY_PREFIX.items()
                 if low.startswith(k)), "")
    reasoning = next((v for k, v in _REASONING_BY_PREFIX.items()
                      if low.startswith(k)), "")
    for a in archs or ():
        tool = tool or _TOOLS_BY_ARCH.get(a, "")
        reasoning = reasoning or _REASONING_BY_ARCH.get(a, "")
    return tool, reasoning


def metadata_from_hf_config(
    hf_id: str,
    cfg: Mapping,
    *,
    name: Optional[str] = None,
    model_file_bytes: int = 0,
    download_auth_required: bool = False,
    quantization: str = "",
    tags: tuple[str, ...] = (),
    speculative_draft: str = "",
) -> ModelMetadata:
    """Auto-generate a preset from a HF config dict (reference:
    ``GeneratePreset``, ``presets/workspace/generator/generator.go:805``)."""
    archs = cfg.get("architectures") or []
    runtime = "engine"
    if archs and not (set(archs) & SUPPORTED_ARCHITECTURES):
        # long-tail architecture: serve via the HF transformers
        # fallback runtime (reference: the text-generation runtime for
        # models vLLM can't serve) — the generic ModelArch extraction
        # below still sizes capacity planning
        runtime = "transformers"
    arch = arch_from_hf_config(cfg)
    if runtime == "transformers" and not (
            arch.hidden_size > 0 and arch.num_layers > 0
            and arch.num_heads > 0):
        # non-transformer config (Mamba/encoder-decoder/vision): the
        # generic dims are garbage and would drive capacity planning to
        # a too-small instance — refuse loudly instead
        raise ValueError(
            f"architecture {archs!r} for {hf_id} is not "
            f"transformer-shaped (no usable hidden/layers/heads dims); "
            f"cannot size capacity for the fallback runtime")
    quant = quantization or str(
        (cfg.get("quantization_config") or {}).get("quant_method", "")
    )
    preset_name = name or hf_id.split("/")[-1].lower()
    tool_parser, reasoning_parser = derive_parsers(
        hf_id.split("/")[-1], archs)
    return ModelMetadata(
        name=preset_name,
        hf_id=hf_id,
        arch=arch,
        model_file_bytes=model_file_bytes,
        token_limit=arch.max_position_embeddings,
        download_auth_required=download_auth_required,
        quantization=quant,
        tags=tags + (("fallback-runtime",) if runtime != "engine" else ()),
        tool_call_parser=tool_parser,
        reasoning_parser=reasoning_parser,
        runtime=runtime,
        speculative_draft=speculative_draft,
    )
