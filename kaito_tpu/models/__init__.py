from kaito_tpu.models.metadata import (  # noqa: F401
    AttentionKind,
    ModelArch,
    ModelMetadata,
)
from kaito_tpu.models.registry import (  # noqa: F401
    get_model_by_name,
    is_valid_preset,
    list_presets,
    register_model,
)
from kaito_tpu.models.autogen import metadata_from_hf_config  # noqa: F401
import kaito_tpu.models.presets  # noqa: F401  (registers built-in presets)
