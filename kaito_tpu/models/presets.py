"""Built-in model presets.

Same preset-name surface as the reference's
``presets/workspace/models/supported_models.yaml`` (31 presets) so a
KAITO user finds every model they had; each entry carries the public HF
``config.json`` essentials so the engine can instantiate the
architecture and the estimator can size HBM without network access.

Configs are the published architecture numbers for each public
checkpoint.  Entries tagged ``approx`` use best-effort numbers where
the upstream checkpoint is gated/unpublished.
"""

from __future__ import annotations

from kaito_tpu.models.autogen import metadata_from_hf_config
from kaito_tpu.models.metadata import ModelMetadata
from kaito_tpu.models.registry import register_model

_LLAMA31_SCALING = {
    "rope_type": "llama3",
    "factor": 8.0,
    "low_freq_factor": 1.0,
    "high_freq_factor": 4.0,
    "original_max_position_embeddings": 8192,
}


def _llama(vocab, hidden, layers, heads, kv, inter, max_pos=131072, theta=500000.0, scaling=_LLAMA31_SCALING):
    return {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": vocab,
        "hidden_size": hidden,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv,
        "intermediate_size": inter,
        "max_position_embeddings": max_pos,
        "rope_theta": theta,
        "rope_scaling": scaling,
        "rms_norm_eps": 1e-5,
    }


_PRESETS: list[ModelMetadata] = []


def _add(name, hf_id, cfg, *, auth=False, quant="", tags=(), draft=""):
    md = metadata_from_hf_config(
        hf_id, cfg, name=name, download_auth_required=auth,
        quantization=quant, tags=tuple(tags), speculative_draft=draft,
    )
    _PRESETS.append(md)
    return md


# ---- Llama --------------------------------------------------------------
_add("llama-3.1-8b-instruct", "meta-llama/Llama-3.1-8B-Instruct",
     _llama(128256, 4096, 32, 32, 8, 14336), auth=True)
# curated draft pairing: same tokenizer family (vocab 128256), ~9x
# smaller — the "auto" value of the kaito-tpu.io/speculative-draft
# annotation resolves to this (docs/speculative.md)
_add("llama-3.3-70b-instruct", "meta-llama/Llama-3.3-70B-Instruct",
     _llama(128256, 8192, 80, 64, 8, 28672), auth=True,
     draft="llama-3.1-8b-instruct")

# ---- DeepSeek V3 / R1 (MLA + MoE) --------------------------------------
_DEEPSEEK_V3 = {
    "architectures": ["DeepseekV3ForCausalLM"],
    "model_type": "deepseek_v3",
    "vocab_size": 129280,
    "hidden_size": 7168,
    "num_hidden_layers": 61,
    "num_attention_heads": 128,
    "num_key_value_heads": 128,
    "intermediate_size": 18432,
    "moe_intermediate_size": 2048,
    "n_routed_experts": 256,
    "num_experts_per_tok": 8,
    "n_shared_experts": 1,
    "first_k_dense_replace": 3,
    "kv_lora_rank": 512,
    "q_lora_rank": 1536,
    "qk_rope_head_dim": 64,
    "qk_nope_head_dim": 128,
    "v_head_dim": 128,
    "max_position_embeddings": 163840,
    "rope_theta": 10000.0,
}
_add("deepseek-r1-0528", "deepseek-ai/DeepSeek-R1-0528", _DEEPSEEK_V3, tags=("reasoning",))
_add("deepseek-v3-0324", "deepseek-ai/DeepSeek-V3-0324", _DEEPSEEK_V3)

# ---- Falcon -------------------------------------------------------------
_FALCON_7B = {
    "architectures": ["FalconForCausalLM"],
    "model_type": "falcon",
    "vocab_size": 65024,
    "hidden_size": 4544,
    "num_hidden_layers": 32,
    "num_attention_heads": 71,
    "multi_query": True,
    "intermediate_size": 18176,
    "max_position_embeddings": 2048,
    "hidden_act": "gelu",
}
_FALCON_40B = {
    "architectures": ["FalconForCausalLM"],
    "model_type": "falcon",
    "vocab_size": 65024,
    "hidden_size": 8192,
    "num_hidden_layers": 60,
    "num_attention_heads": 128,
    "num_key_value_heads": 8,
    "intermediate_size": 32768,
    "max_position_embeddings": 2048,
    "hidden_act": "gelu",
}
_add("falcon-7b", "tiiuae/falcon-7b", _FALCON_7B)
_add("falcon-7b-instruct", "tiiuae/falcon-7b-instruct", _FALCON_7B)
_add("falcon-40b", "tiiuae/falcon-40b", _FALCON_40B)
_add("falcon-40b-instruct", "tiiuae/falcon-40b-instruct", _FALCON_40B)

# ---- Mistral / Ministral ------------------------------------------------
def _mistral(vocab, hidden, layers, heads, kv, inter, max_pos=32768, theta=1000000.0, head_dim=None):
    cfg = {
        "architectures": ["MistralForCausalLM"],
        "model_type": "mistral",
        "vocab_size": vocab,
        "hidden_size": hidden,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv,
        "intermediate_size": inter,
        "max_position_embeddings": max_pos,
        "rope_theta": theta,
        "rope_scaling": None,
    }
    if head_dim:
        cfg["head_dim"] = head_dim
    return cfg


_add("mistral-7b", "mistralai/Mistral-7B-v0.3", _mistral(32768, 4096, 32, 32, 8, 14336))
_add("mistral-7b-instruct", "mistralai/Mistral-7B-Instruct-v0.3", _mistral(32768, 4096, 32, 32, 8, 14336))
_add("ministral-3-3b-instruct", "mistralai/Ministral-3-3B-Instruct",
     _mistral(131072, 3072, 26, 32, 8, 9216, max_pos=131072, head_dim=128), tags=("approx",))
_add("ministral-3-8b-instruct", "mistralai/Ministral-3-8B-Instruct",
     _mistral(131072, 4096, 36, 32, 8, 12288, max_pos=131072, head_dim=128), tags=("approx",))
_add("ministral-3-14b-instruct", "mistralai/Ministral-3-14B-Instruct",
     _mistral(131072, 5120, 40, 40, 8, 16384, max_pos=131072, head_dim=128), tags=("approx",))
# Mistral Large 3: DeepSeek-V3-scale sparse MoE (public numbers approximate).
_add("mistral-large-3-675b-instruct", "mistralai/Mistral-Large-3-675B-Instruct",
     dict(_DEEPSEEK_V3, vocab_size=131072), tags=("approx",))

# ---- Phi ---------------------------------------------------------------
_add("phi-2", "microsoft/phi-2", {
    "architectures": ["PhiForCausalLM"],
    "model_type": "phi",
    "vocab_size": 51200,
    "hidden_size": 2560,
    "num_hidden_layers": 32,
    "num_attention_heads": 32,
    "intermediate_size": 10240,
    "max_position_embeddings": 2048,
    "partial_rotary_factor": 0.4,
    "hidden_act": "gelu_new",
    "layer_norm_epsilon": 1e-5,
})


def _phi3(vocab, hidden, layers, heads, kv, inter, max_pos, scaling=None, partial=1.0, tie=False):
    return {
        "architectures": ["Phi3ForCausalLM"],
        "model_type": "phi3",
        "vocab_size": vocab,
        "hidden_size": hidden,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv,
        "intermediate_size": inter,
        "max_position_embeddings": max_pos,
        "rope_theta": 10000.0,
        "rope_scaling": scaling,
        "partial_rotary_factor": partial,
        "tie_word_embeddings": tie,
    }


_add("phi-3-mini-4k-instruct", "microsoft/Phi-3-mini-4k-instruct", _phi3(32064, 3072, 32, 32, 32, 8192, 4096))
_add("phi-3-mini-128k-instruct", "microsoft/Phi-3-mini-128k-instruct",
     _phi3(32064, 3072, 32, 32, 32, 8192, 131072, scaling={"rope_type": "longrope", "factor": 32.0}))
_add("phi-3-medium-4k-instruct", "microsoft/Phi-3-medium-4k-instruct", _phi3(32064, 5120, 40, 40, 10, 17920, 4096))
_add("phi-3-medium-128k-instruct", "microsoft/Phi-3-medium-128k-instruct",
     _phi3(32064, 5120, 40, 40, 10, 17920, 131072, scaling={"rope_type": "longrope", "factor": 32.0}))
_add("phi-3.5-mini-instruct", "microsoft/Phi-3.5-mini-instruct",
     _phi3(32064, 3072, 32, 32, 32, 8192, 131072, scaling={"rope_type": "longrope", "factor": 32.0}))
_add("phi-4-mini-instruct", "microsoft/Phi-4-mini-instruct",
     _phi3(200064, 3072, 32, 24, 8, 8192, 131072, partial=0.75, tie=True))
_add("phi-4", "microsoft/phi-4", _phi3(100352, 5120, 40, 40, 10, 17920, 16384))

# ---- Qwen 2.5 ----------------------------------------------------------
def _qwen2(vocab, hidden, layers, heads, kv, inter, max_pos=32768):
    return {
        "architectures": ["Qwen2ForCausalLM"],
        "model_type": "qwen2",
        "vocab_size": vocab,
        "hidden_size": hidden,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv,
        "intermediate_size": inter,
        "max_position_embeddings": max_pos,
        "rope_theta": 1000000.0,
        "rms_norm_eps": 1e-6,
        "tie_word_embeddings": hidden < 2048,
    }


_add("qwen2.5-coder-7b-instruct", "Qwen/Qwen2.5-Coder-7B-Instruct", _qwen2(152064, 3584, 28, 28, 4, 18944))
_add("qwen2.5-coder-32b-instruct", "Qwen/Qwen2.5-Coder-32B-Instruct", _qwen2(152064, 5120, 64, 40, 8, 27648),
     draft="qwen2.5-coder-7b-instruct")
_add("deepseek-r1-distill-qwen-14b", "deepseek-ai/DeepSeek-R1-Distill-Qwen-14B",
     _qwen2(152064, 5120, 48, 40, 8, 13824, max_pos=131072), tags=("reasoning",))
_add("deepseek-r1-distill-llama-8b", "deepseek-ai/DeepSeek-R1-Distill-Llama-8B",
     _llama(128256, 4096, 32, 32, 8, 14336), tags=("reasoning",))

# ---- Gemma 3 -----------------------------------------------------------
def _gemma3(vocab, hidden, layers, heads, kv, head_dim, inter, qscalar, max_pos=131072):
    return {
        "architectures": ["Gemma3ForCausalLM"],
        "model_type": "gemma3_text",
        "vocab_size": vocab,
        "hidden_size": hidden,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv,
        "head_dim": head_dim,
        "intermediate_size": inter,
        "max_position_embeddings": max_pos,
        "rope_theta": 1000000.0,
        "sliding_window": 1024,
        "sliding_window_pattern": 6,
        "query_pre_attn_scalar": qscalar,
        "hidden_activation": "gelu_pytorch_tanh",
        "rms_norm_eps": 1e-6,
        "tie_word_embeddings": True,
    }


_add("gemma-3-4b-instruct", "google/gemma-3-4b-it", _gemma3(262208, 2560, 34, 8, 4, 256, 10240, 256), auth=True)
_add("gemma-3-27b-instruct", "google/gemma-3-27b-it", _gemma3(262208, 5376, 62, 32, 16, 128, 21504, 168), auth=True)

# ---- GPT-OSS (MoE) -----------------------------------------------------
def _gpt_oss(layers, experts):
    return {
        "architectures": ["GptOssForCausalLM"],
        "model_type": "gpt_oss",
        "vocab_size": 201088,
        "hidden_size": 2880,
        "num_hidden_layers": layers,
        "num_attention_heads": 64,
        "num_key_value_heads": 8,
        "head_dim": 64,
        "intermediate_size": 2880,
        "num_local_experts": experts,
        "num_experts_per_tok": 4,
        "max_position_embeddings": 131072,
        "rope_theta": 150000.0,
        "sliding_window": 128,
        "quantization_config": {"quant_method": "mxfp4"},
    }


_add("gpt-oss-20b", "openai/gpt-oss-20b", _gpt_oss(24, 32), quant="mxfp4")
_add("gpt-oss-120b", "openai/gpt-oss-120b", _gpt_oss(36, 128), quant="mxfp4")

# ---- additional current-generation presets (beyond the reference's 31) --
_add("llama-3.2-1b-instruct", "meta-llama/Llama-3.2-1B-Instruct",
     {**_llama(128256, 2048, 16, 32, 8, 8192), "tie_word_embeddings": True,
      "head_dim": 64}, auth=True)
_add("llama-3.2-3b-instruct", "meta-llama/Llama-3.2-3B-Instruct",
     {**_llama(128256, 3072, 28, 24, 8, 8192), "tie_word_embeddings": True,
      "head_dim": 128}, auth=True)


def _qwen3(vocab, hidden, layers, heads, kv, inter, head_dim=128, max_pos=40960):
    return {
        "architectures": ["Qwen3ForCausalLM"],
        "model_type": "qwen3",
        "vocab_size": vocab,
        "hidden_size": hidden,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv,
        "head_dim": head_dim,
        "intermediate_size": inter,
        "max_position_embeddings": max_pos,
        "rope_theta": 1000000.0,
        "rms_norm_eps": 1e-6,
    }


_add("qwen3-8b", "Qwen/Qwen3-8B", _qwen3(151936, 4096, 36, 32, 8, 12288))
_add("qwen3-32b", "Qwen/Qwen3-32B", _qwen3(151936, 5120, 64, 64, 8, 25600),
     draft="qwen3-8b")

# ---- tiny test model (not in the reference; for CI and smoke runs) -----
_add("tiny-llama-test", "kaito-tpu/tiny-llama-test",
     _llama(2048, 256, 4, 8, 4, 1024, max_pos=2048, theta=10000.0, scaling=None),
     tags=("test",))

# ---- tiny REAL model: byte-level llama trained in-repo on local prose
# (hack/train_tiny_real.py); the committed checkpoint under
# checkpoints/tiny-llama-real pins golden logprobs + held-out
# bits/byte so rope/template/quant/serving correctness has an end-task
# regression, not just unit parity (VERDICT r3 missing #5) -----
_add("tiny-llama-real", "kaito-tpu/tiny-llama-real",
     _llama(258, 256, 4, 8, 4, 1024, max_pos=2048, theta=10000.0,
            scaling=None),
     tags=("test", "real-checkpoint"))

# MoE sibling: same corpus/tokenizer, mixtral-style 4-expert stack —
# pins router/expert-dispatch correctness end-task alongside the dense
# goldens (checkpoints/tiny-moe-real)
_add("tiny-moe-real", "kaito-tpu/tiny-moe-real",
     {"architectures": ["MixtralForCausalLM"], "model_type": "mixtral",
      "vocab_size": 258, "hidden_size": 128, "num_hidden_layers": 2,
      "num_attention_heads": 4, "num_key_value_heads": 2,
      "intermediate_size": 256, "num_local_experts": 4,
      "num_experts_per_tok": 2, "max_position_embeddings": 2048},
     tags=("test", "real-checkpoint"))


def register_builtin_presets() -> None:
    for md in _PRESETS:
        register_model(md, replace=True)


register_builtin_presets()
