"""In-engine data parallelism: N engine replica groups behind one front.

The serving counterpart of the reference's tier 1, which launches vLLM
with ``--data-parallel-size=<GPUs>`` so one pod runs N engine groups on
one node (`/root/reference/pkg/model/interface.go:500-512`).  TPU-native
shape: the visible chips partition into ``data_parallel`` groups of
``tensor_parallel x expert_parallel`` devices; each group runs a full
``InferenceEngine`` (own mesh, own weights copy, own KV pool, own
scheduler thread), and this facade load-balances requests across them
while exposing ONE engine surface to the HTTP server — aggregate
counters, summed page-pool metrics, shared adapter registry.

Routing is least-loaded (waiting + running) at submit time; aborts
route back to the owning group via the request's ``_dp_group`` tag.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import jax

from kaito_tpu.engine.config import EngineConfig
from kaito_tpu.engine.engine import InferenceEngine, Request, SamplingParams

logger = logging.getLogger(__name__)


class _AggregatePool:
    """Summed allocator view for the /metrics gauges."""

    def __init__(self, engines):
        self._engines = engines

    @property
    def available(self) -> int:
        return sum(e.allocator.available for e in self._engines)

    @property
    def num_pages(self) -> int:
        # gauges compute usable pages as num_pages - 1 per pool; keep
        # that identity for the aggregate (N pools reserve N null pages)
        return sum(e.allocator.num_pages - 1 for e in self._engines) + 1


class _AggregateHostKV:
    def __init__(self, engines):
        self._engines = engines

    @property
    def used_bytes(self) -> int:
        return sum(e.host_kv.used_bytes for e in self._engines
                   if e.host_kv is not None)


class DataParallelEngine:
    """N InferenceEngine groups, one engine surface."""

    def __init__(self, cfg: EngineConfig, metadata=None):
        dp = cfg.data_parallel
        if dp < 2:
            raise ValueError(f"data_parallel must be >= 2, got {dp}")
        if cfg.pipeline_parallel > 1:
            raise ValueError("data_parallel does not compose with "
                             "pipeline_parallel in-engine; scale PP "
                             "deployments with InferenceSet replicas")
        if cfg.pd_enabled:
            raise ValueError("P/D disaggregation routes KV by page id; "
                             "run it with data_parallel=1 per role")
        group = (max(1, cfg.tensor_parallel) * max(1, cfg.expert_parallel)
                 * max(1, cfg.sequence_parallel))
        devices = jax.devices()
        if len(devices) < dp * group:
            raise ValueError(
                f"data_parallel={dp} x (sp*ep*tp)={group} needs "
                f"{dp * group} devices, have {len(devices)}")
        self.cfg = cfg
        self.engines: list[InferenceEngine] = []
        for g in range(dp):
            mesh = self._group_mesh(devices[g * group:(g + 1) * group], cfg)
            eng = InferenceEngine(cfg.replace(data_parallel=1),
                                  metadata=metadata, mesh=mesh)
            self.engines.append(eng)
        first = self.engines[0]
        self.md = first.md
        self.tokenizer = first.tokenizer
        self.adapter_index = first.adapter_index
        self.adapters_merged = first.adapters_merged
        self.allocator = _AggregatePool(self.engines)
        self.host_kv = (_AggregateHostKV(self.engines)
                        if any(e.host_kv is not None for e in self.engines)
                        else None)
        # one histogram family across groups: every group's scheduler
        # observes into the SAME (thread-safe) series, so /metrics
        # exposes one kaito:engine_step_seconds for the whole pod.
        # Tracers/timelines stay per-group — the server's /debug/trace
        # and /debug/timeline merge across `self.engines`.
        for e in self.engines[1:]:
            e.step_hist = first.step_hist
            e.queue_wait_hist = first.queue_wait_hist
        self.step_hist = first.step_hist
        self.queue_wait_hist = first.queue_wait_hist
        self._rr = 0
        self._lock = threading.Lock()
        logger.info("data-parallel serving: %d groups x %d device(s)",
                    dp, group)

    @staticmethod
    def _group_mesh(devices, cfg: EngineConfig):
        """Per-group mesh.  Even a 1-device group gets an explicit mesh
        so its weights/KV land on ITS device (not the process default)."""
        from kaito_tpu.parallel.mesh import build_mesh
        from kaito_tpu.parallel.plan import make_mesh_spec

        spec = make_mesh_spec(sequence=max(1, cfg.sequence_parallel),
                              expert=max(1, cfg.expert_parallel),
                              tensor=max(1, cfg.tensor_parallel))
        return build_mesh(spec, devices)

    # ------------------------------------------------------------------
    # Engine surface (what the HTTP server and metrics touch)
    # ------------------------------------------------------------------

    @property
    def counters(self) -> dict:
        agg: dict = {}
        for e in self.engines:
            for k, v in e.counters.items():
                agg[k] = agg.get(k, 0) + v
        return agg

    @property
    def qos(self):
        # all groups parsed the same cfg.qos_config
        return self.engines[0].qos

    @property
    def num_waiting(self) -> int:
        return sum(e.num_waiting for e in self.engines)

    def num_waiting_for(self, tenant: str) -> int:
        return sum(e.num_waiting_for(tenant) for e in self.engines)

    @property
    def num_running(self) -> int:
        return sum(e.num_running for e in self.engines)

    def _pick(self) -> InferenceEngine:
        """Least-loaded group (waiting+running); the scan starts at a
        rotating offset so ties (an idle fleet) still round-robin."""
        n = len(self.engines)
        with self._lock:
            self._rr = (self._rr + 1) % n
            start = self._rr
        return min((self.engines[(start + i) % n] for i in range(n)),
                   key=lambda e: e.num_waiting + e.num_running)

    def submit(self, prompt_tokens, params: SamplingParams,
               req_id: Optional[str] = None, export_kv: bool = False,
               adapter: str = "",
               timeout_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               tenant: str = "", priority: str = "") -> Request:
        if export_kv:
            raise RuntimeError("P/D KV export requires data_parallel=1")
        eng = self._pick()
        req = eng.submit(prompt_tokens, params, req_id=req_id,
                         adapter=adapter, timeout_s=timeout_s,
                         trace_id=trace_id, tenant=tenant,
                         priority=priority)
        req._dp_group = eng
        return req

    def abort(self, req: Request) -> None:
        getattr(req, "_dp_group", self.engines[0]).abort(req)

    def submit_with_kv(self, *a, **kw):
        raise RuntimeError("P/D KV import requires data_parallel=1")

    def submit_with_kv_chunked(self, *a, **kw):
        raise RuntimeError("P/D KV import requires data_parallel=1")

    @property
    def kv_exports(self):
        return self.engines[0].kv_exports

    def generate(self, prompt: str,
                 params: Optional[SamplingParams] = None) -> str:
        params = params or SamplingParams()
        toks = self.tokenizer.encode(prompt)
        req = self.submit(toks, params)
        return self.tokenizer.decode(list(req.stream()))

    def start(self):
        for e in self.engines:
            e.start()

    def stop(self):
        for e in self.engines:
            e.stop()
