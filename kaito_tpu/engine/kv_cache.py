"""Paged KV cache.

The engine's KV memory is a global page pool per layer —
``[num_layers, num_pages, page_size, kv_heads, head_dim]`` — addressed
through per-sequence page tables, vLLM-style but with static shapes
throughout so XLA compiles one program per (bucket, batch) shape.  The
reference delegates this entirely to vLLM's PagedAttention
(SURVEY.md §2.3); on TPU we own it.

The layout is page-major and TOKEN-major within a page: each page is
one contiguous ``[page_size, kv_heads, head_dim]`` block in HBM (a
single clean leading-index DMA per page in the Pallas decode kernel)
and each token's row is one ``[kv_heads, head_dim]`` tile.  That tile
is exactly what a decode step writes, so the write is a scatter whose
update window is minor-dim-contiguous — XLA keeps the default layout
for it.  (With the head-major order the scatter preferred a transposed
layout while the Mosaic custom call pinned the default one, and XLA
reconciled them with a full-cache copy per layer: 64 GiB/step of pure
layout conversion at phi-4-mini bench shapes.)

Page 0 is reserved as the null page: unused page-table slots point at
it, so gathers are always in-bounds and masking is done by length, not
by index validity.

Quantized mode (``kv_dtype="int8"``): the pools store int8 codes plus a
per-page-per-head fp32 scale tensor ``[L, num_pages, kv_heads]`` carried
in the same pytree.  Writes quantize with a *rescale-on-grow* fold: the
written tile's absmax is folded into the page scale
(sigma_new = max(sigma_old, absmax/127)) and, when the scale grows, the
page's existing codes are re-quantized at the new scale in the same
scatter — so dequantization ``code * sigma`` stays correct for every
token a page holds, not just the last-written one.  Reads dequantize
either inside the Pallas decode kernel (scales ride the page DMA) or
after the gather on the pure-JAX paths.  The null page accumulates
garbage codes AND garbage scales by design; length masking hides both.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from kaito_tpu.models.metadata import ModelArch

NULL_PAGE = 0


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    """Stacked per-layer page pools (a pytree; donate on every step)."""

    k: jax.Array  # [L, num_pages, page_size, kv_heads, head_dim]
    v: jax.Array
    # Per-page-per-head dequantization scales, fp32 [L, num_pages, kv_heads];
    # None for non-quantized pools (None is a valid empty pytree leaf, so
    # the bf16 mode's scan carries and donation are untouched).
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def kv_cache_is_quantized(dtype) -> bool:
    return jnp.dtype(dtype) == jnp.int8


def scale_bytes_per_page(arch: ModelArch) -> int:
    """HBM overhead of the two fp32 scale rows one page carries."""
    return 2 * arch.num_layers * arch.kv_cache_heads * 4


def create_kv_cache(
    arch: ModelArch,
    num_pages: int,
    page_size: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> KVCache:
    shape = (arch.num_layers, num_pages, page_size, arch.kv_cache_heads,
             arch.kv_cache_dim)
    k_scale = v_scale = None
    if kv_cache_is_quantized(dtype):
        # Zero scales dequantize the zeroed pool to exact zeros; scales
        # only grow as real tokens land in a page.
        sshape = (arch.num_layers, num_pages, arch.kv_cache_heads)
        k_scale = jnp.zeros(sshape, jnp.float32)
        v_scale = jnp.zeros(sshape, jnp.float32)
    if arch.attention_kind.value == "MLA":
        # MLA caches one latent stream; `k` holds it, `v` is a
        # zero-size placeholder keeping the pytree uniform
        return KVCache(k=jnp.zeros(shape, dtype),
                       v=jnp.zeros(shape[:-1] + (0,), dtype),
                       k_scale=k_scale, v_scale=v_scale)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   k_scale=k_scale, v_scale=v_scale)


def _safe(s: jax.Array) -> jax.Array:
    """Guard divisions by a not-yet-grown (zero) page scale."""
    return jnp.where(s > 0, s, 1.0)


def dequantize_pages(pages: jax.Array, scale: jax.Array) -> jax.Array:
    """[..., ps, Hkv, D] int8 codes x [..., Hkv] scales -> fp32."""
    return pages.astype(jnp.float32) * scale[..., None, :, None]


def write_prefill_tokens(
    cache_layer: jax.Array,       # [num_pages, ps, Hkv, D] or, with
                                  # ``layer``, the stacked group [Lg, P, ps, Hkv, D]
    new: jax.Array,               # [B, T, Hkv, D]
    page_tables: jax.Array,       # [B, pages_per_seq] int32
    start_pos: jax.Array,         # [B] sequence position of new[:, 0]
    true_lens: jax.Array,         # [B] valid tokens per row; pad -> null page
    page_size: int,
    layer: Optional[jax.Array] = None,   # scalar layer index into the stack
) -> jax.Array:
    """Scatter a batch of prefill chunks into their pages in one flat
    scatter (a vmap would fork the shared pool buffer per row).

    With ``layer``, the stacked group cache is updated in place at that
    layer — the form the serve path uses so the cache can ride the layer
    scan as a *carry* (in-place scatter) instead of as stacked ys, which
    copied the full pool every step (round-2 perf finding: 13.9 ms of a
    31 ms decode step was cache copies)."""
    B, T = new.shape[:2]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    pos = start_pos[:, None] + t                                  # [B, T]
    page_idx = jnp.take_along_axis(page_tables, pos // page_size, axis=1)
    valid = t < true_lens[:, None]
    page_idx = jnp.where(valid, page_idx, NULL_PAGE)
    offset = pos % page_size
    flat = new.reshape(B * T, *new.shape[2:])                      # [B*T, Hkv, D]
    if layer is None:
        return cache_layer.at[page_idx.reshape(-1), offset.reshape(-1)].set(flat)
    return cache_layer.at[layer, page_idx.reshape(-1), offset.reshape(-1)].set(flat)


def write_packed_prefill_tokens(
    cache_layer: jax.Array,       # [num_pages, ps, Hkv, D] or, with
                                  # ``layer``, the stacked group [Lg, P, ps, Hkv, D]
    new: jax.Array,               # [1, T, Hkv, D] segment-packed row
    tok_pages: jax.Array,         # [T] int32 page per token (pad -> null page)
    offsets: jax.Array,           # [T] int32 slot within the page
    layer: Optional[jax.Array] = None,
) -> jax.Array:
    """Scatter a SEGMENT-PACKED prefill row into its pages.

    Many fresh prompts share one packed row (``model.prefill_packed``);
    each token carries its own page index and in-page offset, computed
    host-side from its segment's page table, so one flat scatter lands
    every segment's KV in that segment's own pages.  Pad tokens point
    at the null page."""
    flat = new[0]                                                 # [T, Hkv, D]
    if layer is None:
        return cache_layer.at[tok_pages, offsets].set(flat)
    return cache_layer.at[layer, tok_pages, offsets].set(flat)


def write_packed_prefill_tokens_q(
    cache_layer: jax.Array,       # int8 [Lg, P, ps, Hkv, D] (or unstacked)
    scale_layer: jax.Array,       # fp32 [Lg, P, Hkv] (or [P, Hkv])
    new: jax.Array,               # [1, T, Hkv, D] segment-packed row
    pack_pages: jax.Array,        # [n_pg] int32 pages of the pack (pad -> null)
    tok_pgslot: jax.Array,        # [T] int32 index into pack_pages (n_pg = drop)
    offsets: jax.Array,           # [T] int32 slot within the page
    layer: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Quantizing counterpart of :func:`write_packed_prefill_tokens`.

    Same rescale-on-grow fold as :func:`write_prefill_tokens_q`, but the
    page span is the union of every segment's pages (``pack_pages``) and
    each token addresses its page through ``tok_pgslot``.  Segments are
    fresh (start at position 0), so a page's absmax fold sees exactly
    the same tokens as the serial per-sequence write — the grown scales
    and codes come out identical.  Pad tokens carry ``tok_pgslot ==
    n_pg`` (out of bounds -> dropped) and are excluded from the fold."""
    n_pg = pack_pages.shape[0]
    lidx = (layer,) if layer is not None else ()
    pages = cache_layer[lidx + (pack_pages,)]      # [n_pg, ps, Hkv, D]
    old = scale_layer[lidx + (pack_pages,)]        # [n_pg, Hkv]

    new32 = new[0].astype(jnp.float32)                            # [T, Hkv, D]
    tokmax = jnp.max(jnp.abs(new32), axis=-1)                     # [T, Hkv]
    onehot = tok_pgslot[:, None] == jnp.arange(n_pg)[None, :]     # [T, n_pg]
    cand = jnp.max(
        jnp.where(onehot[..., None], tokmax[:, None, :], 0.0),
        axis=0) / 127.0                                           # [n_pg, Hkv]
    s_new = jnp.maximum(old, cand)
    merged = _requantize(pages, old, s_new)

    s_tok = s_new[jnp.clip(tok_pgslot, 0, n_pg - 1)]              # [T, Hkv]
    q_tok = jnp.clip(jnp.round(new32 / _safe(s_tok)[..., None]), -127, 127)
    merged = merged.at[tok_pgslot, offsets].set(q_tok)
    merged = merged.astype(cache_layer.dtype)

    cache_layer = cache_layer.at[lidx + (pack_pages,)].set(merged)
    scale_layer = scale_layer.at[lidx + (pack_pages,)].set(s_new)
    return cache_layer, scale_layer


def write_decode_tokens(
    cache_layer: jax.Array,       # [num_pages, ps, Hkv, D] or, with
                                  # ``layer``, the stacked group [Lg, P, ps, Hkv, D]
    new: jax.Array,               # [B, Hkv, D] one token per sequence
    page_tables: jax.Array,       # [B, pages_per_seq]
    positions: jax.Array,         # [B] current position of each new token
    page_size: int,
    active: Optional[jax.Array] = None,  # [B] bool; inactive rows hit page 0
    layer: Optional[jax.Array] = None,   # scalar layer index into the stack
) -> jax.Array:
    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    if active is not None:
        # inactive rows target the null page (harmless scratch writes)
        page_idx = jnp.where(active, page_idx, NULL_PAGE)
    offset = positions % page_size
    if layer is None:
        return cache_layer.at[page_idx, offset].set(new)
    return cache_layer.at[layer, page_idx, offset].set(new)


def _requantize(pages: jax.Array, old: jax.Array, s_new: jax.Array) -> jax.Array:
    """Re-express existing int8 codes at a grown page scale.

    ``ratio = old/new <= 1`` so the rescaled codes stay in [-127, 127];
    when the scale didn't grow ratio is exactly 1.0 and the round-trip
    is the identity (no drift on repeated writes to the same page)."""
    ratio = jnp.where(s_new > 0, old / _safe(s_new), 1.0)
    scaled = pages.astype(jnp.float32) * ratio[..., None, :, None]
    return jnp.clip(jnp.round(scaled), -127, 127)


def write_decode_tokens_q(
    cache_layer: jax.Array,       # int8 [Lg, P, ps, Hkv, D] (or unstacked)
    scale_layer: jax.Array,       # fp32 [Lg, P, Hkv] (or [P, Hkv])
    new: jax.Array,               # [B, Hkv, D] one token per sequence
    page_tables: jax.Array,       # [B, pages_per_seq]
    positions: jax.Array,         # [B]
    page_size: int,
    active: Optional[jax.Array] = None,
    layer: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Quantizing counterpart of :func:`write_decode_tokens`.

    Gathers each target page + its scale, folds the new token's absmax
    into the scale (rescaling the page's existing codes if it grew),
    inserts the quantized token row, and scatters both back.  Inactive
    rows hit the null page — its codes and scale become garbage, which
    is fine: reads mask by length and scales stay finite."""
    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    if active is not None:
        page_idx = jnp.where(active, page_idx, NULL_PAGE)
    offset = positions % page_size

    lidx = (layer,) if layer is not None else ()
    pages = cache_layer[lidx + (page_idx,)]        # [B, ps, Hkv, D]
    old = scale_layer[lidx + (page_idx,)]          # [B, Hkv]

    new32 = new.astype(jnp.float32)
    cand = jnp.max(jnp.abs(new32), axis=-1) / 127.0          # [B, Hkv]
    s_new = jnp.maximum(old, cand)
    merged = _requantize(pages, old, s_new)
    q_new = jnp.clip(jnp.round(new32 / _safe(s_new)[..., None]), -127, 127)

    ps = cache_layer.shape[-3]
    at_row = jnp.arange(ps, dtype=jnp.int32)[None, :] == offset[:, None]
    merged = jnp.where(at_row[..., None, None], q_new[:, None], merged)
    merged = merged.astype(cache_layer.dtype)

    cache_layer = cache_layer.at[lidx + (page_idx,)].set(merged)
    scale_layer = scale_layer.at[lidx + (page_idx,)].set(s_new)
    return cache_layer, scale_layer


def write_prefill_tokens_q(
    cache_layer: jax.Array,       # int8 [Lg, P, ps, Hkv, D] (or unstacked)
    scale_layer: jax.Array,       # fp32 [Lg, P, Hkv] (or [P, Hkv])
    new: jax.Array,               # [B, T, Hkv, D]
    page_tables: jax.Array,       # [B, pages_per_seq]
    start_pos: jax.Array,         # [B]
    true_lens: jax.Array,         # [B]
    page_size: int,
    layer: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Quantizing counterpart of :func:`write_prefill_tokens`.

    A T-token chunk starting mid-page spans at most ceil(T/ps)+1 page
    slots, so the update is reformulated page-wise: gather that span,
    fold per-segment absmaxes into the span's scales, requantize what
    the pages already held, insert the new tokens at the grown scales,
    and scatter the span back.  Invalid (padding) tokens are routed to
    an out-of-bounds segment — JAX drops OOB scatter indices — and are
    excluded from the absmax fold."""
    B, T = new.shape[:2]
    ps = page_size
    n_pg = (T + ps - 1) // ps + 1

    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    pos = start_pos[:, None] + t                                  # [B, T]
    valid = t < true_lens[:, None]
    first_slot = (start_pos // ps).astype(jnp.int32)              # [B]
    seg = pos // ps - first_slot[:, None]                         # [B, T] in [0, n_pg)

    pmax = page_tables.shape[1]
    slot_ids = first_slot[:, None] + jnp.arange(n_pg, dtype=jnp.int32)[None, :]
    in_range = slot_ids < pmax
    span_pages = jnp.take_along_axis(
        page_tables, jnp.clip(slot_ids, 0, pmax - 1), axis=1)     # [B, n_pg]
    span_pages = jnp.where(in_range, span_pages, NULL_PAGE)

    lidx = (layer,) if layer is not None else ()
    pages = cache_layer[lidx + (span_pages,)]      # [B, n_pg, ps, Hkv, D]
    old = scale_layer[lidx + (span_pages,)]        # [B, n_pg, Hkv]

    new32 = new.astype(jnp.float32)
    tokmax = jnp.max(jnp.abs(new32), axis=-1)                     # [B, T, Hkv]
    seg_onehot = (seg[:, :, None] == jnp.arange(n_pg)[None, None, :]) \
        & valid[:, :, None]                                        # [B, T, n_pg]
    cand = jnp.max(
        jnp.where(seg_onehot[..., None], tokmax[:, :, None, :], 0.0),
        axis=1) / 127.0                                            # [B, n_pg, Hkv]
    s_new = jnp.maximum(old, cand)
    merged = _requantize(pages, old, s_new)

    s_tok = jnp.take_along_axis(
        s_new, jnp.clip(seg, 0, n_pg - 1)[..., None], axis=1)     # [B, T, Hkv]
    q_tok = jnp.clip(jnp.round(new32 / _safe(s_tok)[..., None]), -127, 127)

    # Insert each token into its page-span slot; invalid tokens get
    # segment n_pg, which is out of bounds for axis 1 -> dropped.
    b_idx = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32)[:, None], (B, T))
    seg_i = jnp.where(valid, seg, n_pg)
    offset = pos % ps
    merged = merged.at[
        b_idx.reshape(-1), seg_i.reshape(-1), offset.reshape(-1)
    ].set(q_tok.reshape(B * T, *q_tok.shape[2:]))
    merged = merged.astype(cache_layer.dtype)

    flat_pages = span_pages.reshape(-1)
    cache_layer = cache_layer.at[lidx + (flat_pages,)].set(
        merged.reshape(B * n_pg, *merged.shape[2:]))
    scale_layer = scale_layer.at[lidx + (flat_pages,)].set(
        s_new.reshape(B * n_pg, s_new.shape[-1]))
    return cache_layer, scale_layer
