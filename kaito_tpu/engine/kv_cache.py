"""Paged KV cache.

The engine's KV memory is a global page pool per layer —
``[num_layers, num_pages, page_size, kv_heads, head_dim]`` — addressed
through per-sequence page tables, vLLM-style but with static shapes
throughout so XLA compiles one program per (bucket, batch) shape.  The
reference delegates this entirely to vLLM's PagedAttention
(SURVEY.md §2.3); on TPU we own it.

The layout is page-major and TOKEN-major within a page: each page is
one contiguous ``[page_size, kv_heads, head_dim]`` block in HBM (a
single clean leading-index DMA per page in the Pallas decode kernel)
and each token's row is one ``[kv_heads, head_dim]`` tile.  That tile
is exactly what a decode step writes, so the write is a scatter whose
update window is minor-dim-contiguous — XLA keeps the default layout
for it.  (With the head-major order the scatter preferred a transposed
layout while the Mosaic custom call pinned the default one, and XLA
reconciled them with a full-cache copy per layer: 64 GiB/step of pure
layout conversion at phi-4-mini bench shapes.)

Page 0 is reserved as the null page: unused page-table slots point at
it, so gathers are always in-bounds and masking is done by length, not
by index validity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from kaito_tpu.models.metadata import ModelArch

NULL_PAGE = 0


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    """Stacked per-layer page pools (a pytree; donate on every step)."""

    k: jax.Array  # [L, num_pages, page_size, kv_heads, head_dim]
    v: jax.Array

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]


def create_kv_cache(
    arch: ModelArch,
    num_pages: int,
    page_size: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> KVCache:
    shape = (arch.num_layers, num_pages, page_size, arch.kv_cache_heads,
             arch.kv_cache_dim)
    if arch.attention_kind.value == "MLA":
        # MLA caches one latent stream; `k` holds it, `v` is a
        # zero-size placeholder keeping the pytree uniform
        return KVCache(k=jnp.zeros(shape, dtype),
                       v=jnp.zeros(shape[:-1] + (0,), dtype))
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def write_prefill_tokens(
    cache_layer: jax.Array,       # [num_pages, ps, Hkv, D] or, with
                                  # ``layer``, the stacked group [Lg, P, ps, Hkv, D]
    new: jax.Array,               # [B, T, Hkv, D]
    page_tables: jax.Array,       # [B, pages_per_seq] int32
    start_pos: jax.Array,         # [B] sequence position of new[:, 0]
    true_lens: jax.Array,         # [B] valid tokens per row; pad -> null page
    page_size: int,
    layer: Optional[jax.Array] = None,   # scalar layer index into the stack
) -> jax.Array:
    """Scatter a batch of prefill chunks into their pages in one flat
    scatter (a vmap would fork the shared pool buffer per row).

    With ``layer``, the stacked group cache is updated in place at that
    layer — the form the serve path uses so the cache can ride the layer
    scan as a *carry* (in-place scatter) instead of as stacked ys, which
    copied the full pool every step (round-2 perf finding: 13.9 ms of a
    31 ms decode step was cache copies)."""
    B, T = new.shape[:2]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    pos = start_pos[:, None] + t                                  # [B, T]
    page_idx = jnp.take_along_axis(page_tables, pos // page_size, axis=1)
    valid = t < true_lens[:, None]
    page_idx = jnp.where(valid, page_idx, NULL_PAGE)
    offset = pos % page_size
    flat = new.reshape(B * T, *new.shape[2:])                      # [B*T, Hkv, D]
    if layer is None:
        return cache_layer.at[page_idx.reshape(-1), offset.reshape(-1)].set(flat)
    return cache_layer.at[layer, page_idx.reshape(-1), offset.reshape(-1)].set(flat)


def write_decode_tokens(
    cache_layer: jax.Array,       # [num_pages, ps, Hkv, D] or, with
                                  # ``layer``, the stacked group [Lg, P, ps, Hkv, D]
    new: jax.Array,               # [B, Hkv, D] one token per sequence
    page_tables: jax.Array,       # [B, pages_per_seq]
    positions: jax.Array,         # [B] current position of each new token
    page_size: int,
    active: Optional[jax.Array] = None,  # [B] bool; inactive rows hit page 0
    layer: Optional[jax.Array] = None,   # scalar layer index into the stack
) -> jax.Array:
    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    if active is not None:
        # inactive rows target the null page (harmless scratch writes)
        page_idx = jnp.where(active, page_idx, NULL_PAGE)
    offset = positions % page_size
    if layer is None:
        return cache_layer.at[page_idx, offset].set(new)
    return cache_layer.at[layer, page_idx, offset].set(new)
