"""Grammar-constrained decoding: JSON schema / regex -> token automata.

The serving-side counterpart of vLLM's guided decoding (the reference
operator's agentic surface).  A schema compiles once into a char-level
DFA (Outlines-style: regex AST -> Thompson NFA -> subset DFA), which is
then lowered against the tokenizer into two dense tables:

    allow[state, token] : bool   -- token may be emitted in this state
    next[state, token]  : int32  -- DFA state after emitting it

Decode steps pay a single gather-and-add of -inf rows on device (see
``sampler.sample``); the host side advances one int per emitted token.
Compiled grammars live in a bounded LRU (``GrammarCache``) keyed by a
schema hash, so hot agent schemas compile once and every subsequent
request is an O(1) lookup.  ``GrammarTable`` packs the masks of all
live grammars into one device-resident table so a whole heterogeneous
batch is served by one gather — a constrained request never serializes
the step or forces a per-request retrace.

Everything here is host-side numpy + pure python; jax enters only in
the sampler/engine, which consume the packed tables.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


class GrammarError(ValueError):
    """Malformed / unsupported / oversized grammar input.

    Raised during request validation and schema compilation — always in
    the HTTP request thread, never the scheduler step thread — so the
    server can turn it into a typed 4xx body."""


# Caps keep compilation O(small) and deny pathological schemas a seat
# in the step thread's memory budget.
MAX_SCHEMA_BYTES = 64 * 1024
MAX_REGEX_LEN = 4096
_MAX_REPEAT = 64          # {m,n} duplication cap (also maxItems/maxLength)
_MAX_SCHEMA_DEPTH = 12

# ---------------------------------------------------------------------------
# Regex AST
#
# Nodes are plain tuples:
#   ("lit", ch)                  single char
#   ("class", frozenset, neg)    char class (neg=True => complement)
#   ("cat", [nodes])             concatenation (empty => epsilon)
#   ("alt", [nodes])             alternation
#   ("star"|"plus"|"opt", node)
#   ("rep", node, m, n)          bounded repeat; n=None => unbounded
#   ("objseq", [members], [optional]) JSON-object property sequence —
#        built natively into the NFA so optional properties stay linear
#        (a comma-correct alternation expansion is exponential)
# ---------------------------------------------------------------------------

_DIGITS = frozenset("0123456789")
_WORD = frozenset("abcdefghijklmnopqrstuvwxyz"
                  "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r\f\v")
_HEX = frozenset("0123456789abcdefABCDEF")


class _RegexParser:
    """A compact regex subset: literals, escapes (incl. \\d \\w \\s and
    their complements), ``[...]`` classes with ranges/negation, ``.``,
    ``* + ? {m} {m,} {m,n}``, ``|`` and ``(...)`` / ``(?:...)`` groups.
    Anchors/backrefs/lookaround are rejected with a clear error."""

    def __init__(self, src: str):
        if len(src) > MAX_REGEX_LEN:
            raise GrammarError(
                f"regex too long: {len(src)} > {MAX_REGEX_LEN} chars")
        self.s = src
        self.i = 0

    def parse(self):
        node = self._alt()
        if self.i != len(self.s):
            raise GrammarError(
                f"unexpected {self.s[self.i]!r} at regex offset {self.i}")
        return node

    def _peek(self) -> Optional[str]:
        return self.s[self.i] if self.i < len(self.s) else None

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self.i += 1
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        items = []
        while True:
            c = self._peek()
            if c is None or c in "|)":
                break
            items.append(self._repeat())
        if len(items) == 1:
            return items[0]
        return ("cat", items)

    def _repeat(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                node, self.i = ("star", node), self.i + 1
            elif c == "+":
                node, self.i = ("plus", node), self.i + 1
            elif c == "?":
                node, self.i = ("opt", node), self.i + 1
            elif c == "{":
                node = ("rep", node, *self._bounds())
            else:
                return node

    def _bounds(self):
        j = self.s.find("}", self.i)
        if j < 0:
            raise GrammarError("unterminated {m,n} bound")
        body = self.s[self.i + 1:j]
        self.i = j + 1
        parts = body.split(",")
        try:
            if len(parts) == 1:
                m = n = int(parts[0])
            elif len(parts) == 2:
                m = int(parts[0]) if parts[0] else 0
                n = int(parts[1]) if parts[1] else None
            else:
                raise ValueError(body)
        except ValueError:
            raise GrammarError(f"bad repeat bound {{{body}}}") from None
        if m < 0 or (n is not None and (n < m or n > _MAX_REPEAT)) \
                or m > _MAX_REPEAT:
            raise GrammarError(
                f"repeat bound {{{body}}} outside [0, {_MAX_REPEAT}]")
        return m, n

    def _atom(self):
        c = self._peek()
        if c is None:
            raise GrammarError("unexpected end of regex")
        if c == "(":
            self.i += 1
            if self.s.startswith("?:", self.i):
                self.i += 2
            elif self._peek() == "?":
                raise GrammarError(
                    "lookaround / named groups are not supported")
            node = self._alt()
            if self._peek() != ")":
                raise GrammarError("unbalanced parenthesis")
            self.i += 1
            return node
        if c == "[":
            return self._char_class()
        if c == "\\":
            return self._escape(in_class=False)
        if c == ".":
            self.i += 1
            return ("class", frozenset("\n"), True)
        if c in "*+?{":
            raise GrammarError(f"dangling quantifier {c!r}")
        if c in "^$":
            raise GrammarError(
                f"anchor {c!r} is not supported (patterns are implicitly "
                "anchored)")
        self.i += 1
        return ("lit", c)

    def _escape(self, in_class: bool):
        self.i += 1
        c = self._peek()
        if c is None:
            raise GrammarError("dangling backslash")
        self.i += 1
        table = {"d": (_DIGITS, False), "D": (_DIGITS, True),
                 "w": (_WORD, False), "W": (_WORD, True),
                 "s": (_SPACE, False), "S": (_SPACE, True)}
        if c in table:
            chars, neg = table[c]
            return ("class", chars, neg)
        lit = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v",
               "0": "\0"}.get(c, c)
        if c.isalnum() and c not in "ntrfv0":
            raise GrammarError(f"unsupported escape \\{c}")
        return ("lit", lit) if not in_class else ("cls-lit", lit)

    def _char_class(self):
        self.i += 1  # '['
        neg = self._peek() == "^"
        if neg:
            self.i += 1
        chars: set[str] = set()
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise GrammarError("unterminated character class")
            if c == "]" and not first:
                self.i += 1
                return ("class", frozenset(chars), neg)
            first = False
            if c == "\\":
                node = self._escape(in_class=True)
                if node[0] == "class":
                    if node[2]:
                        raise GrammarError(
                            "negated escape inside character class")
                    chars |= node[1]
                    continue
                lo = node[1]
            else:
                self.i += 1
                lo = c
            if self._peek() == "-" and self.i + 1 < len(self.s) \
                    and self.s[self.i + 1] != "]":
                self.i += 1
                hi = self._peek()
                if hi == "\\":
                    hi = self._escape(in_class=True)[1]
                else:
                    self.i += 1
                if ord(hi) < ord(lo):
                    raise GrammarError(f"bad class range {lo}-{hi}")
                chars |= {chr(o) for o in range(ord(lo), ord(hi) + 1)}
            else:
                chars.add(lo)


def _regex_ast(pattern: str):
    """Parse a pattern (stripping optional ^...$ anchors — matching is
    always whole-string here)."""
    if pattern.startswith("^"):
        pattern = pattern[1:]
    if pattern.endswith("$") and not pattern.endswith("\\$"):
        pattern = pattern[:-1]
    return _RegexParser(pattern).parse()


# ---------------------------------------------------------------------------
# JSON schema -> regex AST (compact canonical JSON: no inter-token
# whitespace, so the emitted text always round-trips json.loads)
# ---------------------------------------------------------------------------

def _lit_str(text: str):
    return ("cat", [("lit", ch) for ch in text])


def _json_literal(value):
    """AST matching exactly json.dumps(value) (compact separators)."""
    return _lit_str(json.dumps(value, separators=(",", ":"),
                               ensure_ascii=True))


# one JSON string character: printable ASCII except " and \, or an
# escape sequence (\" \\ \/ \b \f \n \r \t \uXXXX).  Plain chars stay
# ASCII-only so byte-level tokenizers can never be steered into an
# invalid UTF-8 sequence mid-string; non-ASCII content remains
# expressible through \uXXXX escapes.
_STR_PLAIN = ("class",
              frozenset(chr(o) for o in range(0x20, 0x7F)
                        if o not in (0x22, 0x5C)), False)
_STR_ESC = ("cat", [("lit", "\\"), ("alt", [
    ("class", frozenset('"\\/bfnrt'), False),
    ("cat", [("lit", "u")] + [("class", _HEX, False)] * 4),
])])
_STR_CHAR = ("alt", [_STR_PLAIN, _STR_ESC])

_NUMBER_RE = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?"
_INTEGER_RE = r"-?(0|[1-9][0-9]*)"


def _string_ast(schema: dict):
    if "pattern" in schema:
        body = _regex_ast(str(schema["pattern"]))
        return ("cat", [("lit", '"'), body, ("lit", '"')])
    lo = int(schema.get("minLength", 0))
    hi = schema.get("maxLength")
    if hi is None:
        body = ("star", _STR_CHAR) if lo == 0 \
            else ("cat", [("rep", _STR_CHAR, lo, lo), ("star", _STR_CHAR)])
    else:
        hi = int(hi)
        if hi > _MAX_REPEAT:
            raise GrammarError(
                f"maxLength {hi} exceeds grammar cap {_MAX_REPEAT}")
        if lo > hi:
            raise GrammarError(f"minLength {lo} > maxLength {hi}")
        body = ("rep", _STR_CHAR, lo, hi)
    return ("cat", [("lit", '"'), body, ("lit", '"')])


def _array_ast(schema: dict, depth: int):
    item = _schema_ast(schema.get("items", {}), depth + 1)
    lo = int(schema.get("minItems", 0))
    hi = schema.get("maxItems")
    if hi is not None:
        hi = int(hi)
        if hi > _MAX_REPEAT:
            raise GrammarError(
                f"maxItems {hi} exceeds grammar cap {_MAX_REPEAT}")
        if lo > hi:
            raise GrammarError(f"minItems {lo} > maxItems {hi}")
    more = ("cat", [("lit", ","), item])
    if lo == 0:
        if hi == 0:
            inner = ("cat", [])
        else:
            tail = ("star", more) if hi is None \
                else ("rep", more, 0, hi - 1)
            inner = ("opt", ("cat", [item, tail]))
    else:
        tail = ("star", more) if hi is None \
            else ("rep", more, lo - 1, hi - 1)
        inner = ("cat", [item, ("rep", more, lo - 1, lo - 1)]) \
            if hi is not None and hi == lo else ("cat", [item, tail])
    return ("cat", [("lit", "["), inner, ("lit", "]")])


def _object_ast(schema: dict, depth: int):
    props = schema.get("properties", {})
    if not isinstance(props, dict):
        raise GrammarError("object 'properties' must be a mapping")
    required = schema.get("required")
    # OpenAI structured-output convention: with no explicit required
    # list every declared property is required (deterministic output
    # order, no exponential optional expansion in the common case)
    req = set(props) if required is None else set(required)
    unknown = req - set(props)
    if unknown:
        raise GrammarError(f"required names undeclared properties: "
                           f"{sorted(unknown)}")
    members, optional = [], []
    for name, sub in props.items():
        member = ("cat", [_json_literal(str(name)), ("lit", ":"),
                          _schema_ast(sub, depth + 1)])
        members.append(member)
        optional.append(name not in req)
    return ("cat", [("lit", "{"), ("objseq", members, optional),
                    ("lit", "}")])


def _value_ast(depth_budget: int):
    """Generic JSON value, structurally bounded to ``depth_budget``
    nesting levels (the json_object builtin)."""
    scalar = ("alt", [_string_ast({}), _regex_ast(_NUMBER_RE),
                      _lit_str("true"), _lit_str("false"),
                      _lit_str("null")])
    if depth_budget <= 0:
        return scalar
    inner = _value_ast(depth_budget - 1)
    member = ("cat", [_string_ast({}), ("lit", ":"), inner])
    obj = ("cat", [("lit", "{"),
                   ("opt", ("cat", [member,
                                    ("star", ("cat", [("lit", ","),
                                                      member]))])),
                   ("lit", "}")])
    arr = ("cat", [("lit", "["),
                   ("opt", ("cat", [inner,
                                    ("star", ("cat", [("lit", ","),
                                                      inner]))])),
                   ("lit", "]")])
    return ("alt", [scalar, obj, arr])


def _json_object_ast(depth_budget: int = 2):
    """Top level of the ``json_object`` builtin: any JSON object,
    structurally bounded to two levels of nesting below the root —
    deeper nesting multiplies DFA states ~4x per level (depth 3 alone
    exceeds the default 512-state cap), and mask rows cost O(vocab)
    device bytes each."""
    inner = _value_ast(depth_budget - 1)
    member = ("cat", [_string_ast({}), ("lit", ":"), inner])
    return ("cat", [("lit", "{"),
                    ("opt", ("cat", [member,
                                     ("star", ("cat", [("lit", ","),
                                                       member]))])),
                    ("lit", "}")])


def _schema_ast(schema, depth: int = 0):
    if depth > _MAX_SCHEMA_DEPTH:
        raise GrammarError(
            f"schema nesting exceeds {_MAX_SCHEMA_DEPTH} levels")
    if schema is True or schema == {}:
        return _value_ast(2)
    if not isinstance(schema, dict):
        raise GrammarError(f"schema must be an object, got "
                           f"{type(schema).__name__}")
    if "$ref" in schema or "$defs" in schema or "definitions" in schema:
        raise GrammarError("$ref / $defs schemas are not supported "
                           "(inline the referenced schema)")
    if "const" in schema:
        return _json_literal(schema["const"])
    if "enum" in schema:
        values = schema["enum"]
        if not isinstance(values, list) or not values:
            raise GrammarError("enum must be a non-empty list")
        return ("alt", [_json_literal(v) for v in values])
    for comb in ("anyOf", "oneOf"):
        if comb in schema:
            branches = schema[comb]
            if not isinstance(branches, list) or not branches:
                raise GrammarError(f"{comb} must be a non-empty list")
            return ("alt", [_schema_ast(b, depth + 1) for b in branches])
    t = schema.get("type")
    if isinstance(t, list):
        if not t:
            raise GrammarError("empty type list")
        return ("alt", [_schema_ast({**schema, "type": one}, depth)
                        for one in t])
    if t == "string":
        return _string_ast(schema)
    if t == "number":
        return _regex_ast(_NUMBER_RE)
    if t == "integer":
        return _regex_ast(_INTEGER_RE)
    if t == "boolean":
        return ("alt", [_lit_str("true"), _lit_str("false")])
    if t == "null":
        return _lit_str("null")
    if t == "object" or (t is None and "properties" in schema):
        return _object_ast(schema, depth)
    if t == "array":
        return _array_ast(schema, depth)
    if t is None:
        return _value_ast(2)
    raise GrammarError(f"unsupported schema type {t!r}")


# ---------------------------------------------------------------------------
# NFA (Thompson) and subset-construction DFA
# ---------------------------------------------------------------------------

class _NFA:
    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset, bool, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def link(self, a: int, b: int) -> None:
        self.eps[a].append(b)

    def edge(self, a: int, chars: frozenset, neg: bool, b: int) -> None:
        self.edges[a].append((chars, neg, b))


def _nfa_build(node, nfa: _NFA) -> tuple[int, int]:
    kind = node[0]
    if kind == "lit" or kind == "cls-lit":
        s, e = nfa.state(), nfa.state()
        nfa.edge(s, frozenset((node[1],)), False, e)
        return s, e
    if kind == "class":
        s, e = nfa.state(), nfa.state()
        nfa.edge(s, node[1], node[2], e)
        return s, e
    if kind == "cat":
        s = e = nfa.state()
        for item in node[1]:
            fs, fe = _nfa_build(item, nfa)
            nfa.link(e, fs)
            e = fe
        return s, e
    if kind == "alt":
        s, e = nfa.state(), nfa.state()
        for item in node[1]:
            fs, fe = _nfa_build(item, nfa)
            nfa.link(s, fs)
            nfa.link(fe, e)
        return s, e
    if kind == "star" or kind == "plus" or kind == "opt":
        fs, fe = _nfa_build(node[1], nfa)
        s, e = nfa.state(), nfa.state()
        nfa.link(s, fs)
        nfa.link(fe, e)
        if kind != "plus":
            nfa.link(s, e)
        if kind != "opt":
            nfa.link(fe, fs)
        return s, e
    if kind == "rep":
        _, item, m, n = node
        parts = [item] * m
        if n is None:
            parts.append(("star", item))
        else:
            parts.extend([("opt", item)] * (n - m))
        return _nfa_build(("cat", parts), nfa)
    if kind == "objseq":
        # Linear construction for a property sequence with optional
        # members: two rails of join states — first[i] (nothing emitted
        # yet, no comma needed) and rest[i] (comma before the next
        # member).  Each member fragment is built exactly once.
        members, optional = node[1], node[2]
        n = len(members)
        first = [nfa.state() for _ in range(n + 1)]
        rest = [nfa.state() for _ in range(n + 1)]
        for i, member in enumerate(members):
            fs, fe = _nfa_build(member, nfa)
            nfa.link(first[i], fs)
            comma_s, comma_e = nfa.state(), nfa.state()
            nfa.edge(comma_s, frozenset(","), False, comma_e)
            nfa.link(rest[i], comma_s)
            nfa.link(comma_e, fs)
            nfa.link(fe, rest[i + 1])
            if optional[i]:
                nfa.link(first[i], first[i + 1])
                nfa.link(rest[i], rest[i + 1])
        end = nfa.state()
        nfa.link(first[n], end)
        nfa.link(rest[n], end)
        return first[0], end
    raise GrammarError(f"internal: unknown AST node {kind!r}")


def _eps_closure(nfa: _NFA, states: frozenset) -> frozenset:
    stack, seen = list(states), set(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


_OTHER = "\x00OTHER"   # sentinel symbol: any char outside the explicit set


def _class_matches(chars: frozenset, neg: bool, symbol: str) -> bool:
    if symbol is _OTHER:
        return neg           # a char no class names explicitly
    return (symbol in chars) != neg


@dataclass
class _DFA:
    trans: list[dict]        # per state: symbol -> next state
    accepting: list[bool]
    explicit: frozenset      # chars with their own column; rest = OTHER


def _to_dfa(ast, max_states: int) -> _DFA:
    nfa = _NFA()
    start, end = _nfa_build(ast, nfa)
    explicit: set[str] = set()
    for edges in nfa.edges:
        for chars, _neg, _dst in edges:
            explicit |= chars
    symbols = sorted(explicit) + [_OTHER]

    start_set = _eps_closure(nfa, frozenset((start,)))
    index = {start_set: 0}
    order = [start_set]
    trans: list[dict] = [{}]
    accepting = [end in start_set]
    i = 0
    while i < len(order):
        cur = order[i]
        for sym in symbols:
            nxt = set()
            for s in cur:
                for chars, neg, dst in nfa.edges[s]:
                    if _class_matches(chars, neg, sym):
                        nxt.add(dst)
            if not nxt:
                continue
            closed = _eps_closure(nfa, frozenset(nxt))
            if closed not in index:
                if len(index) >= max_states:
                    raise GrammarError(
                        f"grammar exceeds {max_states} DFA states — "
                        "simplify the schema or raise "
                        "grammar_max_states")
                index[closed] = len(order)
                order.append(closed)
                trans.append({})
                accepting.append(end in closed)
            trans[i][sym] = index[closed]
        i += 1
    return _DFA(trans=trans, accepting=accepting,
                explicit=frozenset(explicit))


# ---------------------------------------------------------------------------
# Tokenizer lowering: char DFA -> per-state vocab mask + transition table
# ---------------------------------------------------------------------------

def _token_strings(tokenizer) -> list:
    """Per-id surface strings (None = never usable: specials, empty or
    undecodable ids).  Cached on the tokenizer object — one pass per
    process per tokenizer, shared by every grammar."""
    cached = getattr(tokenizer, "_grammar_token_strings", None)
    if cached is not None:
        return cached
    V = int(tokenizer.vocab_size)
    special = set()
    for name in ("bos_token_id", "eos_token_id", "pad_token_id",
                 "unk_token_id"):
        tid = getattr(tokenizer, name, None)
        if tid is not None:
            special.add(int(tid))
    out: list = [None] * V
    from kaito_tpu.engine.tokenizer import ByteTokenizer
    if isinstance(tokenizer, ByteTokenizer):
        for i in range(min(256, V)):
            out[i] = chr(i)      # latin-1 identity: byte i <-> char i
    else:
        for i in range(V):
            if i in special:
                continue
            try:
                s = tokenizer.decode([i])
            except Exception:
                continue
            if s and "�" not in s:
                out[i] = s
    for tid in special:
        if 0 <= tid < V:
            out[tid] = None
    try:
        tokenizer._grammar_token_strings = out
    except Exception:
        pass
    return out


def _token_trie(tokenizer) -> dict:
    """Trie over token strings: char -> [child, ids_ending_here]."""
    cached = getattr(tokenizer, "_grammar_token_trie", None)
    if cached is not None:
        return cached
    root: dict = {}
    for tid, s in enumerate(_token_strings(tokenizer)):
        if not s:
            continue
        node, entry = root, None
        for ch in s:
            entry = _trie_child(node, ch)
            node = entry[0]
        entry[1].append(tid)
    try:
        tokenizer._grammar_token_trie = root
    except Exception:
        pass
    return root


def _trie_child(node: dict, ch: str):
    child = node.get(ch)
    if child is None:
        child = [{}, []]
        node[ch] = child
    return child


@dataclass
class CompiledGrammar:
    """A schema lowered against one tokenizer.  ``allow``/``nxt`` are
    dense [n_states, V]; state 0 is the start state; EOS is allowed
    exactly in accepting states (and leaves the state unchanged)."""

    key: str
    kind: str
    allow: np.ndarray            # [R, V] bool
    nxt: np.ndarray              # [R, V] int32
    accepting: np.ndarray        # [R] bool
    eos_id: int
    compile_seconds: float
    _mask_f32: Optional[np.ndarray] = field(default=None, repr=False)

    @property
    def n_states(self) -> int:
        return int(self.allow.shape[0])

    @property
    def vocab_size(self) -> int:
        return int(self.allow.shape[1])

    def allows(self, state: int, token: int) -> bool:
        return bool(self.allow[state, token])

    def advance(self, state: int, token: int) -> int:
        """Host-side single-token step (mirrors the device gather)."""
        if not self.allow[state, token]:
            return state       # disallowed/EOS: state is frozen
        return int(self.nxt[state, token])

    def accepts(self, state: int) -> bool:
        return bool(self.accepting[state])

    def mask_rows_f32(self) -> np.ndarray:
        """[R, V] float32 of 0 / -inf, built once per compile."""
        if self._mask_f32 is None:
            m = np.where(self.allow, np.float32(0.0),
                         np.float32(-np.inf)).astype(np.float32)
            self._mask_f32 = m
        return self._mask_f32

    def validate_text(self, text: str) -> bool:
        """Whole-string acceptance by the char DFA (test helper)."""
        return _dfa_accepts(self._dfa, text) if self._dfa is not None \
            else True

    _dfa: Optional[_DFA] = field(default=None, repr=False)


def _dfa_accepts(dfa: _DFA, text: str) -> bool:
    q = 0
    for ch in text:
        sym = ch if ch in dfa.explicit else _OTHER
        q = dfa.trans[q].get(sym)
        if q is None:
            return False
    return dfa.accepting[q]


def compile_grammar(kind: str, source: str, tokenizer,
                    max_states: int = 512) -> CompiledGrammar:
    """Compile a grammar spec into token tables for ``tokenizer``.

    kind: "json_schema" (source = canonical schema JSON),
    "json_object" (source ignored) or "regex" (source = pattern)."""
    t0 = time.perf_counter()
    if kind == "json_schema":
        try:
            schema = json.loads(source)
        except json.JSONDecodeError as e:
            raise GrammarError(f"schema is not valid JSON: {e}") from None
        ast = _schema_ast(schema)
    elif kind == "json_object":
        ast = _json_object_ast()
    elif kind == "regex":
        ast = _regex_ast(source)
    else:
        raise GrammarError(f"unknown grammar kind {kind!r}")
    dfa = _to_dfa(ast, max_states)

    V = int(tokenizer.vocab_size)
    eos_id = int(getattr(tokenizer, "eos_token_id", V - 1))
    R = len(dfa.trans)
    allow = np.zeros((R, V), dtype=bool)
    nxt = np.zeros((R, V), dtype=np.int32)
    trie = _token_trie(tokenizer)

    for q in range(R):
        # DFS the token trie in lockstep with the char DFA: every trie
        # node reachable without hitting a dead transition marks its
        # finishing tokens as allowed from q
        stack = [(trie, q)]
        while stack:
            node, s = stack.pop()
            for ch, (child, ids) in node.items():
                sym = ch if ch in dfa.explicit else _OTHER
                s2 = dfa.trans[s].get(sym)
                if s2 is None:
                    continue
                for tid in ids:
                    allow[q, tid] = True
                    nxt[q, tid] = s2
                if child:
                    stack.append((child, s2))
        if dfa.accepting[q]:
            allow[q, eos_id] = True
            nxt[q, eos_id] = q

    # every token-reachable state must offer at least one token, or a
    # constrained row would see an all--inf mask (NaN sampling): prune
    # by rejecting the grammar outright — this only fires when the
    # tokenizer cannot spell some required character
    reach, stack = {0}, [0]
    while stack:
        q = stack.pop()
        if not allow[q].any():
            raise GrammarError(
                "grammar has a dead end: some required output cannot be "
                "spelled with this tokenizer's vocabulary")
        for s2 in np.unique(nxt[q][allow[q]]):
            if int(s2) not in reach:
                reach.add(int(s2))
                stack.append(int(s2))

    key = grammar_key(kind, source)
    return CompiledGrammar(key=key, kind=kind, allow=allow, nxt=nxt,
                           accepting=np.asarray(dfa.accepting, dtype=bool),
                           eos_id=eos_id,
                           compile_seconds=time.perf_counter() - t0,
                           _dfa=dfa)


def grammar_key(kind: str, source: str) -> str:
    h = hashlib.sha256()
    h.update(kind.encode())
    h.update(b"\0")
    h.update(source.encode())
    return h.hexdigest()[:32]


# ---------------------------------------------------------------------------
# Request-surface helpers (used by server.py, jax-free)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class GrammarSpec:
    """A validated, canonicalized grammar request (pre-compilation)."""
    kind: str      # "json_schema" | "json_object" | "regex"
    source: str    # canonical payload ("" for json_object)

    @property
    def key(self) -> str:
        return grammar_key(self.kind, self.source)


def canonical_schema(schema) -> str:
    """Canonical JSON text for hashing/caching (sorted keys would break
    property-order semantics, so only separators are normalized)."""
    text = json.dumps(schema, separators=(",", ":"), ensure_ascii=True)
    if len(text.encode()) > MAX_SCHEMA_BYTES:
        raise GrammarError(
            f"schema too large: {len(text.encode())} bytes > "
            f"{MAX_SCHEMA_BYTES}")
    return text


def spec_from_response_format(rf) -> Optional[GrammarSpec]:
    """Parse an OpenAI ``response_format`` body into a GrammarSpec.
    Returns None for type=text; raises GrammarError on anything
    malformed (typed 400 in the server)."""
    if rf is None:
        return None
    if not isinstance(rf, dict):
        raise GrammarError("response_format must be an object")
    rtype = rf.get("type")
    if rtype in (None, "text"):
        return None
    if rtype == "json_object":
        return GrammarSpec(kind="json_object", source="")
    if rtype == "json_schema":
        js = rf.get("json_schema")
        if not isinstance(js, dict):
            raise GrammarError(
                "response_format.json_schema must be an object")
        schema = js.get("schema")
        if not isinstance(schema, (dict, bool)):
            raise GrammarError(
                "response_format.json_schema.schema must be an object")
        return GrammarSpec(kind="json_schema",
                           source=canonical_schema(schema))
    if rtype == "regex":
        pattern = rf.get("regex") or rf.get("pattern")
        if not isinstance(pattern, str) or not pattern:
            raise GrammarError("response_format.regex must be a "
                               "non-empty string")
        return GrammarSpec(kind="regex", source=pattern)
    raise GrammarError(f"unknown response_format.type {rtype!r} "
                       "(expected text, json_object, json_schema or "
                       "regex)")


def tool_envelope_schema(tools: list, names: Optional[list] = None) -> dict:
    """JSON schema for a forced tool call: ``{"name": ..., "arguments":
    {...}}``.  ``names`` restricts to a subset (the named tool_choice);
    None allows any declared tool (tool_choice=required)."""
    branches = []
    for tool in tools:
        fn = tool.get("function", tool) if isinstance(tool, dict) else {}
        name = fn.get("name")
        if not name or (names is not None and name not in names):
            continue
        params = fn.get("parameters")
        if not isinstance(params, (dict, bool)) or params in (True, {}):
            params = {"type": "object", "properties": {}}
        branches.append({
            "type": "object",
            "properties": {"name": {"const": name}, "arguments": params},
            "required": ["name", "arguments"],
        })
    if not branches:
        raise GrammarError("tool_choice names no declared tool")
    return branches[0] if len(branches) == 1 else {"anyOf": branches}


# ---------------------------------------------------------------------------
# GrammarCache: bounded LRU of compiled grammars, keyed by schema hash
# ---------------------------------------------------------------------------

_COMPILE_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


class GrammarCache:
    """Thread-safe bounded LRU.  Compilation happens under a per-key
    build lock in the REQUEST thread (never the scheduler step thread);
    concurrent requests for the same schema compile once."""

    def __init__(self, entries: int = 64, max_states: int = 512):
        self.entries = max(1, int(entries))
        self.max_states = int(max_states)
        self._lru: OrderedDict[str, CompiledGrammar] = OrderedDict()
        self._lock = threading.Lock()
        self._building: dict[str, threading.Event] = {}
        # exposition-ready stats (metrics.py wraps these; kept as plain
        # numbers so this module stays importable without the registry)
        self.hits_total = 0
        self.misses_total = 0
        self.evictions_total = 0
        self.requests_total = 0       # constrained requests admitted
        self.compile_count = 0
        self.compile_sum_seconds = 0.0
        self.compile_bucket_counts = [0] * (len(_COMPILE_BUCKETS) + 1)
        self.compile_buckets = _COMPILE_BUCKETS

    @property
    def touched(self) -> bool:
        """True once any constrained request has hit this cache — the
        metrics gate (exposition stays byte-identical until then)."""
        return (self.hits_total + self.misses_total
                + self.requests_total) > 0

    def _observe_compile(self, seconds: float) -> None:
        self.compile_count += 1
        self.compile_sum_seconds += seconds
        for i, edge in enumerate(self.compile_buckets):
            if seconds <= edge:
                self.compile_bucket_counts[i] += 1
                return
        self.compile_bucket_counts[-1] += 1

    def get(self, spec: GrammarSpec, tokenizer) -> CompiledGrammar:
        key = spec.key
        while True:
            with self._lock:
                hit = self._lru.get(key)
                if hit is not None:
                    self._lru.move_to_end(key)
                    self.hits_total += 1
                    return hit
                ev = self._building.get(key)
                if ev is None:
                    self._building[key] = threading.Event()
                    self.misses_total += 1
                    break
            ev.wait(timeout=30.0)
        try:
            g = compile_grammar(spec.kind, spec.source, tokenizer,
                                max_states=self.max_states)
        except BaseException:
            with self._lock:
                self._building.pop(key).set()
            raise
        with self._lock:
            self._observe_compile(g.compile_seconds)
            self._lru[key] = g
            self._lru.move_to_end(key)
            while len(self._lru) > self.entries:
                self._lru.popitem(last=False)
                self.evictions_total += 1
            self._building.pop(key).set()
        return g

    def stats(self) -> dict:
        with self._lock:
            return {
                "grammar_cache_hits_total": self.hits_total,
                "grammar_cache_misses_total": self.misses_total,
                "grammar_cache_evictions_total": self.evictions_total,
                "grammar_requests_total": self.requests_total,
                "grammar_cache_entries": len(self._lru),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)


# ---------------------------------------------------------------------------
# GrammarTable: packed device-table row allocator (engine-side)
# ---------------------------------------------------------------------------

class GrammarTable:
    """Packs the 0/-inf mask rows and transition rows of every live
    grammar into one pair of host arrays, ready for a single device
    upload.  Row 0 is the reserved unconstrained row: an all-zero mask
    and an all-zero transition row, so unconstrained slots gather a
    no-op and self-loop at state 0 forever.  Spans are refcounted per
    grammar key; zero-ref spans stay resident (warm for the next
    request with the same schema) until capacity pressure repacks the
    table.  ``version`` bumps whenever row content or layout changes —
    the engine re-uploads and remaps slot states when it observes a new
    version."""

    def __init__(self, vocab_size: int, initial_rows: int = 64):
        self.V = int(vocab_size)
        cap = 1
        while cap < max(2, initial_rows):
            cap *= 2
        self.mask = np.zeros((cap, self.V), dtype=np.float32)
        self.trans = np.zeros((cap, self.V), dtype=np.int32)
        self.used = 1                       # row 0 reserved
        self.spans: dict[str, list] = {}    # key -> [base, n_rows, refs]
        self.version = 1

    @property
    def capacity(self) -> int:
        return int(self.mask.shape[0])

    def _install(self, g: CompiledGrammar) -> int:
        n = g.n_states
        Vg = int(g.allow.shape[1])
        if Vg > self.V:
            raise GrammarError(
                f"grammar vocab {Vg} exceeds model vocab {self.V}")
        if self.used + n > self.capacity:
            self._repack(extra=n)
        base = self.used
        # the grammar is compiled at tokenizer vocab, which may be
        # narrower than the model's logits row: columns the tokenizer
        # never produces are disallowed (-inf) and self-loop
        self.mask[base:base + n, :Vg] = g.mask_rows_f32()
        self.mask[base:base + n, Vg:] = -np.inf
        # transitions are stored pre-offset (absolute row indices) so
        # the device advance is one gather with no base-add
        self.trans[base:base + n, :Vg] = g.nxt + base
        # padded columns self-loop (they are unreachable under the
        # -inf mask; this is belt-and-suspenders)
        self.trans[base:base + n, Vg:] = np.arange(
            base, base + n, dtype=np.int32)[:, None]
        self.used += n
        self.spans[g.key] = [base, n, 0]
        self.version += 1
        return base

    def _repack(self, extra: int) -> None:
        live = {k: v for k, v in self.spans.items() if v[2] > 0}
        need = 1 + sum(v[1] for v in live.values()) + extra
        cap = self.capacity
        while cap < need:
            cap *= 2
        mask = np.zeros((cap, self.V), dtype=np.float32)
        trans = np.zeros((cap, self.V), dtype=np.int32)
        used = 1
        new_spans: dict[str, list] = {}
        for key, (base, n, refs) in live.items():
            mask[used:used + n] = self.mask[base:base + n]
            trans[used:used + n] = (self.trans[base:base + n]
                                    - base + used)
            new_spans[key] = [used, n, refs]
            used += n
        self.mask, self.trans = mask, trans
        self.used, self.spans = used, new_spans
        self.version += 1

    def acquire(self, g: CompiledGrammar) -> int:
        """Pin a grammar's rows; returns the base row index."""
        span = self.spans.get(g.key)
        if span is None:
            base = self._install(g)
            span = self.spans[g.key]
        span[2] += 1
        return span[0]

    def release(self, key: str) -> None:
        span = self.spans.get(key)
        if span is not None and span[2] > 0:
            span[2] -= 1

    def base_of(self, key: str) -> int:
        return self.spans[key][0]


@dataclass
class GrammarSlot:
    """Per-slot host mirror of the device grammar state."""
    grammar: CompiledGrammar
    base: int          # table base row at the table version below
    state: int = 0     # local DFA state (absolute row = base + state)
    version: int = 0   # GrammarTable.version this base was read at

    def advance(self, token: int) -> None:
        self.state = self.grammar.advance(self.state, token)

    def allows(self, token: int) -> bool:
        return self.grammar.allows(self.state, token)

    def accepting(self) -> bool:
        return self.grammar.accepts(self.state)
