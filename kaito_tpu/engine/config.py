"""Engine configuration.

The serving-side contract the reference exposes through vLLM flags +
the KAITO config file (``inference_api.py:64-160`` merges
``--kaito-config-file`` YAML over the vLLM arg surface).  Our config is
a dataclass consumed by the engine, the scheduler and the HTTP server;
the workload generator renders it into the pod command line.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional


@dataclass
class EngineConfig:
    model: str = "tiny-llama-test"      # preset name or HF id
    max_model_len: int = 0               # 0 = model's own limit, capped by HBM
    page_size: int = 64                  # KV tokens per page
    max_num_seqs: int = 8                # concurrent decode slots
    max_pages: int = 0                   # 0 = derive from HBM budget
    max_prefill_tokens: int = 512        # prefill chunk budget per step
    prefill_interleave: int = 2          # decode steps between prefill chunks
    # packed multi-sequence prefill (docs/prefill.md): the per-step
    # prefill budget above becomes an AGGREGATE token budget spread over
    # a PACK of staged slots (segment packing for fresh prompts,
    # batch-axis packing for same-bucket context chunks), so concurrent
    # arrivals stop serializing at batch 1.  0 = auto (pack up to
    # max_num_seqs); 1 reproduces the serial round-robin scheduler
    # byte-identically.
    prefill_pack: int = 0
    prefill_buckets: tuple[int, ...] = (128, 256, 512, 1024, 2048, 4096)
    dtype: str = "bfloat16"
    # KV page-pool dtype: "bfloat16" | "float32" | "int8".  int8 stores
    # quantized codes plus per-page-per-head fp32 scales (kv_cache.py):
    # ~2x pages at equal HBM and half the decode-step KV read.
    kv_dtype: str = "bfloat16"
    # weight-only quantization: "" (off) | "int8" (per-out-channel
    # symmetric) | "int4" (packed two-per-byte, per-group g=128
    # per-out-channel scales; fused Pallas dequant matmul on TPU —
    # docs/quantization.md).  Decode is param-bandwidth-bound, so
    # halving/quartering weight bytes is a direct throughput lever;
    # the reference's vLLM surface exposes the same knob as
    # --quantization.
    quantization: str = ""
    seed: int = 0
    tensor_parallel: int = 1             # TP degree (mesh "tensor" axis)
    expert_parallel: int = 1             # EP degree (mesh "expert" axis)
    pipeline_parallel: int = 1           # PP stages (mesh "pipeline" axis)
    # context-parallel prefill (mesh "sequence" axis): long prompts run
    # as ONE ring-attention prefill sharded over the sequence axis
    # instead of serial chunks — TTFT scales ~1/sequence_parallel while
    # decode stays TP (the KV pool is replicated over the axis).
    sequence_parallel: int = 1
    cp_min_tokens: int = 2048            # prompts >= this take the CP path
    cp_q_tile: int = 1024                # ring query tile (memory bound)
    pp_microbatches: int = 4             # decode microbatches through the ring
    data_parallel: int = 1               # engine replica groups
    use_pallas: Optional[bool] = None    # None = auto (TPU yes, CPU no)
    # fused decode steps per dispatch when the batch is in steady-state
    # decode (no prefills staged, queue empty): one lax.scan dispatch
    # runs K steps with on-device sampling + stop detection, amortizing
    # the per-step host round-trip.  None = auto (8 on TPU, 1 elsewhere)
    decode_run_ahead: Optional[int] = None
    # fused decode steps per dispatch while requests are waiting or
    # prefilling (the sustained-admission regime).  Smaller than
    # decode_run_ahead so admissions and prefill chunks keep a bounded
    # latency; 0 restores the round-2 collapse-to-single-step behavior
    fused_under_load: int = 4
    # zero-bubble decode loop (docs/decode-loop.md): device-resident
    # loop state plus a two-deep dispatch pipeline that overlaps host
    # postprocess (stop replay, streaming, scheduling) with device
    # compute.  None = follow KAITO_ASYNC_DISPATCH (off when unset);
    # True/False force it.  Off keeps the synchronous loop
    # byte-identical to before (no new metric families).
    async_dispatch: Optional[bool] = None
    # collective-compute overlap for TP decode (docs/multichip.md):
    # decompose the row-parallel projections' output all-reduce into
    # pipelined reduce-scatter + all-gather ring hops (ppermute), each
    # overlapped with the next chunk's partial matmul, and stream the
    # next layer's quantized slab into VMEM while the hops drain.
    # None = follow KAITO_COMM_OVERLAP (off when unset); True/False
    # force it.  Off keeps dispatch, numerics and the /metrics
    # exposition byte-identical; the gate only ever engages on a
    # TP>=2 mesh (never PP/single-chip, never prefill).
    comm_overlap: Optional[bool] = None
    # n-gram (prompt-lookup) speculative decoding: propose up to N
    # continuation tokens by matching the trailing n-gram against the
    # sequence's own context, verify them in ONE windowed dispatch, and
    # emit the accepted prefix + a bonus token — exact greedy
    # equivalence, no draft model.  0 = off.  Engages only when every
    # active slot is greedy and the batch is at most
    # speculative_max_batch (the [B, W, V] verify logits stay small;
    # speculation pays off in the low-batch latency regime anyway).
    speculative_ngram: int = 0
    speculative_min_match: int = 2
    speculative_max_batch: int = 8
    # draft-model speculative decoding (docs/speculative.md): a small
    # co-resident draft preset proposes up to speculative_draft_k
    # tokens per slot, the target verifies the window in one forward,
    # and Leviathan rejection sampling keeps sampled traffic
    # distribution-identical (greedy stays bit-exact).  A per-slot
    # accept-rate controller adapts the depth and falls back to the
    # n-gram proposer (then plain decode) on sustained-poor acceptance.
    # "" = off; the value names a catalog preset sharing the target's
    # tokenizer (validated at load).
    speculative_draft: str = ""
    speculative_draft_k: int = 4
    speculative_draft_weights_dir: str = ""   # "" = synthetic weights
    # serving-side knobs carried over from the reference wrapper surface
    port: int = 5000
    served_model_name: str = ""
    adapters_dir: str = ""               # LoRA adapter discovery dir
    # dynamic multi-LoRA serving (docs/multi-lora.md): a fixed-capacity
    # HBM slot table of stacked adapter factors sized [L, slots+1, in,
    # rmax] at boot, so hot-loading an adapter over /v1/adapters is an
    # in-place buffer write — zero recompiles — and eviction demotes to
    # a host-RAM LRU tier that faults back in on the next request.
    # 0 = off: the static boot-discovery path (and the /v1/adapters 403,
    # the metrics exposition) stay byte-identical to before.
    adapter_slots: int = 0
    adapter_rmax: int = 16               # max servable adapter rank
    adapter_host_bytes: int = 256 << 20  # host-RAM overflow tier budget
    # base-model mismatch is load-REFUSAL (counted as
    # kaito:adapter_load_failures_total{reason="base_mismatch"}) unless
    # this escape hatch is set — serving wrong-base deltas silently was
    # the old (round-1) warning behavior
    adapter_allow_base_mismatch: bool = False
    # comma-separated URL/scheme prefixes POST /v1/adapters may pull
    # from ("" = local paths only, same trust model as
    # pd_source_allowlist)
    adapter_source_allowlist: str = ""
    weights_dir: str = ""                # safetensors checkpoint dir ("" = synthetic)
    disable_rate_limit: bool = False
    enable_prefix_caching: bool = True   # native radix-tree prefix reuse
    host_kv_offload_bytes: int = 0       # host-RAM KV spill tier (0 = off)
    pd_enabled: bool = False             # P/D side-channel routes (MRI roles)
    pd_source_allowlist: str = ""        # comma URL prefixes for KV pulls
    max_queue_len: int = 256
    # cluster-wide KV pool (docs/kv-pool.md): replicas publish whole-page
    # prompt-prefix KV into a per-replica store served over the chunked
    # PD wire; the EPP aggregates adverts into a prefix->holder index
    # and either routes to the holder or tells the picked replica to
    # fetch.  Default OFF: with the pool disabled, scheduling behavior
    # and the /metrics exposition are byte-identical to before.
    kv_pool_enabled: bool = False
    kv_pool_bytes: int = 1 << 30         # host bytes for the prefix store
    kv_pool_min_tokens: int = 0          # min prefix tokens to publish
    # (0 = one KV page, i.e. page_size tokens)
    # tier-3 SSD spill under the pool (docs/kv-pool.md "Tier 3: SSD"):
    # entries evicted from the host LRU demote to a bounded slab
    # directory instead of vanishing, and pool misses probe it before
    # remote peers and before recompute.  0 = no disk tier (no spill
    # thread, no kv_tier metric families — byte-identical off).
    kv_pool_disk_bytes: int = 0
    kv_pool_disk_dir: str = ""           # "" = <tempdir>/kaito-kv-tier
    # cap /debug/kv_pool adverts to the freshest N entries per scrape
    # (0 = unlimited); the EPP treats a capped advert as authoritative
    # only for the rows it lists
    kv_pool_advert_max: int = 0
    # grammar-constrained decoding (docs/structured-output.md):
    # response_format={json_schema|json_object|regex} and forced tool
    # calls compile into token-level masks applied on device.  The
    # surface is on by default but completely pay-per-use: with no
    # constrained request in flight the decode path compiles the mask
    # branch away and the /metrics exposition is byte-identical.
    # False rejects response_format/tools-constrained requests with a
    # typed 400 (fleet operators pinning the old surface).
    structured_output: bool = True
    grammar_cache_entries: int = 64      # compiled-schema LRU entries
    # DFA state cap per grammar; each state costs O(vocab) device bytes
    # in the packed mask table, so this bounds both compile time and
    # the table footprint
    grammar_max_states: int = 512
    # multi-tenant QoS (docs/qos.md): JSON tenant-class document
    # (inline, or @path to a file) parsed by engine.qos.  "" = off —
    # one implicit tenant, legacy FIFO admission and
    # newest-preempts-first eviction, byte-identical exposition.
    qos_config: str = ""
    # failure-domain isolation (docs/failure-domains.md)
    request_timeout_s: float = 0.0       # server-default deadline (0 = off);
    # clients may tighten per request via the body's "timeout" field
    kv_shed_threshold: float = 0.0       # shed new work with 429 when KV-page
    # usage crosses this fraction while a queue exists (0 = off)
    kv_import_retries: int = 1           # transient KV-transfer failures fall
    # back to local recompute this many times before failing the request
    # observability (docs/observability.md)
    slow_request_threshold_s: float = 0.0  # dump a request's span tree to the
    # log when its end-to-end latency crosses this (0 = off)
    trace_capacity: int = 8192           # span ring-buffer entries
    timeline_capacity: int = 4096        # step flight-recorder entries
    # SLO watchdog targets (runtime/slo.py; defaults = BASELINE north
    # star).  Env vars KAITO_SLO_* override these at server start.
    slo_ttft_p50_ms: float = 200.0
    slo_ttft_p99_ms: float = 1000.0
    slo_itl_p99_ms: float = 250.0
    slo_tokens_per_sec_per_chip: float = 2000.0
    slo_availability: float = 0.999
    # true per-token inter-token latency (--itl / KAITO_ITL): stamp
    # every retired token's wall time in the emit path and feed gaps
    # into kaito:inter_token_latency_seconds + the watchdog's itl_p99
    # SLI.  Off = no stamps, no families, byte-identical exposition.
    itl_enabled: bool = False
    # serving role this replica's SLO burn attributes to ("prefill" /
    # "decode"; empty = "unified").  Set by the MRI role annotation via
    # KAITO_INFERENCE_ROLE so disaggregated pools scale on the right SLO.
    role: str = ""
    # incident flight recorder (utils/flightrec.py): directory for
    # bounded JSON bundles snapshotting every debug surface on an SLO
    # page, an engine-fatal error, or SIGTERM with in-flight requests.
    # Empty = off — no watcher thread, /debug/flight 403.
    flight_dir: str = ""
    flight_max_bundles: int = 16         # LRU by mtime beyond this
    # sampled device-time attribution (engine/devprof.py).  0 = off —
    # no sampler thread, no kaito:device_* families, /debug/device 403,
    # byte-identical exposition.  >0 captures a devprof_window_s
    # jax.profiler window every devprof_interval_s and folds it into
    # comm/compute/idle buckets + per-phase device metrics.
    devprof_interval_s: float = 0.0
    devprof_window_s: float = 0.25       # capture length per sample
    devprof_ring: int = 16               # recent windows kept for /debug/device

    def replace(self, **kw) -> "EngineConfig":
        return dataclasses.replace(self, **kw)

    @property
    def pages_per_seq(self) -> int:
        if not self.max_model_len:
            raise ValueError("max_model_len not resolved")
        return -(-self.max_model_len // self.page_size)
