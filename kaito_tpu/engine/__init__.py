"""JAX/XLA/Pallas serving engine.

The TPU-native replacement for the reference's in-pod vLLM stack
(``presets/workspace/inference/vllm/inference_api.py`` + the vendored
vLLM/Ray/NCCL container): config-driven transformer models, a paged KV
cache, continuous batching, Pallas attention kernels, and an
OpenAI-compatible HTTP front end.
"""

from kaito_tpu.engine.config import EngineConfig  # noqa: F401
