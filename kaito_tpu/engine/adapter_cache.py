"""Bounded two-tier LoRA adapter cache (docs/multi-lora.md).

The static boot path (``engine/adapters.py``) sizes its stacked buffers
from whatever the adapter directory held at startup, so "add a
fine-tune" means "restart the fleet".  This module is the dynamic
counterpart — the S-LoRA/Punica serving discipline on TPU:

- **HBM slot table** — the same stacked per-target layout the layer
  scan already consumes (``{group: {f"{t}_a": [L, S+1, in, rmax],
  f"{t}_b": [L, S+1, rmax, out]}}``, slot 0 = all-zeros base), but
  pre-allocated to a FIXED capacity of ``slots`` adapters at rank
  ``rmax``.  Hot-loading an adapter is an in-place ``at[:, slot].set``
  of its padded factors — every buffer keeps its shape, dtype and
  sharding, so the jitted decode programs can never retrace
  (pinned by a jit-cache-size assertion in tests/test_multi_lora.py).
- **Host-RAM tier** — a byte-budgeted LRU of evicted adapters' raw
  factors (same discipline as ``host_offload.HostKVPool``): an adapter
  squeezed out of HBM faults back in on its next request instead of
  requiring an operator round trip to the registry.

Correctness model: a slot referenced by any in-flight request is
PINNED — the engine supplies ``busy_fn`` and the cache refuses to
evict or overwrite a busy slot (the decode step indexes factors by
slot id; swapping one under an active sequence would silently change
its weights mid-generation).  Dropping an idle adapter is always safe:
the next request faults it back from the host tier or the admin
surface reloads it from its source.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

# load-refusal reasons (the label values of
# kaito:adapter_load_failures_total)
REASON_BASE_MISMATCH = "base_mismatch"
REASON_RANK_OVERFLOW = "rank_overflow"
REASON_UNREADABLE = "unreadable"
REASON_NO_TARGETS = "no_targets"
REASON_CAPACITY = "capacity"


class AdapterLoadError(ValueError):
    """A load the cache refused; ``reason`` is the counter label."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class AdapterBusyError(RuntimeError):
    """Eviction/overwrite refused: in-flight requests pin the slot."""


class HostAdapterEntry:
    __slots__ = ("factors", "r", "scaling", "base", "nbytes")

    def __init__(self, factors: dict, r: int, scaling: float,
                 base: str, nbytes: int):
        self.factors = factors
        self.r = r
        self.scaling = scaling
        self.base = base
        self.nbytes = nbytes


class HostAdapterTier:
    """Byte-budgeted LRU of evicted adapters' raw host factors, keyed
    by adapter name (the ``HostKVPool`` discipline: same-key overwrite
    discards first, oversize entries are refused, eviction pops the
    least-recently-used end)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self.used_bytes = 0
        self._entries: "collections.OrderedDict[str, HostAdapterEntry]" = \
            collections.OrderedDict()
        self.hits = 0          # pop() found the adapter (fault-back-in)
        self.misses = 0        # pop() came up empty (evicted/never held)
        self.evicted_entries = 0

    def __len__(self) -> int:
        return len(self._entries)

    def names(self) -> list[str]:
        return list(self._entries)

    def put(self, name: str, entry: HostAdapterEntry) -> bool:
        self.discard(name)     # same-key overwrite must not double-count
        if entry.nbytes > self.max_bytes:
            return False
        while (self.used_bytes + entry.nbytes > self.max_bytes
               and self._entries):
            _, old = self._entries.popitem(last=False)
            self.used_bytes -= old.nbytes
            self.evicted_entries += 1
        self._entries[name] = entry
        self.used_bytes += entry.nbytes
        return True

    def pop(self, name: str) -> Optional[HostAdapterEntry]:
        entry = self._entries.pop(name, None)
        if entry is not None:
            self.used_bytes -= entry.nbytes
            self.hits += 1
        else:
            self.misses += 1
        return entry

    def has(self, name: str) -> bool:
        return name in self._entries

    def discard(self, name: str) -> None:
        entry = self._entries.pop(name, None)
        if entry is not None:
            self.used_bytes -= entry.nbytes


class AdapterCache:
    """Fixed-capacity HBM slot table + host-RAM overflow tier.

    ``serve_lora`` is THE buffer tree the engine mounts at
    ``params["serve_lora"]`` — the cache mutates its leaves in place
    (functionally: each hot-load replaces a leaf with a same-shape
    ``at[].set`` result), so the engine never rebuilds its param tree
    and the decode programs never retrace.
    """

    def __init__(self, model, *, slots: int, rmax: int,
                 base_model: str = "", host_bytes: int = 0,
                 allow_base_mismatch: bool = False, mesh=None):
        if slots < 1:
            raise ValueError("adapter cache needs at least one slot")
        if rmax < 1:
            raise ValueError("adapter rmax must be positive")
        if model.is_mla:
            raise ValueError("per-request adapters are not supported on "
                             "MLA models")
        self.slots = slots
        self.rmax = rmax
        self.base_model = base_model
        self.allow_base_mismatch = allow_base_mismatch
        self._model = model
        self._mesh = mesh
        self._lock = threading.RLock()
        # engine hook: True when in-flight work references the adapter
        # (waiting queue or an active decode slot) — pinned slots are
        # never evicted or overwritten
        self.busy_fn: Callable[[str], bool] = lambda name: False
        # resident state: name -> slot (1-based; 0 is the base lane).
        # name_to_slot is handed to the engine as its adapter_index and
        # mutated IN PLACE so both sides always see the same residency.
        self.name_to_slot: dict[str, int] = {}
        self._slot_names: list[str] = [""] * (slots + 1)
        self._lru: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        self._meta: dict[str, dict] = {}
        self.host = HostAdapterTier(host_bytes) if host_bytes > 0 else None
        # counters (exposed as kaito:adapter_* when the cache is on)
        self.loads_total = 0         # installs into an HBM slot
        self.evictions_total = 0     # HBM slots reclaimed
        self.hits_total = 0          # ensure() found the adapter resident
        self.faults_total = 0        # ensure() pulled it back from host
        self.load_failures: dict[str, int] = {}
        # pre-allocate every per-request-servable target at full
        # capacity: [L, slots+1, in, rmax] / [L, slots+1, rmax, out].
        # MoE groups keep dense attention adapters only (the expert MLP
        # path has no LoRA sites) — mirrors adapters.load_adapter_stacks.
        self._specs: dict[str, dict[str, tuple[int, int]]] = {}
        serve_lora: dict = {}
        for g in model.groups:
            specs = model._layer_specs(g.moe)
            targets = (("q", "k", "v", "o") if g.moe
                       else ("q", "k", "v", "o", "gate", "up", "down"))
            group_buf: dict = {}
            gspec: dict[str, tuple[int, int]] = {}
            for t in targets:
                if t not in specs:
                    continue
                in_dim, out_dim = specs[t][0]
                gspec[t] = (in_dim, out_dim)
                group_buf[f"{t}_a"] = jnp.zeros(
                    (g.count, slots + 1, in_dim, rmax), model.dtype)
                group_buf[f"{t}_b"] = jnp.zeros(
                    (g.count, slots + 1, rmax, out_dim), model.dtype)
            if group_buf:
                self._specs[g.name] = gspec
                serve_lora[g.name] = group_buf
        if not serve_lora:
            raise ValueError("model exposes no per-request-servable "
                             "LoRA targets")
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            serve_lora = jax.device_put(serve_lora,
                                        NamedSharding(mesh, P()))
        self.serve_lora = serve_lora
        nbytes = sum(x.nbytes for b in serve_lora.values()
                     for x in b.values())
        logger.info("adapter cache: %d HBM slots (rmax=%d, %.1f MiB)%s",
                    slots, rmax, nbytes / 2**20,
                    "" if self.host is None else
                    f" + {host_bytes / 2**20:.0f} MiB host tier")

    # -- residency ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self.name_to_slot)

    def resident(self) -> list[str]:
        with self._lock:
            return list(self.name_to_slot)

    def has(self, name: str) -> bool:
        with self._lock:
            return (name in self.name_to_slot
                    or (self.host is not None and self.host.has(name)))

    def ensure(self, name: str) -> int:
        """Slot index of ``name``, faulting it back from the host tier
        if HBM evicted it.  Raises KeyError when the cache holds no
        trace of the adapter (the admin surface must re-load it)."""
        with self._lock:
            slot = self.name_to_slot.get(name)
            if slot is not None:
                self._lru.move_to_end(name)
                self.hits_total += 1
                return slot
            entry = self.host.pop(name) if self.host is not None else None
            if entry is None:
                raise KeyError(name)
            slot = self._install_locked(name, entry.factors, r=entry.r,
                                        scaling=entry.scaling,
                                        base=entry.base,
                                        validate_base=False)
            self.faults_total += 1
            return slot

    # -- loading -----------------------------------------------------------

    def _refuse(self, reason: str, message: str) -> AdapterLoadError:
        self.load_failures[reason] = self.load_failures.get(reason, 0) + 1
        logger.warning("adapter load refused (%s): %s", reason, message)
        return AdapterLoadError(reason, message)

    def load_from_path(self, name: str, path: str) -> int:
        """Load a kaito-tpu-lora-v1 artifact directory into a slot."""
        from kaito_tpu.tuning.lora import load_adapter

        try:
            adapter, cfg, base = load_adapter(path)
        except Exception as e:
            raise self._refuse(REASON_UNREADABLE,
                               f"adapter {name!r} at {path}: {e}") from None
        return self.install(name, adapter, r=cfg.r, scaling=cfg.scaling,
                            base=base)

    def install(self, name: str, factors: dict, *, r: int,
                scaling: float, base: str = "") -> int:
        """Install raw adapter factors (``{group}/{t}_lora_a`` flat keys
        or the nested trainer tree) into an HBM slot; returns the slot
        index.  Refusals raise :class:`AdapterLoadError` with a counted
        reason; a pinned-full table raises with reason "capacity"."""
        with self._lock:
            if (base and self.base_model and base != self.base_model
                    and not self.allow_base_mismatch):
                raise self._refuse(
                    REASON_BASE_MISMATCH,
                    f"adapter {name!r} targets base {base!r}, serving "
                    f"{self.base_model!r} (pass --adapter-allow-base-"
                    f"mismatch to serve it anyway)")
            if r > self.rmax:
                raise self._refuse(
                    REASON_RANK_OVERFLOW,
                    f"adapter {name!r} rank {r} exceeds the slot table's "
                    f"rmax {self.rmax} (restart with a larger "
                    f"--adapter-rmax)")
            flat = _flatten_factors(factors)
            if not any(self._factor_targets(flat)):
                raise self._refuse(
                    REASON_NO_TARGETS,
                    f"adapter {name!r} carries no per-request-servable "
                    f"targets")
            return self._install_locked(name, flat, r=r, scaling=scaling,
                                        base=base, validate_base=False)

    def _factor_targets(self, flat: dict):
        for gname, gspec in self._specs.items():
            for t in gspec:
                if f"{gname}/{t}_lora_a" in flat:
                    yield gname, t

    def _install_locked(self, name: str, factors: dict, *, r: int,
                        scaling: float, base: str,
                        validate_base: bool) -> int:
        flat = _flatten_factors(factors)
        slot = self.name_to_slot.get(name)
        if slot is not None and self.busy_fn(name):
            raise AdapterBusyError(
                f"adapter {name!r} is serving in-flight requests")
        if slot is None:
            slot = self._free_slot_locked()
        self._write_slot(slot, flat, scaling)
        prev = self._slot_names[slot]
        if prev and prev != name:
            self.name_to_slot.pop(prev, None)
            self._lru.pop(prev, None)
        self._slot_names[slot] = name
        self.name_to_slot[name] = slot
        self._lru[name] = None
        self._lru.move_to_end(name)
        self._meta[name] = {"r": r, "scaling": scaling, "base": base,
                            "nbytes": sum(np.asarray(a).nbytes
                                          for a in flat.values())}
        self.loads_total += 1
        logger.info("adapter %s -> slot %d (r=%d)", name, slot, r)
        return slot

    def _free_slot_locked(self) -> int:
        if len(self.name_to_slot) < self.slots:
            used = set(self.name_to_slot.values())
            for s in range(1, self.slots + 1):
                if s not in used:
                    return s
        # full: evict the least-recently-used adapter nobody is serving
        for victim in self._lru:
            if not self.busy_fn(victim):
                return self._evict_locked(victim)
        raise self._refuse(
            REASON_CAPACITY,
            f"all {self.slots} adapter slots pinned by in-flight "
            f"requests")

    def _evict_locked(self, name: str) -> int:
        slot = self.name_to_slot.pop(name)
        self._lru.pop(name, None)
        meta = self._meta.pop(name, {})
        self._slot_names[slot] = ""
        self.evictions_total += 1
        if self.host is not None:
            # demote the factors to the host tier so the next request
            # for this adapter faults it back instead of 404ing
            entry = HostAdapterEntry(
                factors=self._read_slot(slot, meta),
                r=int(meta.get("r", self.rmax)),
                scaling=float(meta.get("scaling", 1.0)),
                base=str(meta.get("base", "")),
                nbytes=int(meta.get("nbytes", 0)) or 1)
            self.host.put(name, entry)
        logger.info("adapter %s evicted from slot %d%s", name, slot,
                    "" if self.host is None else " (host tier)")
        return slot

    def _write_slot(self, slot: int, flat: dict, scaling: float) -> None:
        """Donate the padded factors into lane ``slot`` of every target
        buffer.  Targets the adapter does not carry are ZEROED — a
        reused slot must not leak its previous occupant's deltas.
        Every write is a same-shape ``at[].set``, so shape, dtype and
        sharding are preserved and the jit cache stays warm."""
        for gname, gspec in self._specs.items():
            buf = self.serve_lora[gname]
            for t, (in_dim, out_dim) in gspec.items():
                a = flat.get(f"{gname}/{t}_lora_a")
                b = flat.get(f"{gname}/{t}_lora_b")
                if a is not None and b is not None:
                    a = np.asarray(a, np.float32)       # [L, in, r]
                    b = np.asarray(b, np.float32)       # [L, r, out]
                    pa = np.zeros((a.shape[0], in_dim, self.rmax),
                                  np.float32)
                    pa[:, :, :a.shape[-1]] = a
                    pb = np.zeros((b.shape[0], self.rmax, out_dim),
                                  np.float32)
                    pb[:, :b.shape[1], :] = b * scaling
                else:
                    L = buf[f"{t}_a"].shape[0]
                    pa = np.zeros((L, in_dim, self.rmax), np.float32)
                    pb = np.zeros((L, self.rmax, out_dim), np.float32)
                buf[f"{t}_a"] = buf[f"{t}_a"].at[:, slot].set(
                    pa.astype(self._model.dtype))
                buf[f"{t}_b"] = buf[f"{t}_b"].at[:, slot].set(
                    pb.astype(self._model.dtype))

    def _read_slot(self, slot: int, meta: dict) -> dict:
        """Raw (unpadded, unscaled) factors of lane ``slot`` copied to
        host — what the host tier stores for fault-back-in."""
        r = int(meta.get("r", self.rmax)) or self.rmax
        scaling = float(meta.get("scaling", 1.0)) or 1.0
        out: dict = {}
        for gname, gspec in self._specs.items():
            buf = self.serve_lora[gname]
            for t in gspec:
                a = np.asarray(buf[f"{t}_a"][:, slot], np.float32)
                b = np.asarray(buf[f"{t}_b"][:, slot], np.float32)
                if not a.any() and not b.any():
                    continue
                out[f"{gname}/{t}_lora_a"] = a[:, :, :r]
                out[f"{gname}/{t}_lora_b"] = b[:, :r, :] / scaling
        return out

    # -- removal -----------------------------------------------------------

    def remove(self, name: str) -> bool:
        """Drop an adapter from BOTH tiers (the DELETE /v1/adapters
        semantics — no fault-back-in afterwards).  Returns False when
        the cache holds no trace of it; raises AdapterBusyError when
        in-flight requests pin it."""
        with self._lock:
            dropped = False
            if name in self.name_to_slot:
                if self.busy_fn(name):
                    raise AdapterBusyError(
                        f"adapter {name!r} is serving in-flight requests")
                slot = self.name_to_slot.pop(name)
                self._lru.pop(name, None)
                self._meta.pop(name, None)
                self._slot_names[slot] = ""
                self._write_slot(slot, {}, 1.0)
                self.evictions_total += 1
                dropped = True
            if self.host is not None and self.host.has(name):
                self.host.discard(name)
                dropped = True
            return dropped

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /v1/adapters`` payload (and the advert the EPP's
        adapter scraper folds into its affinity index)."""
        with self._lock:
            resident = [{"name": n, "slot": s,
                         "r": int(self._meta.get(n, {}).get("r", 0)),
                         "base": str(self._meta.get(n, {}).get("base", ""))}
                        for n, s in sorted(self.name_to_slot.items(),
                                           key=lambda kv: kv[1])]
            out = {
                "enabled": True,
                "slots": self.slots,
                "rmax": self.rmax,
                "resident": resident,
                "host_tier": (sorted(self.host.names())
                              if self.host is not None else []),
                "loads_total": self.loads_total,
                "evictions_total": self.evictions_total,
                "hits_total": self.hits_total,
                "faults_total": self.faults_total,
                "load_failures": dict(self.load_failures),
            }
            return out


def _flatten_factors(factors: dict) -> dict:
    """Accept either the flat ``{group}/{t}_lora_a`` artifact layout
    (``tuning.lora.extract_adapter``) or the nested trainer tree and
    return the flat form."""
    if all(isinstance(v, dict) for v in factors.values()) and factors:
        flat: dict = {}
        for gname, stack in factors.items():
            for k, v in stack.items():
                flat[f"{gname}/{k}"] = v
        return flat
    return dict(factors)
