"""Cluster-wide KV pool, replica-local side (docs/kv-pool.md).

Every replica keeps a byte-budgeted LRU of finished prompt prefixes —
whole-page KV slabs staged by the existing PD export machinery — keyed
by the SAME chained FNV-1a block hashes the EPP computes over request
bodies (``runtime/routing.prefix_blocks``).  The store is served over
the chunked PD wire (``/kv_pool/<key>/meta`` + ``/chunk/<i>``), and its
key set is advertised at ``/debug/kv_pool`` for the EPP's cluster-wide
prefix→holder index.  A freshly scaled-up replica can therefore fetch a
prefix another replica warmed instead of recomputing it, so warm TTFT
survives scale-out, rollout, and failover.

Correctness model: the block hashes are an INDEX, never an authority.
Chat templates, tokenizer boundary effects, and hash collisions all
mean a char-block match does not prove a token-level match — so the
pool meta response carries the entry's exact ``prompt_tokens`` and the
fetching engine trims to the longest common whole-page token prefix
before importing (``common_prefix_pages``).  Any miss, eviction, or
transfer failure degrades to the local prefill the scheduler already
has; the pool can only ever remove work, never corrupt it.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kaito_tpu.engine.pd import plan_chunks, serialize_chunk
from kaito_tpu.runtime.routing import adapter_seed, prefix_blocks

# one KV page of page_size tokens covers page_size * CHARS_PER_TOKEN
# prompt chars — the same heuristic the EPP uses to align its block
# size to the engine's page size (routing.CHARS_PER_TOKEN)
CHARS_PER_TOKEN = 4
_MASK64 = (1 << 64) - 1


def pool_block_chars(page_size: int) -> int:
    """Char-block size whose blocks line up 1:1 with KV pages."""
    return page_size * CHARS_PER_TOKEN


def prompt_pool_blocks(text: str, page_size: int,
                       adapter: str = "") -> list[int]:
    """The engine-side publisher's block hashes for a prompt.  MUST
    stay the exact chain the EPP computes (``prefix_blocks`` at
    ``kv_page_size * 4`` chars) — a silent divergence makes the global
    index useless (pinned by tests/test_kv_pool.py).  ``adapter`` seeds
    the chain so KV computed under a LoRA adapter never hash-matches
    base KV (or another adapter's) for the same text; "" keeps every
    pre-adapter chain byte-identical."""
    return prefix_blocks(text, pool_block_chars(page_size),
                         seed=adapter_seed(adapter))


def pool_key(blocks: list[int]) -> str:
    """Store key of a prefix: the chained hash of its LAST block (it
    folds every earlier block, so it names the whole prefix)."""
    return f"{blocks[-1] & _MASK64:016x}"


def meta_nbytes(meta: dict) -> int:
    """Host bytes a staged entry's chunks occupy once drained (K + V +
    fp32 scale slabs for int8 pools), from the wire meta alone."""
    dt = np.dtype(meta["dtype"])
    n = int(np.prod(meta["shape"])) * dt.itemsize
    n += int(np.prod(meta.get("v_shape", meta["shape"]))) * dt.itemsize
    if "ks_shape" in meta:
        n += (int(np.prod(meta["ks_shape"]))
              + int(np.prod(meta["vs_shape"]))) * 4
    return n


class HostExport:
    """A StagedExport-shaped serving surface over HOST arrays.

    After a fetch, the target replica replicates the imported prefix
    into its own store (so the pool heals toward N holders and the
    original holder can scale down without losing the prefix).  The
    assembled host slab is what it has; this wraps it with the same
    ``meta``/``plans``/``get_chunk`` surface the pool endpoints serve,
    serializing chunks on demand so the bytes aren't stored twice."""

    def __init__(self, k: np.ndarray, v: np.ndarray,
                 ks: Optional[np.ndarray] = None,
                 vs: Optional[np.ndarray] = None, *,
                 n_tokens: int, model: str, prompt_tokens: list[int]):
        self._k, self._v, self._ks, self._vs = k, v, ks, vs
        L, n_pages = int(k.shape[0]), int(k.shape[1])
        per_layer_page = int(np.prod(k.shape[2:])
                             + np.prod(v.shape[2:])) * k.dtype.itemsize
        if ks is not None:
            per_layer_page += int(np.prod(ks.shape[2:])
                                  + np.prod(vs.shape[2:])) * 4
        self.plans = plan_chunks(L, n_pages, per_layer_page)
        self.meta = {"shape": [int(s) for s in k.shape],
                     "v_shape": [int(s) for s in v.shape],
                     "dtype": str(k.dtype), "n_tokens": n_tokens,
                     "model": model,
                     "chunks": [p.to_json() for p in self.plans]}
        if ks is not None:
            self.meta["ks_shape"] = [int(s) for s in ks.shape]
            self.meta["vs_shape"] = [int(s) for s in vs.shape]
        self.prompt_tokens = list(prompt_tokens)
        self.first_token = -1

    @property
    def n_chunks(self) -> int:
        return len(self.plans)

    def ensure_draining(self) -> None:
        """Parity with StagedExport — the bytes are already on host."""

    def get_chunk(self, i: int, timeout: float = 60.0,
                  consume: bool = False) -> bytes:
        if not 0 <= i < len(self.plans):
            raise IndexError(f"chunk {i} out of range ({len(self.plans)})")
        p = self.plans[i]
        k = self._k[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
        v = self._v[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
        ks = vs = None
        if self._ks is not None:
            ks = self._ks[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
            vs = self._vs[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
        return serialize_chunk(np.ascontiguousarray(k),
                               np.ascontiguousarray(v), ks, vs)


@dataclass
class PoolEntry:
    """One published prefix: whole pages only, tokens are authoritative."""

    key: str
    blocks: list[int]          # chained block hashes, one per KV page
    n_tokens: int              # == n_pages * page_size
    n_pages: int
    export: object             # StagedExport or HostExport
    nbytes: int
    created: float = field(default_factory=time.monotonic)


class PrefixPageStore:
    """Byte-budgeted thread-safe LRU of published prefixes, keyed by
    ``pool_key``.  Dropping an entry is always safe — the fetch path
    treats a 410 exactly like a miss and recomputes locally."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self.used_bytes = 0
        self._entries: "collections.OrderedDict[str, PoolEntry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.published_total = 0
        self.evictions_total = 0
        self.hits_total = 0          # get() served a fetch
        self.misses_total = 0        # get() came up empty (evicted/never had)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def put(self, entry: PoolEntry) -> bool:
        """Publish; returns False if the entry can never fit."""
        if entry.nbytes > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(entry.key, None)
            if old is not None:
                self.used_bytes -= old.nbytes
            while (self.used_bytes + entry.nbytes > self.max_bytes
                   and self._entries):
                _, victim = self._entries.popitem(last=False)
                self.used_bytes -= victim.nbytes
                self.evictions_total += 1
            self._entries[entry.key] = entry
            self.used_bytes += entry.nbytes
            self.published_total += 1
        return True

    def get(self, key: str) -> Optional[PoolEntry]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses_total += 1
                return None
            self._entries.move_to_end(key)
            self.hits_total += 1
            return entry

    def peek(self, key: str) -> Optional[PoolEntry]:
        """Lookup WITHOUT hit/miss accounting or LRU touch — chunk
        pulls of an already-claimed fetch must not inflate the hit
        rate (one fetch = one hit, counted at the meta handshake)."""
        with self._lock:
            return self._entries.get(key)

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def advert(self) -> list[dict]:
        """The holder's index advert, freshest last-used first: key +
        per-page block-hash chain (hex — JSON numbers lose 64-bit
        precision) + token count, enough for the EPP to match request
        prefixes without ever seeing KV bytes."""
        with self._lock:
            entries = list(self._entries.values())
        return [{"key": e.key,
                 "blocks": [f"{b & _MASK64:016x}" for b in e.blocks],
                 "n_tokens": e.n_tokens}
                for e in reversed(entries)]


def common_prefix_pages(req_tokens: list[int], entry_tokens: list[int],
                        page_size: int) -> int:
    """Whole pages of ``entry_tokens`` that are a verified token-level
    prefix of ``req_tokens`` — capped below the full request so at
    least one token remains for the prefill to produce logits from.
    This, not the hash match, is the import authority."""
    limit = min(len(req_tokens) - 1, len(entry_tokens))
    n = 0
    while n < limit and req_tokens[n] == entry_tokens[n]:
        n += 1
    return n // page_size
