"""Cluster-wide KV pool, replica-local side (docs/kv-pool.md).

Every replica keeps a byte-budgeted LRU of finished prompt prefixes —
whole-page KV slabs staged by the existing PD export machinery — keyed
by the SAME chained FNV-1a block hashes the EPP computes over request
bodies (``runtime/routing.prefix_blocks``).  The store is served over
the chunked PD wire (``/kv_pool/<key>/meta`` + ``/chunk/<i>``), and its
key set is advertised at ``/debug/kv_pool`` for the EPP's cluster-wide
prefix→holder index.  A freshly scaled-up replica can therefore fetch a
prefix another replica warmed instead of recomputing it, so warm TTFT
survives scale-out, rollout, and failover.

Correctness model: the block hashes are an INDEX, never an authority.
Chat templates, tokenizer boundary effects, and hash collisions all
mean a char-block match does not prove a token-level match — so the
pool meta response carries the entry's exact ``prompt_tokens`` and the
fetching engine trims to the longest common whole-page token prefix
before importing (``common_prefix_pages``).  Any miss, eviction, or
transfer failure degrades to the local prefill the scheduler already
has; the pool can only ever remove work, never corrupt it.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from kaito_tpu.engine.pd import plan_chunks, serialize_chunk
from kaito_tpu.runtime.routing import adapter_seed, prefix_blocks

# one KV page of page_size tokens covers page_size * CHARS_PER_TOKEN
# prompt chars — the same heuristic the EPP uses to align its block
# size to the engine's page size (routing.CHARS_PER_TOKEN)
CHARS_PER_TOKEN = 4
_MASK64 = (1 << 64) - 1


def pool_block_chars(page_size: int) -> int:
    """Char-block size whose blocks line up 1:1 with KV pages."""
    return page_size * CHARS_PER_TOKEN


def prompt_pool_blocks(text: str, page_size: int,
                       adapter: str = "") -> list[int]:
    """The engine-side publisher's block hashes for a prompt.  MUST
    stay the exact chain the EPP computes (``prefix_blocks`` at
    ``kv_page_size * 4`` chars) — a silent divergence makes the global
    index useless (pinned by tests/test_kv_pool.py).  ``adapter`` seeds
    the chain so KV computed under a LoRA adapter never hash-matches
    base KV (or another adapter's) for the same text; "" keeps every
    pre-adapter chain byte-identical."""
    return prefix_blocks(text, pool_block_chars(page_size),
                         seed=adapter_seed(adapter))


def pool_key(blocks: list[int]) -> str:
    """Store key of a prefix: the chained hash of its LAST block (it
    folds every earlier block, so it names the whole prefix)."""
    return f"{blocks[-1] & _MASK64:016x}"


def meta_nbytes(meta: dict) -> int:
    """Host bytes a staged entry's chunks occupy once drained (K + V +
    fp32 scale slabs for int8 pools), from the wire meta alone."""
    dt = np.dtype(meta["dtype"])
    n = int(np.prod(meta["shape"])) * dt.itemsize
    n += int(np.prod(meta.get("v_shape", meta["shape"]))) * dt.itemsize
    if "ks_shape" in meta:
        n += (int(np.prod(meta["ks_shape"]))
              + int(np.prod(meta["vs_shape"]))) * 4
    return n


class HostExport:
    """A StagedExport-shaped serving surface over HOST arrays.

    After a fetch, the target replica replicates the imported prefix
    into its own store (so the pool heals toward N holders and the
    original holder can scale down without losing the prefix).  The
    assembled host slab is what it has; this wraps it with the same
    ``meta``/``plans``/``get_chunk`` surface the pool endpoints serve,
    serializing chunks on demand so the bytes aren't stored twice."""

    def __init__(self, k: np.ndarray, v: np.ndarray,
                 ks: Optional[np.ndarray] = None,
                 vs: Optional[np.ndarray] = None, *,
                 n_tokens: int, model: str, prompt_tokens: list[int]):
        self._k, self._v, self._ks, self._vs = k, v, ks, vs
        L, n_pages = int(k.shape[0]), int(k.shape[1])
        per_layer_page = int(np.prod(k.shape[2:])
                             + np.prod(v.shape[2:])) * k.dtype.itemsize
        if ks is not None:
            per_layer_page += int(np.prod(ks.shape[2:])
                                  + np.prod(vs.shape[2:])) * 4
        self.plans = plan_chunks(L, n_pages, per_layer_page)
        self.meta = {"shape": [int(s) for s in k.shape],
                     "v_shape": [int(s) for s in v.shape],
                     "dtype": str(k.dtype), "n_tokens": n_tokens,
                     "model": model,
                     "chunks": [p.to_json() for p in self.plans]}
        if ks is not None:
            self.meta["ks_shape"] = [int(s) for s in ks.shape]
            self.meta["vs_shape"] = [int(s) for s in vs.shape]
        self.prompt_tokens = list(prompt_tokens)
        self.first_token = -1

    @property
    def n_chunks(self) -> int:
        return len(self.plans)

    def ensure_draining(self) -> None:
        """Parity with StagedExport — the bytes are already on host."""

    def get_chunk(self, i: int, timeout: float = 60.0,
                  consume: bool = False) -> bytes:
        if not 0 <= i < len(self.plans):
            raise IndexError(f"chunk {i} out of range ({len(self.plans)})")
        p = self.plans[i]
        k = self._k[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
        v = self._v[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
        ks = vs = None
        if self._ks is not None:
            ks = self._ks[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
            vs = self._vs[p.layer_lo:p.layer_hi, p.page_lo:p.page_hi]
        return serialize_chunk(np.ascontiguousarray(k),
                               np.ascontiguousarray(v), ks, vs)


@dataclass
class PoolEntry:
    """One published prefix: whole pages only, tokens are authoritative."""

    key: str
    blocks: list[int]          # chained block hashes, one per KV page
    n_tokens: int              # == n_pages * page_size
    n_pages: int
    export: object             # StagedExport or HostExport
    nbytes: int
    created: float = field(default_factory=time.monotonic)


class PrefixPageStore:
    """Byte-budgeted thread-safe LRU of published prefixes, keyed by
    ``pool_key``.  Dropping an entry is always safe — the fetch path
    treats a 410 exactly like a miss and recomputes locally."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self.used_bytes = 0
        self._entries: "collections.OrderedDict[str, PoolEntry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.published_total = 0
        self.evictions_total = 0
        self.hits_total = 0          # get() served a fetch
        self.misses_total = 0        # get() came up empty (evicted/never had)
        # tier-3 spill hook (docs/kv-pool.md): when a disk tier exists
        # the engine points this at its async spill queue so LRU
        # victims demote to SSD instead of vanishing.  Called OUTSIDE
        # the store lock with the evicted PoolEntry; must never block.
        self.on_evict: Optional[Callable[[PoolEntry], None]] = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def put(self, entry: PoolEntry) -> bool:
        """Publish; returns False if the entry can never fit."""
        if entry.nbytes > self.max_bytes:
            return False
        victims: list[PoolEntry] = []
        with self._lock:
            old = self._entries.pop(entry.key, None)
            if old is not None:
                self.used_bytes -= old.nbytes
            while (self.used_bytes + entry.nbytes > self.max_bytes
                   and self._entries):
                _, victim = self._entries.popitem(last=False)
                self.used_bytes -= victim.nbytes
                self.evictions_total += 1
                victims.append(victim)
            self._entries[entry.key] = entry
            self.used_bytes += entry.nbytes
            self.published_total += 1
        if self.on_evict is not None:
            for victim in victims:
                self.on_evict(victim)
        return True

    def get(self, key: str) -> Optional[PoolEntry]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses_total += 1
                return None
            self._entries.move_to_end(key)
            self.hits_total += 1
            return entry

    def peek(self, key: str) -> Optional[PoolEntry]:
        """Lookup WITHOUT hit/miss accounting or LRU touch — chunk
        pulls of an already-claimed fetch must not inflate the hit
        rate (one fetch = one hit, counted at the meta handshake)."""
        with self._lock:
            return self._entries.get(key)

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def advert(self, max_entries: int = 0) -> list[dict]:
        """The holder's index advert, freshest last-used first: key +
        per-page block-hash chain (hex — JSON numbers lose 64-bit
        precision) + token count, enough for the EPP to match request
        prefixes without ever seeing KV bytes.  ``max_entries`` > 0
        caps the advert to the freshest N rows so large pools stop
        inflating every EPP scrape; a capped advert is authoritative
        only for the rows it lists (the scraper merges instead of
        wholesale-replacing)."""
        with self._lock:
            entries = list(self._entries.values())
        if max_entries > 0:
            entries = entries[-max_entries:]
        return [{"key": e.key,
                 "blocks": [f"{b & _MASK64:016x}" for b in e.blocks],
                 "n_tokens": e.n_tokens}
                for e in reversed(entries)]


class DiskPageStore:
    """Tier-3 of the KV pool: a bounded directory of SSD slab files
    holding prefixes demoted out of the host-RAM ``PrefixPageStore``
    LRU (docs/kv-pool.md "Tier 3: SSD").

    Layout — per entry, two files named by the same ``pool_key``:

    - ``<key>.slab``: the entry's ``serialize_chunk`` outputs
      concatenated in plan order, byte-identical to what the pool's
      ``/chunk/<i>`` endpoints would have served (int8 scale slabs and
      all).  Chunk boundaries live in the meta, so a read is one
      ``seek`` + one bounded ``read`` — mmap-friendly, no parsing.
    - ``<key>.json``: wire meta (model/dtype/shapes/chunk plans),
      chunk byte sizes, block-hash chain (hex), token count, and the
      authoritative ``prompt_tokens``.

    The slab is written first, the meta second, both via the
    flight-recorder tmp+rename idiom — a meta file therefore PROVES a
    complete slab, and a crash mid-spill leaves only an orphan slab
    that the next startup scan deletes.  Pruning is mtime-LRU against
    ``max_bytes``; a read hit touches the meta so conversations that
    keep coming back stay resident.  Like every pool tier, dropping an
    entry is always safe — the fetch path falls through to remote
    peers and then local recompute."""

    SLAB = ".slab"
    META = ".json"

    def __init__(self, root: str, max_bytes: int):
        self.root = root
        self.max_bytes = max_bytes
        self.used_bytes = 0
        self._lock = threading.Lock()
        self._sizes: dict[str, int] = {}     # key -> slab+meta bytes
        self.hits_total = 0          # lookup_longest found an entry
        self.misses_total = 0        # lookup_longest came up empty
        self.spills_total = 0        # entries written by the spill worker
        self.evictions_total = 0     # entries pruned by the byte budget
        self.errors_total = 0        # corrupt meta/slab, failed writes
        os.makedirs(root, exist_ok=True)
        self._scan()

    def __len__(self) -> int:
        with self._lock:
            return len(self._sizes)

    def _paths(self, key: str) -> tuple[str, str]:
        # keys are our own 16-hex-char pool_key strings; refuse
        # anything else so a hostile key can't traverse out of root
        if not (len(key) == 16 and all(c in "0123456789abcdef"
                                       for c in key)):
            raise ValueError(f"bad pool key {key!r}")
        return (os.path.join(self.root, key + self.SLAB),
                os.path.join(self.root, key + self.META))

    def _scan(self) -> None:
        """Rebuild the in-memory index from disk (restart survival):
        a meta file with a matching slab is an entry; anything else —
        orphan slabs from interrupted spills, stray tmp files — is
        deleted."""
        with self._lock:
            for name in sorted(os.listdir(self.root)):
                path = os.path.join(self.root, name)
                if not os.path.isfile(path):
                    continue
                if name.endswith(self.META) and len(name) == 16 + len(self.META):
                    key = name[:16]
                    slab, meta = path[:-len(self.META)] + self.SLAB, path
                    if os.path.exists(slab):
                        size = os.path.getsize(slab) + os.path.getsize(meta)
                        self._sizes[key] = size
                        self.used_bytes += size
                    else:
                        os.unlink(meta)
                elif name.endswith(self.SLAB):
                    key = name[:16] if len(name) == 16 + len(self.SLAB) else ""
                    if key not in self._sizes and not os.path.exists(
                            path[:-len(self.SLAB)] + self.META):
                        os.unlink(path)
                elif name.endswith(".tmp"):
                    os.unlink(path)
            self._prune_locked()

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._sizes

    def spill(self, entry: PoolEntry) -> bool:
        """Persist a demoted entry (spill-worker thread only — chunk
        serialization may block on the export's D2H drain).  Returns
        True if the entry is on disk afterwards."""
        key = entry.key
        with self._lock:
            if key in self._sizes:
                return True          # already demoted once before
        if entry.nbytes > self.max_bytes:
            return False
        exp = entry.export
        try:
            exp.ensure_draining()
            # consume=False: pool entries serve arbitrarily many
            # readers (same contract as the /chunk endpoints) — the
            # spill must not destroy chunks a concurrent fetch needs
            chunks = [exp.get_chunk(i, consume=False)
                      for i in range(len(exp.plans))]
            blob = b"".join(chunks)
            meta = {"meta": exp.meta,
                    "chunk_sizes": [len(c) for c in chunks],
                    "blocks": [f"{b & _MASK64:016x}" for b in entry.blocks],
                    "n_tokens": entry.n_tokens,
                    "n_pages": entry.n_pages,
                    "prompt_tokens": [int(t) for t in exp.prompt_tokens]}
            meta_bytes = json.dumps(meta).encode()
            slab_path, meta_path = self._paths(key)
            tmp = slab_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, slab_path)
            tmp = meta_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(meta_bytes)
            os.replace(tmp, meta_path)
        except Exception:
            self.errors_total += 1
            return False
        with self._lock:
            if key not in self._sizes:
                size = len(blob) + len(meta_bytes)
                self._sizes[key] = size
                self.used_bytes += size
                self.spills_total += 1
            self._prune_locked()
        return True

    def load_meta(self, key: str) -> Optional[dict]:
        """Parsed meta for a resident entry, or None.  Corrupt meta
        (unparseable JSON, missing fields) drops the entry — the
        caller falls through to the next tier."""
        with self._lock:
            if key not in self._sizes:
                return None
        _, meta_path = self._paths(key)
        try:
            with open(meta_path, "rb") as f:
                meta = json.loads(f.read())
            if not isinstance(meta.get("chunk_sizes"), list) \
                    or "meta" not in meta:
                raise ValueError("malformed disk meta")
            return meta
        except (OSError, ValueError):
            self.errors_total += 1
            self.drop(key)
            return None

    def lookup_longest(self, blocks: list[int]) -> Optional[tuple[str, dict]]:
        """Longest stored prefix of the request's block chain:
        ``(key, meta)`` for the deepest ``blocks[:n]`` whose key is
        resident, or None.  One lookup counts one hit or one miss."""
        for n in range(len(blocks), 0, -1):
            key = pool_key(blocks[:n])
            meta = self.load_meta(key)
            if meta is not None:
                self.hits_total += 1
                self.touch(key)
                return key, meta
        self.misses_total += 1
        return None

    def read_chunk(self, key: str, i: int, meta: dict) -> bytes:
        """Chunk ``i``'s exact serialized bytes from the slab.  A
        truncated or vanished slab raises — the import machinery
        already turns any feed error into a clean local-recompute
        fallback (``kv_pool_fetch_failures_total``)."""
        sizes = meta["chunk_sizes"]
        if not 0 <= i < len(sizes):
            raise IndexError(f"chunk {i} out of range ({len(sizes)})")
        off = sum(sizes[:i])
        slab_path, _ = self._paths(key)
        try:
            with open(slab_path, "rb") as f:
                f.seek(off)
                data = f.read(int(sizes[i]))
        except OSError as e:
            self.errors_total += 1
            raise ValueError(f"disk slab read failed: {e}") from e
        if len(data) != int(sizes[i]):
            self.errors_total += 1
            self.drop(key)
            raise ValueError(
                f"truncated disk slab {key} chunk {i}: "
                f"{len(data)} != {sizes[i]}")
        return data

    def touch(self, key: str) -> None:
        """Refresh LRU position (prune order is meta mtime)."""
        try:
            _, meta_path = self._paths(key)
            os.utime(meta_path)
        except OSError:
            pass

    def drop(self, key: str) -> None:
        with self._lock:
            size = self._sizes.pop(key, None)
            if size is not None:
                self.used_bytes -= size
        try:
            slab_path, meta_path = self._paths(key)
            for p in (meta_path, slab_path):
                if os.path.exists(p):
                    os.unlink(p)
        except (OSError, ValueError):
            pass

    def _prune_locked(self) -> None:
        """Evict oldest-touched entries until under budget (meta
        mtime ascending — ``touch`` on read keeps live conversations
        resident).  Caller holds the lock."""
        if self.used_bytes <= self.max_bytes:
            return
        ages = []
        for key in self._sizes:
            _, meta_path = self._paths(key)
            try:
                ages.append((os.path.getmtime(meta_path), key))
            except OSError:
                ages.append((0.0, key))
        ages.sort()
        for _, key in ages:
            if self.used_bytes <= self.max_bytes:
                break
            size = self._sizes.pop(key, 0)
            self.used_bytes -= size
            self.evictions_total += 1
            try:
                slab_path, meta_path = self._paths(key)
                for p in (meta_path, slab_path):
                    if os.path.exists(p):
                        os.unlink(p)
            except (OSError, ValueError):
                pass


def common_prefix_pages(req_tokens: list[int], entry_tokens: list[int],
                        page_size: int) -> int:
    """Whole pages of ``entry_tokens`` that are a verified token-level
    prefix of ``req_tokens`` — capped below the full request so at
    least one token remains for the prefill to produce logits from.
    This, not the hash match, is the import authority."""
    limit = min(len(req_tokens) - 1, len(entry_tokens))
    n = 0
    while n < limit and req_tokens[n] == entry_tokens[n]:
        n += 1
    return n // page_size
