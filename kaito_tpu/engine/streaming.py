"""Streaming weight load: safetensors over ranged reads, no local copy.

The TPU counterpart of the reference's model-streaming subsystem
(`/root/reference/pkg/workspace/inference/modelstreaming/modelstreaming.go:73`
+ vLLM's runai_streamer load-format): instead of staging a full HF
snapshot on disk before loading, the engine reads each tensor's exact
byte span straight from the blob store (GCS JSON-API ranged GETs, auth
via the GKE metadata server — the workload-identity analogue of the
reference's ``fetch_sas.py``) and places it directly into the stacked
device param tree.  A 70B checkpoint therefore needs zero local disk
and cold-start is bounded by network bandwidth, not copy+load.

The safetensors layout makes this cheap: one small ranged read for the
JSON header per shard, then one ranged read per tensor.
"""

from __future__ import annotations

import json
import logging
import struct
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

import numpy as np

logger = logging.getLogger(__name__)

_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}

INDEX_FILE = "model.safetensors.index.json"
SINGLE_FILE = "model.safetensors"


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


class HTTPRangeReader:
    """Ranged reads against any HTTP(S) file server.

    One persistent connection per reader (keep-alive) so a
    thousands-of-tensors load doesn't pay a TLS handshake per read;
    transient errors (5xx, resets) retry with backoff the way the
    reference's runai streamer does.
    """

    def __init__(self, base_url: str,
                 token_provider: Optional[Callable[[], str]] = None,
                 retries: int = 4):
        import http.client
        import urllib.parse

        self.base_url = base_url.rstrip("/")
        self.token_provider = token_provider
        self.retries = retries
        u = urllib.parse.urlsplit(self.base_url)
        self._scheme, self._host, self._prefix = u.scheme, u.netloc, u.path
        self._conn: Optional[http.client.HTTPConnection] = None
        self.bytes_read = 0
        self.requests = 0

    def _connect(self):
        import http.client

        if self._conn is None:
            cls = (http.client.HTTPSConnection if self._scheme == "https"
                   else http.client.HTTPConnection)
            self._conn = cls(self._host, timeout=120)
        return self._conn

    def _drop(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def read(self, path: str, start: Optional[int] = None,
             end: Optional[int] = None) -> bytes:
        """end is EXCLUSIVE; both None reads the whole object."""
        headers = {}
        if start is not None:
            tail = str(end - 1) if end is not None else ""
            headers["Range"] = f"bytes={start}-{tail}"
        last: Exception = RuntimeError("no attempts")
        for attempt in range(self.retries + 1):
            if self.token_provider:
                headers["Authorization"] = f"Bearer {self.token_provider()}"
            try:
                conn = self._connect()
                conn.request("GET", f"{self._prefix}/{path}", headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status == 404:
                    raise urllib.error.HTTPError(
                        f"{self.base_url}/{path}", 404, "not found",
                        resp.headers, None)
                if resp.status in (429, 500, 502, 503, 504):
                    raise OSError(f"HTTP {resp.status} (transient)")
                if resp.status not in (200, 206):
                    raise urllib.error.HTTPError(
                        f"{self.base_url}/{path}", resp.status,
                        data[:200].decode(errors="replace"),
                        resp.headers, None)
                if start is not None and resp.status != 206:
                    # server ignored Range: refusing protects the
                    # no-full-shard-fetch contract (and our offsets)
                    raise RuntimeError(
                        f"{self._host} ignored Range (HTTP 200 for "
                        f"{path}); streaming needs a range-capable store")
                self.bytes_read += len(data)
                self.requests += 1
                return data
            except urllib.error.HTTPError:
                raise
            except RuntimeError:
                raise
            except Exception as e:   # transient: resets, timeouts, 5xx
                last = e
                self._drop()
                if attempt < self.retries:
                    time.sleep(min(2.0 ** attempt * 0.2, 5.0))
        raise last


_gcp_token_cache: dict = {"token": "", "expiry": 0.0}


def gcp_metadata_token() -> str:
    """Workload-identity access token from the GKE metadata server (the
    analogue of the reference's SAS-token init container)."""
    now = time.monotonic()
    if _gcp_token_cache["expiry"] - now > 60:
        return _gcp_token_cache["token"]
    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        data = json.loads(resp.read())
    _gcp_token_cache["token"] = data["access_token"]
    _gcp_token_cache["expiry"] = now + float(data.get("expires_in", 300))
    return _gcp_token_cache["token"]


def make_reader(location: str) -> HTTPRangeReader:
    """gs://bucket/prefix -> GCS JSON-API media endpoint; http(s) URLs
    pass through (tests, plain mirrors)."""
    if location.startswith("gs://"):
        bucket, _, prefix = location[len("gs://"):].partition("/")
        base = f"https://storage.googleapis.com/{bucket}"
        if prefix:
            base += f"/{prefix}"
        return HTTPRangeReader(base, token_provider=gcp_metadata_token)
    return HTTPRangeReader(location)


class SafetensorsStream:
    """Header-indexed ranged access to one or more safetensors shards."""

    def __init__(self, reader: HTTPRangeReader):
        self.reader = reader
        # tensor name -> (file, dtype_str, shape, abs_start, abs_end)
        self.index: dict[str, tuple[str, str, tuple, int, int]] = {}
        files = self._discover_files()
        for f in files:
            self._index_file(f)

    def _discover_files(self) -> list[str]:
        try:
            idx = json.loads(self.reader.read(INDEX_FILE))
            return sorted(set(idx.get("weight_map", {}).values()))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise    # auth/permission problems must surface, not mask
            return [SINGLE_FILE]

    def _index_file(self, fname: str) -> None:
        head = self.reader.read(fname, 0, 8)
        (n,) = struct.unpack("<Q", head)
        header = json.loads(self.reader.read(fname, 8, 8 + n))
        data_base = 8 + n
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            a, b = meta["data_offsets"]
            self.index[name] = (fname, meta["dtype"], tuple(meta["shape"]),
                                data_base + a, data_base + b)

    def keys(self) -> list[str]:
        return sorted(self.index)

    def read_tensor(self, name: str) -> Optional[np.ndarray]:
        entry = self.index.get(name)
        if entry is None:
            return None
        fname, dtype_s, shape, start, end = entry
        blob = self.reader.read(fname, start, end)
        if dtype_s == "BF16":
            arr = np.frombuffer(blob, dtype=_bf16())
        else:
            arr = np.frombuffer(blob, dtype=_DTYPES[dtype_s])
        return arr.reshape(shape)


def stream_safetensors_params(model, location: str,
                              reader: Optional[HTTPRangeReader] = None,
                              leaf_transform=None) -> dict:
    """Assemble the stacked param tree by streaming each tensor's byte
    span from the blob store — no staging copy (reference contract:
    modelstreaming.go SetStreamingConfig + runai_streamer)."""
    from kaito_tpu.engine.weights import assemble_params

    t0 = time.monotonic()
    reader = reader or make_reader(location)
    stream = SafetensorsStream(reader)
    params = assemble_params(model, stream.read_tensor, stream.keys(),
                             leaf_transform=leaf_transform)
    secs = time.monotonic() - t0
    # cold-start record, benchmark-probe style (driver/controller greppable)
    print("KAITO_WEIGHTS_STREAM_RESULT " + json.dumps({
        "location": location, "seconds": round(secs, 2),
        "bytes": reader.bytes_read, "requests": reader.requests,
        "mib_per_s": round(reader.bytes_read / 2**20 / max(secs, 1e-6), 1),
    }), flush=True)
    logger.info("streamed %.1f MiB in %.1fs (%d ranged reads) from %s",
                reader.bytes_read / 2**20, secs, reader.requests, location)
    return params
