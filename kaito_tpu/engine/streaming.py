"""Streaming weight load: safetensors over ranged reads, no local copy.

The TPU counterpart of the reference's model-streaming subsystem
(`/root/reference/pkg/workspace/inference/modelstreaming/modelstreaming.go:73`
+ vLLM's runai_streamer load-format): instead of staging a full HF
snapshot on disk before loading, the engine reads each tensor's exact
byte span straight from the blob store (GCS JSON-API ranged GETs, auth
via the GKE metadata server — the workload-identity analogue of the
reference's ``fetch_sas.py``) and places it directly into the stacked
device param tree.  A 70B checkpoint therefore needs zero local disk
and cold-start is bounded by network bandwidth, not copy+load.

The safetensors layout makes this cheap: one small ranged read for the
JSON header per shard, then one ranged read per tensor.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

import numpy as np

logger = logging.getLogger(__name__)

_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}

INDEX_FILE = "model.safetensors.index.json"
SINGLE_FILE = "model.safetensors"


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


class HTTPRangeReader:
    """Ranged reads against any HTTP(S) file server.

    One persistent connection per reader (keep-alive) so a
    thousands-of-tensors load doesn't pay a TLS handshake per read;
    transient errors (5xx, resets) retry with backoff the way the
    reference's runai streamer does.
    """

    def __init__(self, base_url: str,
                 token_provider: Optional[Callable[[], str]] = None,
                 retries: int = 4):
        import http.client
        import urllib.parse

        self.base_url = base_url.rstrip("/")
        self.token_provider = token_provider
        self.retries = retries
        u = urllib.parse.urlsplit(self.base_url)
        self._scheme, self._host, self._prefix = u.scheme, u.netloc, u.path
        self._conn: Optional[http.client.HTTPConnection] = None
        self.bytes_read = 0
        self.requests = 0

    def _connect(self):
        import http.client

        if self._conn is None:
            cls = (http.client.HTTPSConnection if self._scheme == "https"
                   else http.client.HTTPConnection)
            self._conn = cls(self._host, timeout=120)
        return self._conn

    def _drop(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def read(self, path: str, start: Optional[int] = None,
             end: Optional[int] = None) -> bytes:
        """end is EXCLUSIVE; both None reads the whole object."""
        headers = {}
        if start is not None:
            tail = str(end - 1) if end is not None else ""
            headers["Range"] = f"bytes={start}-{tail}"
        last: Exception = RuntimeError("no attempts")
        for attempt in range(self.retries + 1):
            if self.token_provider:
                headers["Authorization"] = f"Bearer {self.token_provider()}"
            try:
                conn = self._connect()
                conn.request("GET", f"{self._prefix}/{path}", headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status == 404:
                    raise urllib.error.HTTPError(
                        f"{self.base_url}/{path}", 404, "not found",
                        resp.headers, None)
                if resp.status in (429, 500, 502, 503, 504):
                    raise OSError(f"HTTP {resp.status} (transient)")
                if resp.status not in (200, 206):
                    raise urllib.error.HTTPError(
                        f"{self.base_url}/{path}", resp.status,
                        data[:200].decode(errors="replace"),
                        resp.headers, None)
                if start is not None and resp.status != 206:
                    # server ignored Range: refusing protects the
                    # no-full-shard-fetch contract (and our offsets)
                    raise RuntimeError(
                        f"{self._host} ignored Range (HTTP 200 for "
                        f"{path}); streaming needs a range-capable store")
                self.bytes_read += len(data)
                self.requests += 1
                return data
            except urllib.error.HTTPError:
                raise
            except RuntimeError:
                raise
            except Exception as e:   # transient: resets, timeouts, 5xx
                last = e
                self._drop()
                if attempt < self.retries:
                    time.sleep(min(2.0 ** attempt * 0.2, 5.0))
        raise last


_gcp_token_cache: dict = {"token": "", "expiry": 0.0}


def gcp_metadata_token() -> str:
    """Workload-identity access token from the GKE metadata server (the
    analogue of the reference's SAS-token init container)."""
    now = time.monotonic()
    if _gcp_token_cache["expiry"] - now > 60:
        return _gcp_token_cache["token"]
    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        data = json.loads(resp.read())
    _gcp_token_cache["token"] = data["access_token"]
    _gcp_token_cache["expiry"] = now + float(data.get("expires_in", 300))
    return _gcp_token_cache["token"]


def env_token() -> str:
    """Static bearer token from KAITO_STREAM_TOKEN (pre-provisioned
    secrets / cross-cloud SAS-style tokens).  Fails fast when unset —
    an empty Bearer header would surface as opaque 401s per ranged
    GET instead of one diagnosable startup error."""
    tok = os.environ.get("KAITO_STREAM_TOKEN", "")
    if not tok:
        raise RuntimeError(
            "weights location uses the +token scheme but "
            "KAITO_STREAM_TOKEN is unset (secret mount missing?)")
    return tok


# Pluggable credential-exchange registry (the analogue of the
# reference's per-cloud streamer credential init containers,
# preset_inferences.go runai_streamer + SAS-token flow): scheme ->
# (base_url_builder, token_provider).  Extend by registering a scheme;
# the GCS entry is the GKE-native default.
def _gcs_base(location: str) -> str:
    bucket, _, prefix = location[len("gs://"):].partition("/")
    base = f"https://storage.googleapis.com/{bucket}"
    return base + (f"/{prefix}" if prefix else "")


CREDENTIAL_PROVIDERS: dict = {
    "gs": (_gcs_base, gcp_metadata_token),
    "https+token": (lambda loc: "https://" + loc.split("://", 1)[1],
                    env_token),
    "http+token": (lambda loc: "http://" + loc.split("://", 1)[1],
                   env_token),
}


def register_credential_provider(scheme: str, base_builder, token_provider):
    """Add a blob-store scheme (e.g. an S3/Azure signer): the streaming
    loader resolves ``scheme://...`` weight locations through it."""
    CREDENTIAL_PROVIDERS[scheme] = (base_builder, token_provider)


def make_reader(location: str) -> HTTPRangeReader:
    """Resolve a weights location through the credential registry:
    ``gs://`` uses the GKE metadata server, ``http(s)+token://`` a
    pre-provisioned env token, plain http(s) passes through (tests,
    public mirrors)."""
    scheme = location.split("://", 1)[0] if "://" in location else ""
    entry = CREDENTIAL_PROVIDERS.get(scheme)
    if entry is not None:
        base_builder, token_provider = entry
        return HTTPRangeReader(base_builder(location),
                               token_provider=token_provider or None)
    return HTTPRangeReader(location)


class SafetensorsStream:
    """Header-indexed ranged access to one or more safetensors shards."""

    def __init__(self, reader: HTTPRangeReader):
        self.reader = reader
        # tensor name -> (file, dtype_str, shape, abs_start, abs_end)
        self.index: dict[str, tuple[str, str, tuple, int, int]] = {}
        files = self._discover_files()
        for f in files:
            self._index_file(f)

    def _discover_files(self) -> list[str]:
        try:
            idx = json.loads(self.reader.read(INDEX_FILE))
            return sorted(set(idx.get("weight_map", {}).values()))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise    # auth/permission problems must surface, not mask
            return [SINGLE_FILE]

    def _index_file(self, fname: str) -> None:
        head = self.reader.read(fname, 0, 8)
        (n,) = struct.unpack("<Q", head)
        header = json.loads(self.reader.read(fname, 8, 8 + n))
        data_base = 8 + n
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            a, b = meta["data_offsets"]
            self.index[name] = (fname, meta["dtype"], tuple(meta["shape"]),
                                data_base + a, data_base + b)

    def keys(self) -> list[str]:
        return sorted(self.index)

    def read_tensor(self, name: str) -> Optional[np.ndarray]:
        entry = self.index.get(name)
        if entry is None:
            return None
        fname, dtype_s, shape, start, end = entry
        blob = self.reader.read(fname, start, end)
        if dtype_s == "BF16":
            arr = np.frombuffer(blob, dtype=_bf16())
        else:
            arr = np.frombuffer(blob, dtype=_DTYPES[dtype_s])
        return arr.reshape(shape)


def stream_safetensors_params(model, location: str,
                              reader: Optional[HTTPRangeReader] = None,
                              leaf_transform=None) -> dict:
    """Assemble the stacked param tree by streaming each tensor's byte
    span from the blob store — no staging copy (reference contract:
    modelstreaming.go SetStreamingConfig + runai_streamer)."""
    from kaito_tpu.engine.weights import assemble_params

    t0 = time.monotonic()
    reader = reader or make_reader(location)
    stream = SafetensorsStream(reader)
    params = assemble_params(model, stream.read_tensor, stream.keys(),
                             leaf_transform=leaf_transform)
    secs = time.monotonic() - t0
    # cold-start record, benchmark-probe style (driver/controller greppable)
    print("KAITO_WEIGHTS_STREAM_RESULT " + json.dumps({
        "location": location, "seconds": round(secs, 2),
        "bytes": reader.bytes_read, "requests": reader.requests,
        "mib_per_s": round(reader.bytes_read / 2**20 / max(secs, 1e-6), 1),
    }), flush=True)
    logger.info("streamed %.1f MiB in %.1fs (%d ranged reads) from %s",
                reader.bytes_read / 2**20, secs, reader.requests, location)
    return params
