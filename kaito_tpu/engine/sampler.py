"""Token sampling: greedy / temperature / top-k / top-p, fully batched
and jittable (no data-dependent shapes).

Per-slot sampling parameters live in arrays so one compiled decode step
serves heterogeneous requests — the continuous-batching analogue of
vLLM's SamplingParams handling inside the reference's engine.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class SamplingState:
    """Per-slot sampling knobs, shape [B]."""

    temperature: jax.Array   # 0 => greedy
    top_k: jax.Array         # 0 => disabled
    top_p: jax.Array         # 1.0 => disabled
    key: jax.Array           # [B, 2] per-slot PRNG keys
    presence: jax.Array      # 0 => disabled (OpenAI presence_penalty)
    frequency: jax.Array     # 0 => disabled (OpenAI frequency_penalty)
    repetition: jax.Array    # 1 => disabled (HF/vLLM repetition_penalty)
    min_p: jax.Array         # 0 => disabled (vLLM min_p)

    @staticmethod
    def create(batch: int, seed: int = 0) -> "SamplingState":
        keys = jax.random.split(jax.random.PRNGKey(seed), batch)
        # idle rows are greedy/no-mask/no-penalty so the sampler's
        # cond gates (which read every row) stay enabled on a fresh
        # engine; admission overwrites the row via set_slot
        return SamplingState(
            temperature=jnp.zeros((batch,), jnp.float32),
            top_k=jnp.zeros((batch,), jnp.int32),
            top_p=jnp.ones((batch,), jnp.float32),
            key=jnp.asarray(keys, jnp.uint32),
            presence=jnp.zeros((batch,), jnp.float32),
            frequency=jnp.zeros((batch,), jnp.float32),
            repetition=jnp.ones((batch,), jnp.float32),
            min_p=jnp.zeros((batch,), jnp.float32),
        )

    def reset_slot(self, i: int) -> "SamplingState":
        """Greedy/no-mask/no-penalty row without touching the PRNG key
        (admission reseeds it): retirement stays a few tiny scatters."""
        return SamplingState(
            temperature=self.temperature.at[i].set(0.0),
            top_k=self.top_k.at[i].set(0),
            top_p=self.top_p.at[i].set(1.0),
            key=self.key,
            presence=self.presence.at[i].set(0.0),
            frequency=self.frequency.at[i].set(0.0),
            repetition=self.repetition.at[i].set(1.0),
            min_p=self.min_p.at[i].set(0.0),
        )

    def set_slot(self, i: int, *, temperature: float, top_k: int, top_p: float,
                 seed: int, presence: float = 0.0, frequency: float = 0.0,
                 repetition: float = 1.0, min_p: float = 0.0
                 ) -> "SamplingState":
        key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
        return SamplingState(
            temperature=self.temperature.at[i].set(temperature),
            top_k=self.top_k.at[i].set(top_k),
            top_p=self.top_p.at[i].set(top_p),
            key=self.key.at[i].set(jnp.asarray(key, jnp.uint32)),
            presence=self.presence.at[i].set(presence),
            frequency=self.frequency.at[i].set(frequency),
            repetition=self.repetition.at[i].set(repetition),
            min_p=self.min_p.at[i].set(min_p),
        )

    @property
    def any_penalty(self) -> jax.Array:
        return jnp.any((self.presence != 0.0) | (self.frequency != 0.0)
                       | (self.repetition != 1.0))


def chosen_logprob(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """log p(token) per row under the UNMODIFIED model distribution
    (OpenAI logprobs semantics — the sampling mask/temperature do not
    change the reported values).  logits [B, V] fp32, tokens [B]."""
    lse = jax.nn.logsumexp(logits, axis=-1)
    chosen = jnp.take_along_axis(
        logits, tokens[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return chosen - lse


def apply_penalties(logits: jax.Array, state: SamplingState,
                    counts: jax.Array, prompt_seen=None) -> jax.Array:
    """Sampling penalties, gated behind a cond like the sort path — a
    [B, V] read-modify-write per step must cost nothing for
    penalty-free batches.

    vLLM semantics: presence/frequency consider OUTPUT tokens only
    (``counts``, [B, V] int32 histogram); repetition_penalty considers
    prompt AND output (``prompt_seen``, [B, V] bool)."""

    def apply(l):
        c = counts.astype(jnp.float32)
        out_seen = c > 0
        rep_seen = out_seen if prompt_seen is None \
            else (out_seen | prompt_seen)
        rep = state.repetition[:, None]
        l = jnp.where(rep_seen & (l > 0), l / rep,
                      jnp.where(rep_seen, l * rep, l))
        return l - state.frequency[:, None] * c \
            - state.presence[:, None] * out_seen.astype(jnp.float32)

    return jax.lax.cond(state.any_penalty, apply, lambda l: l, logits)


def spec_verify_sample(target_logits: jax.Array, draft_logits: jax.Array,
                       proposal: jax.Array, prop_len: jax.Array,
                       temperature: jax.Array, onehot_q: jax.Array,
                       keys: jax.Array, grammar_rows=None):
    """Leviathan-style speculative verification: accept a prefix of the
    proposal, then draw one token from the residual distribution — the
    emitted stream is distribution-identical to sampling the target
    autoregressively (and bit-identical to greedy prefix-accept + bonus
    when temperature == 0).

    target_logits [B, W, V] fp32 (W = K+1 window positions);
    draft_logits  [B, K, V] fp32 (draft dist at each proposed position;
                  ignored where ``onehot_q`` or temperature == 0 — a
                  deterministic proposer's q is one-hot at the proposal);
    proposal      [B, K] int32; prop_len [B] valid proposal tokens;
    temperature   [B]; onehot_q [B] bool (n-gram / deterministic rows);
    keys          [B, 2] uint32 PRNG keys (speculation-private — the
                  engine's SamplingState keys are never consumed here);
    grammar_rows  optional [B, W, V] fp32 of 0 / -inf grammar masks per
                  window position (a shape-mismatched placeholder
                  statically disables the path).  The verify
                  distribution renormalizes under the mask — softmax of
                  masked logits IS the renormalized conditional — so
                  constrained rows keep speculating instead of falling
                  back to plain decode.

    Returns (out [B, W] int32, n_emit [B] int32, lps [B, W] f32,
    new_keys [B, 2]).  out[:, :n_emit] are the emitted tokens (accepted
    prefix + one residual/bonus draw); positions >= n_emit are garbage.
    lps are log p(token) under the UNMODIFIED target distribution
    (OpenAI logprobs semantics, matching ``chosen_logprob``).
    """
    B, W, V = target_logits.shape
    K = W - 1
    masked_logits = target_logits
    if grammar_rows is not None and grammar_rows.shape == target_logits.shape:
        masked_logits = target_logits + grammar_rows
    greedy_row = temperature <= 0.0
    temp = jnp.maximum(temperature, 1e-6)[:, None, None]
    p_soft = jax.nn.softmax(masked_logits / temp, axis=-1)
    p_hot = jax.nn.one_hot(jnp.argmax(masked_logits, axis=-1), V,
                           dtype=p_soft.dtype)
    p = jnp.where(greedy_row[:, None, None], p_hot, p_soft)     # [B, W, V]
    q_soft = jax.nn.softmax(draft_logits / temp, axis=-1)
    q_hot = jax.nn.one_hot(proposal, V, dtype=q_soft.dtype)
    det = (onehot_q | greedy_row)[:, None, None]
    q = jnp.where(det, q_hot, q_soft)                           # [B, K, V]

    j = jnp.arange(K)[None, :]
    valid = j < prop_len[:, None]                               # [B, K]
    p_prop = jnp.take_along_axis(
        p[:, :K], proposal[..., None], axis=-1)[..., 0]
    q_prop = jnp.take_along_axis(q, proposal[..., None], axis=-1)[..., 0]
    ratio = p_prop / jnp.maximum(q_prop, 1e-20)

    def row_draws(key_data):
        key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
        new_key, k_u, k_cat = jax.random.split(key, 3)
        u = jax.random.uniform(k_u, (K,))
        return jax.random.key_data(new_key), u, jax.random.key_data(k_cat)

    new_keys, u, cat_keys = jax.vmap(row_draws)(keys)
    accept = (u < ratio) & valid
    # longest accepted PREFIX (a single rejection stops the row)
    n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=-1), axis=-1)

    # residual at the first rejected position: max(p - q, 0) normalized;
    # past the proposal (full accept / empty proposal) the "residual"
    # is the target distribution itself (the bonus token)
    p_n = jnp.take_along_axis(p, n[:, None, None], axis=1)[:, 0]  # [B, V]
    q_pad = jnp.concatenate([q, jnp.zeros((B, 1, V), q.dtype)], axis=1)
    q_n = jnp.take_along_axis(q_pad, n[:, None, None], axis=1)[:, 0]
    q_n = jnp.where((n < prop_len)[:, None], q_n, 0.0)
    resid = jnp.maximum(p_n - q_n, 0.0)
    rs = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(rs > 1e-12, resid / jnp.maximum(rs, 1e-12), p_n)

    def row_cat(key_data, probs):
        key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
        tok = jax.random.categorical(
            key, jnp.log(jnp.maximum(probs, 1e-38)))
        return tok.astype(jnp.int32)

    extra_cat = jax.vmap(row_cat)(cat_keys, resid)
    # greedy rows stay draw-free: one-hot residual -> exact argmax
    extra = jnp.where(greedy_row,
                      jnp.argmax(resid, axis=-1).astype(jnp.int32),
                      extra_cat)

    jj = jnp.arange(W)[None, :]
    prop_pad = jnp.concatenate(
        [proposal, jnp.zeros((B, 1), proposal.dtype)], axis=1)
    out = jnp.where(jj < n[:, None], prop_pad, 0)
    out = jnp.where(jj == n[:, None], extra[:, None], out)
    out = out.astype(jnp.int32)
    logp = jax.nn.log_softmax(target_logits, axis=-1)
    lps = jnp.take_along_axis(logp, out[..., None], axis=-1)[..., 0]
    return out, (n + 1).astype(jnp.int32), lps, new_keys


def sample(logits: jax.Array, state: SamplingState,
           counts=None, prompt_seen=None,
           grammar_rows=None) -> tuple[jax.Array, SamplingState]:
    """Sample one token per row. logits: [B, V] fp32; counts: optional
    [B, V] output-token histogram for penalties (a shape-mismatched
    placeholder statically disables the penalty path, so penalty-free
    engines never allocate or touch [B, V] state); grammar_rows:
    optional [B, V] fp32 of 0 / -inf constrained-decoding masks,
    pre-gathered per slot (same placeholder discipline — grammar-free
    engines compile this path away entirely).  The mask lands before
    temperature/top-k/top-p so greedy, categorical and nucleus paths
    all honor it; unconstrained rows carry an all-zero row (no-op).

    The sort-based top-k/top-p masking and the categorical draw are
    gated behind ``lax.cond`` on what the batch actually requests: a
    full [B, V] sort every decode step tripled the fused decode step's
    device time at a 200k vocab when every slot was greedy.  The masked
    path is bit-identical to the always-sort implementation whenever any
    slot enables top-k/top-p."""
    B, V = logits.shape
    if counts is not None and counts.shape == logits.shape:
        logits = apply_penalties(logits, state, counts, prompt_seen)
    if grammar_rows is not None and grammar_rows.shape == logits.shape:
        logits = logits + grammar_rows
    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled = logits / temp

    def mask_topk_topp(scaled):
        # top-k: mask logits below the k-th largest (k==0 disables)
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        k = jnp.clip(state.top_k, 0, V)
        kth = jnp.take_along_axis(
            sorted_desc, jnp.maximum(k - 1, 0)[:, None], axis=-1)
        out = jnp.where((k[:, None] > 0) & (scaled < kth), -jnp.inf, scaled)

        # top-p (nucleus): keep the smallest prefix of the sorted
        # distribution with cumulative prob >= p
        probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs_sorted, axis=-1)
        cutoff_idx = jnp.sum(cum < state.top_p[:, None], axis=-1)  # [B]
        cutoff_val = jnp.take_along_axis(sorted_desc, cutoff_idx[:, None],
                                         axis=-1)
        return jnp.where(out < cutoff_val, -jnp.inf, out)

    def mask_min_p(scaled):
        # vLLM min_p: drop tokens whose prob is below min_p * max_prob
        # (scale-invariant in logit space: logit < max_logit + log(min_p))
        mx = jnp.max(scaled, axis=-1, keepdims=True)
        thresh = mx + jnp.log(jnp.maximum(state.min_p, 1e-10))[:, None]
        keep_all = (state.min_p <= 0.0)[:, None]
        return jnp.where(keep_all | (scaled >= thresh), scaled, -jnp.inf)

    random_row = state.temperature > 0.0
    need_mask = jnp.any(random_row & ((state.top_k > 0)
                                      | (state.top_p < 1.0)))
    scaled = jax.lax.cond(need_mask, mask_topk_topp, lambda s: s, scaled)
    need_min_p = jnp.any(random_row & (state.min_p > 0.0))
    scaled = jax.lax.cond(need_min_p, mask_min_p, lambda s: s, scaled)

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def draw(operands):
        keys, rows = operands

        def one(key_data, row):
            key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
            new_key, sub = jax.random.split(key)
            tok = jax.random.categorical(sub, row)
            return jax.random.key_data(new_key), tok.astype(jnp.int32)

        return jax.vmap(one)(keys, rows)

    new_keys, sampled = jax.lax.cond(
        jnp.any(random_row), draw,
        lambda operands: (operands[0], greedy), (state.key, scaled))
    tokens = jnp.where(random_row, sampled, greedy)
    new_state = SamplingState(
        temperature=state.temperature, top_k=state.top_k, top_p=state.top_p,
        key=new_keys, presence=state.presence, frequency=state.frequency,
        repetition=state.repetition, min_p=state.min_p)
    return tokens.astype(jnp.int32), new_state
