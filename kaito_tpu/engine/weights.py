"""Checkpoint loading: HF safetensors -> stacked param trees.

The engine's weights path for real checkpoints (the reference's pods
download HF repos and vLLM loads them; our pods read the ModelMirror
volume / GCS stream and this module maps HF parameter names onto the
scan-stacked layout).  HF linear weights are [out, in]; ours are
[in, out], so projections transpose on load.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from kaito_tpu.engine.model import TransformerLM

logger = logging.getLogger(__name__)

# our layer key -> (HF suffix, transpose?)
_LAYER_MAP = {
    "attn_norm": ("input_layernorm.weight", False),
    "attn_norm_bias": ("input_layernorm.bias", False),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "mlp_norm_bias": ("post_attention_layernorm.bias", False),
    "q": ("self_attn.q_proj.weight", True),
    "k": ("self_attn.k_proj.weight", True),
    "v": ("self_attn.v_proj.weight", True),
    "o": ("self_attn.o_proj.weight", True),
    "q_bias": ("self_attn.q_proj.bias", False),
    "k_bias": ("self_attn.k_proj.bias", False),
    "v_bias": ("self_attn.v_proj.bias", False),
    "o_bias": ("self_attn.o_proj.bias", False),
    "q_norm": ("self_attn.q_norm.weight", False),
    "k_norm": ("self_attn.k_norm.weight", False),
    "gate": ("mlp.gate_proj.weight", True),
    "up": ("mlp.up_proj.weight", True),
    "down": ("mlp.down_proj.weight", True),
    "up_bias": ("mlp.up_proj.bias", False),
    "down_bias": ("mlp.down_proj.bias", False),
    "post_attn_norm": ("post_attention_layernorm.weight", False),
    "post_mlp_norm": ("post_feedforward_layernorm.weight", False),
}
# gemma-3 swaps the meaning of post_attention_layernorm: pre-MLP norm is
# pre_feedforward_layernorm
_GEMMA_OVERRIDES = {
    "mlp_norm": ("pre_feedforward_layernorm.weight", False),
    "post_attn_norm": ("post_attention_layernorm.weight", False),
}


def _reader(directory: str) -> tuple[Callable[[str], Optional[np.ndarray]], list[str]]:
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(directory) if f.endswith(".safetensors"))
    handles = [safe_open(os.path.join(directory, f), framework="numpy")
               for f in files]
    key_to_handle = {}
    for h in handles:
        for k in h.keys():
            key_to_handle[k] = h

    def read(name: str) -> Optional[np.ndarray]:
        h = key_to_handle.get(name)
        if h is None:
            return None
        return np.asarray(h.get_tensor(name))

    return read, sorted(key_to_handle)


def load_safetensors_params(model: TransformerLM, directory: str,
                            leaf_transform=None) -> dict:
    """Assemble the stacked param tree from HF shards on disk."""
    read, all_keys = _reader(directory)
    params = assemble_params(model, read, all_keys,
                             leaf_transform=leaf_transform)
    logger.info("loaded %d stacked tensors from %s", len(all_keys), directory)
    return params


def assemble_params(model: TransformerLM,
                    read: Callable[[str], Optional[np.ndarray]],
                    all_keys: list[str],
                    leaf_transform=None) -> dict:
    """Map HF tensors (via any reader — disk shards or ranged streaming)
    onto the scan-stacked layout.

    ``leaf_transform(group, key, np_array) -> device leaf`` (group ""
    for top-level params) replaces the default ``jnp.asarray``
    placement per assembled tensor — the engine uses it to shard each
    stacked tensor straight onto its mesh and quantize it immediately
    (donated), so a 70B int8 load never materializes the bf16 tree.
    """
    arch = model.arch
    dtype = model.dtype

    def put(group: str, key: str, np_arr: np.ndarray):
        if leaf_transform is not None:
            return leaf_transform(group, key, np.asarray(np_arr))
        return jnp.asarray(np_arr, dtype)

    def get(name: str, required: bool = True) -> Optional[np.ndarray]:
        for prefix in ("model.", "transformer.", ""):
            t = read(prefix + name)
            if t is not None:
                return t
        if required:
            raise KeyError(f"missing tensor {name!r}; have e.g. {all_keys[:5]}")
        return None

    params: dict = {}
    embed = get("embed_tokens.weight")
    pad = model.vocab_padded - embed.shape[0]
    if pad > 0:
        embed = np.concatenate([embed, np.zeros((pad, embed.shape[1]),
                                                embed.dtype)])
    params["embed"] = put("", "embed", embed)
    params["final_norm"] = put("", "final_norm", get("norm.weight"))
    fnb = get("norm.bias", required=False)
    if fnb is not None:
        params["final_norm_bias"] = put("", "final_norm_bias", fnb)
    if not arch.tie_word_embeddings:
        head = read("lm_head.weight")
        if head is None:
            head = get("embed_tokens.weight")
        if model.vocab_padded - head.shape[0] > 0:
            head = np.concatenate([
                head, np.zeros((model.vocab_padded - head.shape[0],
                                head.shape[1]), head.dtype)])
        params["lm_head"] = put("", "lm_head", head)

    layer_map = dict(_LAYER_MAP)
    if arch.pre_post_norm:
        layer_map.update(_GEMMA_OVERRIDES)

    for g in model.groups:
        specs = model._layer_specs(g.moe)
        stack: dict[str, list] = {}
        for li in range(g.start, g.start + g.count):
            fused_qkv = None
            for our_key in specs:
                if "lora" in our_key:
                    continue
                entry = layer_map.get(our_key)
                tensor = None
                if entry is not None:
                    suffix, transpose = entry
                    tensor = get(f"layers.{li}.{suffix}", required=False)
                    if tensor is not None and transpose:
                        tensor = tensor.T
                if tensor is None and our_key in ("q", "k", "v"):
                    # phi-3 style fused qkv_proj
                    if fused_qkv is None:
                        fused = get(f"layers.{li}.self_attn.qkv_proj.weight",
                                    required=False)
                        if fused is not None:
                            Hq = arch.num_heads * arch.head_dim
                            Hkv = arch.num_kv_heads * arch.head_dim
                            fused = fused.T
                            fused_qkv = {
                                "q": fused[:, :Hq],
                                "k": fused[:, Hq:Hq + Hkv],
                                "v": fused[:, Hq + Hkv:Hq + 2 * Hkv],
                            }
                    if fused_qkv is not None:
                        tensor = fused_qkv[our_key]
                if tensor is None and our_key in ("gate", "up"):
                    # phi-3 style fused gate_up_proj
                    fused = get(f"layers.{li}.mlp.gate_up_proj.weight",
                                required=False)
                    if fused is not None:
                        fused = fused.T
                        I = arch.intermediate_size
                        tensor = fused[:, :I] if our_key == "gate" else fused[:, I:]
                if tensor is None and g.moe:
                    tensor = _read_moe_tensor(get, arch, li, our_key)
                if tensor is None:
                    raise KeyError(
                        f"no source tensor for layer {li} key {our_key!r}")
                stack.setdefault(our_key, []).append(np.asarray(tensor))
        params[g.name] = {
            k: put(g.name, k, np.stack(v)) for k, v in stack.items()}
    return params


# MoE tensors don't fit the flat suffix map: HF stores one tensor per
# expert (mixtral `block_sparse_moe.experts.{e}.w{1,2,3}`, qwen/deepseek
# `mlp.experts.{e}.{gate,up,down}_proj`), ours stack over the expert
# dim.  w1=gate, w3=up, w2=down (mixtral's numbering).
_MOE_EXPERT_SUFFIXES = {
    "experts_gate": ("w1", "gate_proj"),
    "experts_up": ("w3", "up_proj"),
    "experts_down": ("w2", "down_proj"),
}
_MOE_SHARED = {
    "shared_gate": "gate_proj",
    "shared_up": "up_proj",
    "shared_down": "down_proj",
}


def _read_moe_tensor(get, arch, li: int, our_key: str):
    """Load-side MoE mapping: router / stacked experts / shared experts
    from either HF naming convention; None when absent."""
    if our_key == "router":
        for suffix in ("block_sparse_moe.gate.weight", "mlp.gate.weight"):
            t = get(f"layers.{li}.{suffix}", required=False)
            if t is not None:
                return t.T                          # [X, H] -> [H, X]
        return None
    if our_key in _MOE_EXPERT_SUFFIXES:
        mix, qwen = _MOE_EXPERT_SUFFIXES[our_key]
        per_expert = []
        for e in range(arch.num_experts):
            t = get(f"layers.{li}.block_sparse_moe.experts.{e}.{mix}.weight",
                    required=False)
            if t is None:
                t = get(f"layers.{li}.mlp.experts.{e}.{qwen}.weight",
                        required=False)
            if t is None:
                return None
            per_expert.append(t.T)                  # HF [out, in] -> ours
        return np.stack(per_expert)
    if our_key in _MOE_SHARED:
        t = get(f"layers.{li}.mlp.shared_experts."
                f"{_MOE_SHARED[our_key]}.weight", required=False)
        return None if t is None else t.T
    return None


def _export_moe_tensor(out: dict, li: int, our_key: str, t: np.ndarray):
    """Export-side inverse of _read_moe_tensor (mixtral naming)."""
    if our_key == "router":
        out[f"model.layers.{li}.block_sparse_moe.gate.weight"] = \
            np.ascontiguousarray(t.T)
        return True
    if our_key in _MOE_EXPERT_SUFFIXES:
        mix, _ = _MOE_EXPERT_SUFFIXES[our_key]
        for e in range(t.shape[0]):
            out[f"model.layers.{li}.block_sparse_moe.experts.{e}"
                f".{mix}.weight"] = np.ascontiguousarray(t[e].T)
        return True
    if our_key in _MOE_SHARED:
        out[f"model.layers.{li}.mlp.shared_experts."
            f"{_MOE_SHARED[our_key]}.weight"] = np.ascontiguousarray(t.T)
        return True
    return False


def export_hf_state_dict(model: TransformerLM, params: dict) -> dict[str, np.ndarray]:
    """Inverse mapping (ours -> HF names); backs tests and adapter
    export tooling."""
    arch = model.arch
    out: dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(
        params["embed"][: arch.vocab_size])
    out["model.norm.weight"] = np.asarray(params["final_norm"])
    if "lm_head" in params:
        out["lm_head.weight"] = np.asarray(params["lm_head"][: arch.vocab_size])
    layer_map = dict(_LAYER_MAP)
    if arch.pre_post_norm:
        layer_map.update(_GEMMA_OVERRIDES)
    for g in model.groups:
        for our_key, stack in params[g.name].items():
            entry = layer_map.get(our_key)
            if entry is None:
                if g.moe:
                    for i in range(g.count):
                        _export_moe_tensor(out, g.start + i, our_key,
                                           np.asarray(stack[i]))
                continue
            suffix, transpose = entry
            for i in range(g.count):
                t = np.asarray(stack[i])
                # safetensors serializes raw buffers; a transposed VIEW
                # would be written with the wrong layout
                out[f"model.layers.{g.start + i}.{suffix}"] = (
                    np.ascontiguousarray(t.T) if transpose else t)
    return out
