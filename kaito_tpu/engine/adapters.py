"""Serving-side LoRA adapter loading.

Counterpart of the reference wrapper's ``--kaito-adapters-dir``
discovery + vLLM LoRARequest plumbing (``inference_api.py:417``): at
startup the engine scans the adapter directory, loads our adapter
artifacts (kaito_tpu.tuning.lora format), and applies them — merged
into the base weights for zero-overhead serving.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


def discover_adapters(adapters_dir: str) -> dict[str, str]:
    """Find adapters: subdirectories holding an adapter config."""
    found: dict[str, str] = {}
    if not adapters_dir or not os.path.isdir(adapters_dir):
        return found
    for name in sorted(os.listdir(adapters_dir)):
        path = os.path.join(adapters_dir, name)
        if os.path.isdir(path) and (
            os.path.exists(os.path.join(path, "adapter_config.json"))
            or os.path.exists(os.path.join(path, "adapter.msgpack"))
        ):
            found[name] = path
    return found


def apply_adapters_to_params(model, params, adapters_dir: str) -> dict:
    """Load every adapter in the dir and merge into the base weights.
    Multiple adapters merge additively (strength folded at tune time)."""
    from kaito_tpu.tuning.lora import (
        LoraConfig,
        apply_adapter,
        load_adapter,
        merge_lora,
    )

    for name, path in discover_adapters(adapters_dir).items():
        try:
            adapter, cfg, base = load_adapter(path)
        except Exception:
            logger.exception("skipping unreadable adapter %s", name)
            continue
        logger.info("loading adapter %s (base %s, r=%d)", name, base, cfg.r)
        params = apply_adapter(params, adapter)
        model.lora_scaling = cfg.scaling
        params = merge_lora(model, params)
    return params
