"""Serving-side LoRA adapter loading.

Counterpart of the reference wrapper's ``--kaito-adapters-dir``
discovery + vLLM LoRARequest plumbing (``inference_api.py:417-498``):
at startup the engine scans the adapter directory and loads every
adapter (kaito_tpu.tuning.lora format) into STACKED per-target buffers
— ``[L, n_adapters+1, in, r_max]`` factors that ride the layer scan —
so each request selects its adapter by index at runtime (index 0 is the
all-zeros base).  Requests choose an adapter with the ``model`` field,
exactly like the reference serves adapters as selectable models.

``apply_adapters_to_params`` (merge-into-base) remains for the TP/PP
paths where the stacked buffers aren't wired yet.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


def discover_adapters(adapters_dir: str) -> dict[str, str]:
    """Find adapters: subdirectories holding an adapter config."""
    found: dict[str, str] = {}
    if not adapters_dir or not os.path.isdir(adapters_dir):
        return found
    for name in sorted(os.listdir(adapters_dir)):
        path = os.path.join(adapters_dir, name)
        if os.path.isdir(path) and (
            os.path.exists(os.path.join(path, "adapter_config.json"))
            or os.path.exists(os.path.join(path, "adapter.msgpack"))
        ):
            found[name] = path
    return found


def load_adapter_stacks(model, adapters_dir: str, base_model: str = "",
                        allow_base_mismatch: bool = False,
                        refusals: Optional[dict] = None) -> tuple[dict, dict]:
    """Build the serve-time stacked LoRA buffers.

    Returns ``(serve_lora, name_to_index)`` where serve_lora is
    ``{group: {f"{t}_a": [L, n+1, in, rmax], f"{t}_b": [L, n+1, rmax, out]}}``
    (adapter 0 all-zeros = base model; alpha/r scaling folded into B)
    and name_to_index maps adapter names to their runtime index.
    Empty dicts when no adapters are present.

    An adapter whose recorded base model disagrees with the serving
    model is REFUSED (skipped and counted into ``refusals`` under
    ``"base_mismatch"`` — the kaito:adapter_load_failures_total label)
    rather than warned about and served: a wrong-base delta silently
    degrades every response routed at it.  ``allow_base_mismatch``
    (--adapter-allow-base-mismatch) restores the old behavior for
    intentionally cross-based adapters.
    """
    from kaito_tpu.tuning.lora import load_adapter

    def _count(reason: str) -> None:
        if refusals is not None:
            refusals[reason] = refusals.get(reason, 0) + 1

    if model.is_mla:
        # the MLA layer body has no multi-LoRA sites yet; refusing to
        # load keeps selection an explicit error instead of a silent
        # base-model response
        if discover_adapters(adapters_dir):
            logger.warning("per-request adapters are not supported on MLA "
                           "models yet; adapters in %s ignored", adapters_dir)
        return {}, {}
    found = discover_adapters(adapters_dir)
    loaded = []
    for name, path in found.items():
        try:
            adapter, cfg, base = load_adapter(path)
        except Exception:
            logger.exception("skipping unreadable adapter %s", name)
            _count("unreadable")
            continue
        if base and base_model and base != base_model:
            if not allow_base_mismatch:
                logger.warning(
                    "refusing adapter %s: targets base %s, serving %s "
                    "(pass --adapter-allow-base-mismatch to serve it "
                    "anyway)", name, base, base_model)
                _count("base_mismatch")
                continue
            logger.warning("adapter %s targets base %s, serving %s "
                           "(allowed by --adapter-allow-base-mismatch)",
                           name, base, base_model)
        loaded.append((name, adapter, cfg))
    if not loaded:
        return {}, {}

    rmax = max(cfg.r for _, _, cfg in loaded)
    n = len(loaded)
    serve_lora: dict = {}
    for g in model.groups:
        specs = model._layer_specs(g.moe)
        # MoE groups still have dense ATTENTION projections — their
        # q/k/v/o adapters apply; only the expert MLP targets are
        # per-request-unsupported (the moe path has no LoRA sites)
        targets = (("q", "k", "v", "o") if g.moe
                   else ("q", "k", "v", "o", "gate", "up", "down"))
        group_buf: dict = {}
        for t in targets:
            if t not in specs:
                continue
            in_dim, out_dim = specs[t][0]
            key_a = f"{g.name}/{t}_lora_a"
            key_b = f"{g.name}/{t}_lora_b"
            if not any(key_a in ad for _, ad, _ in loaded):
                continue
            A = np.zeros((g.count, n + 1, in_dim, rmax), np.float32)
            B = np.zeros((g.count, n + 1, rmax, out_dim), np.float32)
            for i, (name, ad, cfg) in enumerate(loaded):
                if key_a not in ad:
                    continue
                a = np.asarray(ad[key_a], np.float32)     # [L, in, r]
                b = np.asarray(ad[key_b], np.float32)     # [L, r, out]
                A[:, i + 1, :, :a.shape[-1]] = a
                B[:, i + 1, :b.shape[1], :] = b * cfg.scaling
            group_buf[f"{t}_a"] = jnp.asarray(A, model.dtype)
            group_buf[f"{t}_b"] = jnp.asarray(B, model.dtype)
        if group_buf:
            serve_lora[g.name] = group_buf
    if not serve_lora:
        # no routable targets at all: report nothing loadable so the
        # caller falls back to merge semantics instead of serving
        # phantom adapter names
        logger.warning("adapters in %s carry no per-request-servable "
                       "targets", adapters_dir)
        return {}, {}
    name_to_index = {name: i + 1 for i, (name, _, _) in enumerate(loaded)}
    logger.info("loaded %d adapters for per-request serving: %s (rmax=%d)",
                n, list(name_to_index), rmax)
    return serve_lora, name_to_index


def apply_adapters_to_params(model, params, adapters_dir: str) -> dict:
    """Load every adapter in the dir and merge into the base weights.
    Multiple adapters merge additively (strength folded at tune time)."""
    from kaito_tpu.tuning.lora import (
        LoraConfig,
        apply_adapter,
        load_adapter,
        merge_lora,
    )

    for name, path in discover_adapters(adapters_dir).items():
        try:
            adapter, cfg, base = load_adapter(path)
        except Exception:
            logger.exception("skipping unreadable adapter %s", name)
            continue
        logger.info("loading adapter %s (base %s, r=%d)", name, base, cfg.r)
        params = apply_adapter(params, adapter)
        model.lora_scaling = cfg.scaling
        params = merge_lora(model, params)
    return params
