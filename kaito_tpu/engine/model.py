"""Config-driven transformer LM.

One implementation covers the dense families (llama / mistral / qwen2 /
phi-3 / phi-4 / gemma-3 / falcon / phi-2) and token-choice MoE
(mixtral / gpt-oss style); layers run under ``lax.scan`` over stacked
parameters so an 80-layer model compiles as one layer, and per-layer
heterogeneity (sliding vs global attention, local vs global RoPE) rides
along as scanned flag arrays.  Dense-prefix MoE models (DeepSeek-style
``first_k_dense_replace``) split into two scans.

This replaces the model zoo the reference gets for free from vLLM
(SURVEY.md §2.2, §7 step 3); parameters are plain pytrees whose logical
axes map onto the planner's mesh via kaito_tpu.parallel.sharding.
"""

from __future__ import annotations

import math
import zlib
from dataclasses import dataclass
from functools import cached_property, partial
from typing import Optional

import jax
import jax.numpy as jnp

from kaito_tpu.engine import attention as attn
from kaito_tpu.engine import nn
from kaito_tpu.engine.kv_cache import (KVCache, write_decode_tokens,
                                       write_packed_prefill_tokens,
                                       write_packed_prefill_tokens_q,
                                       write_decode_tokens_q,
                                       write_prefill_tokens,
                                       write_prefill_tokens_q)
from kaito_tpu.models.metadata import AttentionKind, ModelArch

VOCAB_ALIGN = 128
_BIG_WINDOW = 1 << 30


def _name_salt(name: str) -> int:
    """Stable per-parameter PRNG salt.  Python's hash() is salted per
    process, which made synthetic weights differ across processes — a
    correctness hazard for multi-host lockstep serving (each process
    traces its own init program) and a source of cross-run test flakes
    (per-process weight draws occasionally produce argmax near-ties)."""
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


@dataclass(frozen=True)
class LayerGroup:
    name: str          # "dense" | "moe"
    start: int
    count: int
    moe: bool


def _layer_groups(arch: ModelArch) -> tuple[LayerGroup, ...]:
    if arch.num_experts > 0 and arch.moe_layer_start > 0:
        k = arch.moe_layer_start
        return (
            LayerGroup("dense", 0, k, False),
            LayerGroup("moe", k, arch.num_layers - k, True),
        )
    if arch.num_experts > 0:
        return (LayerGroup("moe", 0, arch.num_layers, True),)
    return (LayerGroup("dense", 0, arch.num_layers, False),)


def _prefetch_stack(stack: dict):
    """Layer-ahead slabs for the comm-overlap decode scan
    (docs/multichip.md): the QUANTIZED o/down planes rolled one layer
    forward on the stack axis, so the scan body at layer L slices layer
    L+1's slab and hands it to the fused kernel's prefetch stream.  The
    roll wraps the last layer to layer 0 — which is exactly the slab
    the NEXT decode step reads first.  bf16 stacks (no q planes) add
    nothing: prefetch is a quantized-weights optimization and the plain
    path stays untouched."""
    out = {}
    for name in ("o", "down"):
        w = stack.get(name)
        if isinstance(w, dict) and ("q8" in w or "q4" in w):
            out[name] = {k: jnp.roll(v, -1, axis=0) for k, v in w.items()}
    return out or None


class TransformerLM:
    """Functional model: all state lives in explicit params/cache trees."""

    def __init__(self, arch: ModelArch, dtype=jnp.bfloat16,
                 attn_impl: str = "jax"):
        self.is_mla = arch.attention_kind == AttentionKind.MLA
        self.arch = arch
        self.dtype = dtype
        self.attn_impl = attn_impl  # "jax" | "pallas" (paged decode)
        self.lora_scaling = 0.0     # set by the tuner when lora keys exist
        self.ring = None            # (Mesh, axis) => sequence-parallel training
        # (Mesh, axis, head_axis|None, q_tile) => context-parallel
        # serving prefill (mode "prefill_cp"); set by the engine
        self.cp = None
        # (Mesh, axis) => collective-compute overlap for TP decode
        # (docs/multichip.md); set by the engine when the comm-overlap
        # gate resolves on.  Only the DECODE mode's row-parallel
        # projections (attention-out, MLP-down) route through the
        # pipelined ring — prefill/CP/PP paths never read this.
        self.overlap = None
        self.moe_impl = "dense"     # "dense" | "ragged" (grouped matmul)
        self.groups = _layer_groups(arch)
        self.vocab_padded = -(-arch.vocab_size // VOCAB_ALIGN) * VOCAB_ALIGN
        # rope tables are concrete constants; computing them lazily inside
        # a traced scan body would cache tracers
        if self.is_mla:
            from dataclasses import replace

            rope_arch = replace(arch, head_dim=arch.qk_rope_head_dim or 64,
                                partial_rotary_factor=1.0)
            self._inv_freq_global = nn.rope_frequencies(rope_arch)
        else:
            self._inv_freq_global = nn.rope_frequencies(arch)
        # attention_factor only reads rope_scaling/max_pos, which the
        # MLA rope_arch replace() leaves untouched
        self._rope_mscale = nn.rope_attention_factor(arch)
        # longrope (phi-3 family): per-position short/long table switch
        self._longrope = None if self.is_mla else nn.longrope_tables(arch)
        self._inv_freq_local = self._make_inv_freq_local()

    def _rope_select(self, positions):
        """(inv_freq, mscale) for the global table — per-position
        short/long selection when the arch is longrope (positions past
        the original trained length use the long factors)."""
        if self._longrope is None:
            return self._inv_freq_global, self._rope_mscale
        short, long, orig, short_m, long_m = self._longrope
        mask = positions >= orig                       # [..., seq]
        inv = jnp.where(mask[..., None], long, short)  # [..., seq, half]
        ms = jnp.where(mask[..., None, None], long_m, short_m)
        return inv, ms

    # ------------------------------------------------------------------
    # Parameter construction
    # ------------------------------------------------------------------

    def _layer_specs(self, moe: bool) -> dict[str, tuple[tuple[int, ...], tuple]]:
        a = self.arch
        E, H, Hkv, D, I = (a.hidden_size, a.num_heads, a.num_kv_heads,
                           a.head_dim, a.intermediate_size)
        if self.is_mla:
            dn = a.qk_nope_head_dim or D
            dr = a.qk_rope_head_dim or 64
            dv = a.v_head_dim or D
            dl = a.kv_lora_rank or 512
            specs: dict[str, tuple[tuple[int, ...], tuple]] = {
                "attn_norm": ((E,), ("embed",)),
                "kv_a": ((E, dl + dr), ("embed", None)),
                "kv_a_norm": ((dl,), (None,)),
                "kv_b_k": ((dl, H * dn), (None, "heads")),
                "kv_b_v": ((dl, H * dv), (None, "heads")),
                "o": ((H * dv, E), ("heads", "embed")),
            }
            if a.q_lora_rank:
                specs.update({
                    "q_a": ((E, a.q_lora_rank), ("embed", None)),
                    "q_a_norm": ((a.q_lora_rank,), (None,)),
                    "q_b": ((a.q_lora_rank, H * (dn + dr)), (None, "heads")),
                })
            else:
                specs["q"] = ((E, H * (dn + dr)), ("embed", "heads"))
        else:
            specs = {
                "attn_norm": ((E,), ("embed",)),
                "q": ((E, H * D), ("embed", "heads")),
                "k": ((E, Hkv * D), ("embed", "kv_heads")),
                "v": ((E, Hkv * D), ("embed", "kv_heads")),
                "o": ((H * D, E), ("heads", "embed")),
            }
        if a.qkv_bias or a.linear_bias:
            specs.update({
                "q_bias": ((H * D,), ("heads",)),
                "k_bias": ((Hkv * D,), ("kv_heads",)),
                "v_bias": ((Hkv * D,), ("kv_heads",)),
            })
        if a.linear_bias:
            specs["o_bias"] = ((E,), ("embed",))
        if a.qk_norm:
            specs["q_norm"] = ((D,), (None,))
            specs["k_norm"] = ((D,), (None,))
        if a.norm_type == "layernorm":
            specs["attn_norm_bias"] = ((E,), ("embed",))
        if not a.parallel_residual:
            specs["mlp_norm"] = ((E,), ("embed",))
            if a.norm_type == "layernorm":
                specs["mlp_norm_bias"] = ((E,), ("embed",))
        if a.pre_post_norm:
            specs["post_attn_norm"] = ((E,), ("embed",))
            specs["post_mlp_norm"] = ((E,), ("embed",))
        if moe:
            X = a.num_experts
            Im = a.moe_intermediate_size or I
            specs.update({
                "router": ((E, X), ("embed", "expert")),
                "experts_gate": ((X, E, Im), ("expert", "embed", "intermediate")),
                "experts_up": ((X, E, Im), ("expert", "embed", "intermediate")),
                "experts_down": ((X, Im, E), ("expert", "intermediate", "embed")),
            })
            if a.num_shared_experts:
                Is = Im * a.num_shared_experts
                specs.update({
                    "shared_gate": ((E, Is), ("embed", "intermediate")),
                    "shared_up": ((E, Is), ("embed", "intermediate")),
                    "shared_down": ((Is, E), ("intermediate", "embed")),
                })
        else:
            if a.gated_mlp:
                specs["gate"] = ((E, I), ("embed", "intermediate"))
            specs["up"] = ((E, I), ("embed", "intermediate"))
            specs["down"] = ((I, E), ("intermediate", "embed"))
            if a.linear_bias:
                specs["up_bias"] = ((I,), ("intermediate",))
                specs["down_bias"] = ((E,), ("embed",))
        return specs

    def _top_specs(self) -> dict[str, tuple[tuple[int, ...], tuple]]:
        a = self.arch
        E = a.hidden_size
        specs = {
            "embed": ((self.vocab_padded, E), ("vocab", "embed")),
            "final_norm": ((E,), ("embed",)),
        }
        if a.norm_type == "layernorm":
            specs["final_norm_bias"] = ((E,), ("embed",))
        if not a.tie_word_embeddings:
            specs["lm_head"] = ((self.vocab_padded, E), ("vocab", "embed"))
        return specs

    def init_params(self, key: jax.Array) -> dict:
        """Random (synthetic) weights with sane init scales."""
        params: dict = {}
        keys = jax.random.split(key, len(self.groups) + 1)
        for spec_key, (shape, _) in self._top_specs().items():
            if "norm" in spec_key:
                params[spec_key] = jnp.zeros(shape, self.dtype) if "bias" in spec_key or self.arch.norm_offset else jnp.ones(shape, self.dtype)
            else:
                params[spec_key] = 0.02 * jax.random.normal(
                    jax.random.fold_in(keys[0], _name_salt(spec_key)), shape, self.dtype)
        for gi, g in enumerate(self.groups):
            layer: dict = {}
            for name, (shape, _) in self._layer_specs(g.moe).items():
                full = (g.count,) + shape
                if "norm" in name and "bias" not in name:
                    init = jnp.zeros(full, self.dtype) if self.arch.norm_offset else jnp.ones(full, self.dtype)
                elif name.endswith("_bias") or "bias" in name:
                    init = jnp.zeros(full, self.dtype)
                else:
                    fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
                    std = 1.0 / math.sqrt(fan_in)
                    init = std * jax.random.normal(
                        jax.random.fold_in(keys[1 + gi], _name_salt(name)), full, self.dtype)
                layer[name] = init
            params[g.name] = layer
        return params

    def param_logical_axes(self) -> dict:
        """Tree matching init_params with logical axis names per dim."""
        axes: dict = {}
        for name, (_, ax) in self._top_specs().items():
            axes[name] = ax
        for g in self.groups:
            axes[g.name] = {
                name: ("layers",) + ax
                for name, (_, ax) in self._layer_specs(g.moe).items()
            }
        return axes

    def param_count(self, params: dict) -> int:
        return sum(x.size for x in jax.tree.leaves(params))

    # ------------------------------------------------------------------
    # Flags / rope tables
    # ------------------------------------------------------------------

    def _make_inv_freq_local(self) -> jax.Array:
        # gemma-3 sliding layers use unscaled theta=10k rope
        a = self.arch
        if a.sliding_window_pattern and a.sliding_window:
            from dataclasses import replace

            local = replace(a, rope_theta=10000.0, rope_scaling=None)
            return nn.rope_frequencies(local)
        return self._inv_freq_global

    def _window_flags(self, start: int, count: int) -> Optional[jax.Array]:
        """Per-layer int32 window sizes (or _BIG_WINDOW for global)."""
        a = self.arch
        if not a.sliding_window:
            return None
        idx = jnp.arange(start, start + count)
        if a.sliding_window_pattern:
            is_global = (idx + 1) % a.sliding_window_pattern == 0
        else:
            is_global = jnp.zeros_like(idx, dtype=bool)
        return jnp.where(is_global, _BIG_WINDOW, a.sliding_window).astype(jnp.int32)

    @property
    def _scale(self) -> float:
        a = self.arch
        if self.is_mla:
            base = 1.0 / math.sqrt((a.qk_nope_head_dim or a.head_dim)
                                   + (a.qk_rope_head_dim or 0))
            # deepseek-yarn: the all-dim mscale lands in the softmax
            # scale (squared — applied to both q and k), while the
            # mscale/mscale_all_dim RATIO rides the rope table
            s = a.rope_scaling or {}
            stype = str(s.get("rope_type", s.get("type", ""))).lower()
            if stype == "yarn" and s.get("mscale_all_dim") is not None:
                m = nn.yarn_get_mscale(float(s.get("factor", 1.0)),
                                       float(s["mscale_all_dim"]))
                base *= m * m
            return base
        denom = a.query_pre_attn_scalar if a.query_pre_attn_scalar else a.head_dim
        return 1.0 / math.sqrt(denom)

    # ------------------------------------------------------------------
    # MLA (DeepSeek-style latent attention)
    # ------------------------------------------------------------------

    def _mla_attention(self, h, p, ck, cv, li, ks, vs, mode, *, positions,
                       page_tables, lengths, true_lens, active,
                       start_pos=None):
        """Latent attention: project to a shared compressed KV latent,
        cache only [c_kv ; k_rope], expand per-head K/V on use (prefill)
        or absorb projections into the query (decode).

        ``ck`` is the full layer-group latent cache [Lg, P, ps, 1, dl+dr]
        riding the layer scan as a carry; ``li`` selects this layer.
        ``ks``/``vs`` are the group's page-scale pools when the latent
        stream is int8-quantized (None otherwise); only ``ks`` is live —
        MLA has a single cached stream — but both ride the carry so the
        pytree shape matches the GQA path."""
        a = self.arch
        B, T, E = h.shape
        H = a.num_heads
        dn = a.qk_nope_head_dim or a.head_dim
        dr = a.qk_rope_head_dim or 64
        dl = a.kv_lora_rank or 512

        if "q_a" in p:
            q_lat = nn.rms_norm(nn.linear(h, p["q_a"]), p["q_a_norm"],
                                a.rms_norm_eps, False)
            q = nn.linear(q_lat, p["q_b"])
        else:
            q = nn.linear(h, p["q"])
        q = q.reshape(B, T, H, dn + dr)
        q_nope, q_rope = q[..., :dn], q[..., dn:]
        q_rope = nn.apply_rope(q_rope, positions, self._inv_freq_global, dr,
                               mscale=self._rope_mscale)

        kv = nn.linear(h, p["kv_a"])             # [B, T, dl+dr]
        c_kv = nn.rms_norm(kv[..., :dl], p["kv_a_norm"], a.rms_norm_eps, False)
        k_rope = nn.apply_rope(kv[..., dl:][:, :, None, :], positions,
                               self._inv_freq_global, dr,
                               mscale=self._rope_mscale)[:, :, 0]
        latent = jnp.concatenate([c_kv, k_rope], axis=-1)  # [B, T, dl+dr]

        if mode == "train":
            out = attn.mla_prefill_attention(
                q_nope, q_rope, c_kv, k_rope, p["kv_b_k"], p["kv_b_v"],
                scale=self._scale, true_len=true_lens)
        elif mode == "prefill":
            ps = ck.shape[-3]
            start = (start_pos if start_pos is not None
                     else jnp.zeros((B,), jnp.int32))
            if ks is not None:
                ck, ks = write_prefill_tokens_q(
                    ck, ks, latent[:, :, None, :], page_tables,
                    start, true_lens, ps, layer=li)
            else:
                ck = write_prefill_tokens(ck, latent[:, :, None, :],
                                          page_tables, start, true_lens, ps,
                                          layer=li)
            if start_pos is not None:
                # chunked prefill: attend over the paged latent history
                # (earlier chunks) + this chunk, absolute positions
                out = attn.mla_paged_context_attention(
                    q_nope, q_rope, ck, page_tables, start, true_lens,
                    p["kv_b_k"], p["kv_b_v"], scale=self._scale,
                    kv_lora_rank=dl, layer=li, latent_scale=ks)
            else:
                out = attn.mla_prefill_attention(
                    q_nope, q_rope, c_kv, k_rope, p["kv_b_k"], p["kv_b_v"],
                    scale=self._scale, true_len=true_lens)
        else:
            ps = ck.shape[-3]
            if ks is not None:
                ck, ks = write_decode_tokens_q(
                    ck, ks, latent[:, 0][:, None, :], page_tables,
                    positions[:, 0], ps, active, layer=li)
            else:
                ck = write_decode_tokens(ck, latent[:, 0][:, None, :],
                                         page_tables, positions[:, 0], ps,
                                         active, layer=li)
            out = attn.mla_paged_decode_attention(
                q_nope[:, 0], q_rope[:, 0], ck, page_tables, lengths,
                p["kv_b_k"], p["kv_b_v"], scale=self._scale,
                kv_lora_rank=dl, layer=li, latent_scale=ks)[:, None]
        dv = a.v_head_dim or a.head_dim
        attn_out = nn.linear(out.reshape(B, T, H * dv), p["o"])
        return attn_out, ck, cv, ks, vs

    # ------------------------------------------------------------------
    # Layer body (shared by prefill and decode via mode switch)
    # ------------------------------------------------------------------

    def _attn_qkv(self, x: jax.Array, p: dict, positions: jax.Array,
                  window: Optional[jax.Array], lora: Optional[dict] = None,
                  lora_ids: Optional[jax.Array] = None, overlap=None):
        """Project to q/k/v heads with norms+rope applied.

        x: [B, T, E]; positions: [B, T] absolute positions.

        ``overlap`` is the engine's (mesh, axis) comm-overlap handle
        (docs/multichip.md): when set, the COLUMN-parallel q projection
        — the widest of the three, head-sharded over the TP axis —
        routes through the pipelined all-gather+matmul ring so the
        activation gather hides behind the partial dots.  k/v (the
        narrow kv-head projections) and the rank-r LoRA deltas stay on
        the plain path, whose collectives are noise next to q's.
        """
        a = self.arch
        B, T, _ = x.shape
        ls = self.lora_scaling
        if overlap is not None:
            from kaito_tpu.engine.ops.overlap_collectives import (
                ag_matmul_eligible, all_gather_matmul)

            mesh, axis = overlap
            n = int(mesh.shape[axis])
            if ag_matmul_eligible(x, p["q"], n):
                q_proj = all_gather_matmul(x, p["q"], mesh,
                                           axis_name=axis)
            else:
                q_proj = nn.linear(x, p["q"])
        else:
            q_proj = nn.linear(x, p["q"])
        q = q_proj + nn.lora_delta(x, p, "q", ls) \
            + nn.multi_lora_delta(x, lora, "q", lora_ids)
        k = nn.linear(x, p["k"]) + nn.lora_delta(x, p, "k", ls) \
            + nn.multi_lora_delta(x, lora, "k", lora_ids)
        v = nn.linear(x, p["v"]) + nn.lora_delta(x, p, "v", ls) \
            + nn.multi_lora_delta(x, lora, "v", lora_ids)
        if "q_bias" in p:
            q, k, v = q + p["q_bias"], k + p["k_bias"], v + p["v_bias"]
        q = q.reshape(B, T, a.num_heads, a.head_dim)
        k = k.reshape(B, T, a.num_kv_heads, a.head_dim)
        v = v.reshape(B, T, a.num_kv_heads, a.head_dim)
        if a.qk_norm:
            q = nn.rms_norm(q, p["q_norm"], a.rms_norm_eps, a.norm_offset)
            k = nn.rms_norm(k, p["k_norm"], a.rms_norm_eps, a.norm_offset)
        if window is None or self._inv_freq_local is self._inv_freq_global:
            inv_freq, mscale = self._rope_select(positions)
        else:
            # sliding-window mix (gemma-3): local layers use the
            # unscaled 10k table with no magnitude correction (no
            # supported arch mixes sliding windows with longrope)
            inv_freq = jnp.where(window >= _BIG_WINDOW,
                                 self._inv_freq_global, self._inv_freq_local)
            mscale = jnp.where(window >= _BIG_WINDOW,
                               self._rope_mscale, 1.0)
        q = nn.apply_rope(q, positions, inv_freq, a.head_dim, mscale=mscale)
        k = nn.apply_rope(k, positions, inv_freq, a.head_dim, mscale=mscale)
        return q, k, v

    def _mlp(self, x: jax.Array, p: dict, moe: bool,
             lora: Optional[dict] = None,
             lora_ids: Optional[jax.Array] = None,
             overlap=None, pf_down=None) -> jax.Array:
        if moe:
            B, T, E = x.shape
            fn = nn.moe_mlp_ragged if self.moe_impl == "ragged" else nn.moe_mlp
            y = fn(x.reshape(B * T, E), p, self.arch)
            return y.reshape(B, T, E)
        return nn.mlp(x, p, self.arch, self.lora_scaling,
                      serve_lora=lora, lora_ids=lora_ids,
                      overlap=overlap, pf_down=pf_down)

    def _norm(self, x, p, name):
        if self.arch.norm_type == "layernorm":
            return nn.layer_norm(x, p[name], p.get(f"{name}_bias"), self.arch.rms_norm_eps)
        return nn.rms_norm(x, p[name], self.arch.rms_norm_eps, self.arch.norm_offset)

    def _layer(self, x, p, ck, cv, li, window, moe, mode, *,
               positions, page_tables, lengths, true_lens, active,
               start_pos=None, lora=None, lora_ids=None,
               ks=None, vs=None, packed=None, pf=None):
        """One transformer block. Returns (x, ck, cv, ks, vs).

        ``ck``/``cv`` are the FULL layer-group page pools
        [Lg, P, ps, Hkv, D] riding the layer scan as a carry; ``li`` is
        this layer's index into them.  Writes are in-place scatters on
        the carry and attention reads gather straight from the big
        buffer — neither materializes a per-layer slice (which cost
        ~14 ms/step when the cache rode the scan as stacked ys).
        ``ks``/``vs`` are the group's [Lg, P, Hkv] page-scale pools when
        the KV pools are int8-quantized, riding the same carry; None in
        bf16 mode."""
        a = self.arch
        B, T, E = x.shape
        h = self._norm(x, p, "attn_norm")
        if self.is_mla:
            attn_out, ck, cv, ks, vs = self._mla_attention(
                h, p, ck, cv, li, ks, vs, mode, positions=positions,
                page_tables=page_tables, lengths=lengths,
                true_lens=true_lens, active=active, start_pos=start_pos)
            if a.parallel_residual:
                return x + attn_out + self._mlp(h, p, moe), ck, cv, ks, vs
            x = x + attn_out
            h2 = self._norm(x, p, "mlp_norm")
            return x + self._mlp(h2, p, moe), ck, cv, ks, vs
        # collective-compute overlap (docs/multichip.md): DECODE-only,
        # resolved once here — q (column-parallel, below), o and down
        # (row-parallel, further down) all key off the same handle
        ov = self.overlap if mode == "decode" else None
        q, k_new, v_new = self._attn_qkv(h, p, positions, window,
                                         lora=lora, lora_ids=lora_ids,
                                         overlap=ov)
        ps = ck.shape[-3]

        if mode == "prefill_cp":
            # context-parallel single-shot prefill: q/k/v are sharded
            # over the sequence mesh axis; the ring rotates KV shards
            # while the page-pool scatter below (pool replicated over
            # the sequence axis) lets GSPMD all-gather the new KV once.
            # Padding needs no mask of its own: pads sit AFTER true_len,
            # so causal masking already hides them from valid queries,
            # and write_prefill_tokens routes their writes to the null
            # page.  Serving prompts start at position 0 (the engine
            # gates prefix-cache hits off this path).
            from kaito_tpu.parallel.ring_attention import ring_attention

            mesh, axis_name, head_axis, q_tile = self.cp
            start = jnp.zeros((B,), jnp.int32)
            if ks is not None:
                ck, ks = write_prefill_tokens_q(ck, ks, k_new, page_tables,
                                                start, true_lens, ps, layer=li)
                cv, vs = write_prefill_tokens_q(cv, vs, v_new, page_tables,
                                                start, true_lens, ps, layer=li)
            else:
                ck = write_prefill_tokens(ck, k_new, page_tables, start,
                                          true_lens, ps, layer=li)
                cv = write_prefill_tokens(cv, v_new, page_tables, start,
                                          true_lens, ps, layer=li)
            with jax.named_scope("attention"):
                out = ring_attention(
                    q, k_new, v_new, mesh, axis_name, scale=self._scale,
                    causal=True, sliding_window=window,
                    logit_softcap=a.attn_logit_softcap,
                    head_axis=head_axis, q_tile=q_tile)
        elif mode == "prefill_packed":
            # Segment-packed prefill: many fresh prompts share this row;
            # each token carries its own page target (host-computed from
            # its segment's page table) and attention masks by segment id
            # (docs/prefill.md).  ``positions`` are within-segment, which
            # for fresh prompts ARE the absolute positions — so RoPE,
            # page offsets and the sliding window all line up with the
            # serial path.
            seg_ids, tok_pages, pack_pages, tok_pgslot = packed
            offsets = (positions[0] % ps).astype(jnp.int32)
            if ks is not None:
                ck, ks = write_packed_prefill_tokens_q(
                    ck, ks, k_new, pack_pages, tok_pgslot, offsets, layer=li)
                cv, vs = write_packed_prefill_tokens_q(
                    cv, vs, v_new, pack_pages, tok_pgslot, offsets, layer=li)
            else:
                ck = write_packed_prefill_tokens(ck, k_new, tok_pages,
                                                 offsets, layer=li)
                cv = write_packed_prefill_tokens(cv, v_new, tok_pages,
                                                 offsets, layer=li)
            if self.attn_impl == "pallas":
                from kaito_tpu.engine.ops.flash_prefill import (
                    flash_prefill_packed)

                win = window if window is not None else jnp.int32(_BIG_WINDOW)
                out = flash_prefill_packed(
                    q, k_new, v_new, seg_ids, positions,
                    jnp.asarray(win, jnp.int32), scale=self._scale,
                    softcap=a.attn_logit_softcap)
            else:
                out = attn.packed_prefill_attention(
                    q, k_new, v_new, seg_ids, positions, scale=self._scale,
                    sliding_window=window,
                    logit_softcap=a.attn_logit_softcap)
        elif mode == "prefill":
            start = (start_pos if start_pos is not None
                     else jnp.zeros((B,), jnp.int32))
            if ks is not None:
                ck, ks = write_prefill_tokens_q(ck, ks, k_new, page_tables,
                                                start, true_lens, ps, layer=li)
                cv, vs = write_prefill_tokens_q(cv, vs, v_new, page_tables,
                                                start, true_lens, ps, layer=li)
            else:
                ck = write_prefill_tokens(ck, k_new, page_tables, start,
                                          true_lens, ps, layer=li)
                cv = write_prefill_tokens(cv, v_new, page_tables, start,
                                          true_lens, ps, layer=li)
            if start_pos is not None:
                # chunk attends over cached context + itself (prefix reuse)
                out = attn.paged_context_attention(
                    q, ck, cv, page_tables, start, true_lens,
                    scale=self._scale, sliding_window=window,
                    logit_softcap=a.attn_logit_softcap, layer=li,
                    k_scale=ks, v_scale=vs)
            elif self.attn_impl == "pallas":
                from kaito_tpu.engine.ops.flash_prefill import (
                    flash_prefill_attention)

                win = window if window is not None else jnp.int32(_BIG_WINDOW)
                out = flash_prefill_attention(
                    q, k_new, v_new, true_lens, jnp.asarray(win, jnp.int32),
                    scale=self._scale, softcap=a.attn_logit_softcap)
            else:
                out = attn.prefill_attention(
                    q, k_new, v_new, scale=self._scale,
                    sliding_window=window, logit_softcap=a.attn_logit_softcap,
                    true_len=true_lens)
        else:
            if ks is not None:
                ck, ks = write_decode_tokens_q(ck, ks, k_new[:, 0], page_tables,
                                               positions[:, 0], ps, active,
                                               layer=li)
                cv, vs = write_decode_tokens_q(cv, vs, v_new[:, 0], page_tables,
                                               positions[:, 0], ps, active,
                                               layer=li)
            else:
                ck = write_decode_tokens(ck, k_new[:, 0], page_tables,
                                         positions[:, 0], ps, active, layer=li)
                cv = write_decode_tokens(cv, v_new[:, 0], page_tables,
                                         positions[:, 0], ps, active, layer=li)
            if self.attn_impl == "pallas":
                from kaito_tpu.engine.ops.decode_attention import (
                    paged_decode_attention_pallas)

                win = window if window is not None else jnp.int32(_BIG_WINDOW)
                out = paged_decode_attention_pallas(
                    q[:, 0], ck, cv, page_tables, lengths,
                    jnp.asarray(win, jnp.int32), scale=self._scale,
                    softcap=a.attn_logit_softcap, layer=li,
                    k_scale=ks, v_scale=vs)
            else:
                out = attn.paged_decode_attention(
                    q[:, 0], ck, cv, page_tables, lengths, scale=self._scale,
                    sliding_window=window, logit_softcap=a.attn_logit_softcap,
                    layer=li, k_scale=ks, v_scale=vs)
            out = out[:, None]
        o_in = out.reshape(B, T, a.num_heads * a.head_dim)
        # collective-compute overlap (docs/multichip.md): the DECODE
        # step's row-parallel attention-out projection routes through
        # the pipelined ring; every prefill mode and the gate-off path
        # keep the plain linear (implicit GSPMD all-reduce) unchanged
        if ov is not None:
            from kaito_tpu.engine.ops.overlap_collectives import (
                overlap_linear)

            o_proj = overlap_linear(o_in, p["o"], ov[0], axis_name=ov[1],
                                    prefetch=(pf or {}).get("o"))
        else:
            o_proj = nn.linear(o_in, p["o"])
        attn_out = o_proj + nn.lora_delta(o_in, p, "o", self.lora_scaling) \
            + nn.multi_lora_delta(o_in, lora, "o", lora_ids)
        if "o_bias" in p:
            attn_out = attn_out + p["o_bias"]

        if a.parallel_residual:
            mlp_out = self._mlp(h, p, moe, lora=lora, lora_ids=lora_ids,
                                overlap=ov, pf_down=(pf or {}).get("down"))
            return x + attn_out + mlp_out, ck, cv, ks, vs

        if a.pre_post_norm:
            attn_out = self._norm(attn_out, p, "post_attn_norm")
        x = x + attn_out
        h2 = self._norm(x, p, "mlp_norm")
        mlp_out = self._mlp(h2, p, moe, lora=lora, lora_ids=lora_ids,
                            overlap=ov, pf_down=(pf or {}).get("down"))
        if a.pre_post_norm:
            mlp_out = self._norm(mlp_out, p, "post_mlp_norm")
        return x + mlp_out, ck, cv, ks, vs

    # ------------------------------------------------------------------
    # Forward passes
    # ------------------------------------------------------------------

    def _run_layers(self, params, cache: Optional[KVCache], x, mode, *,
                    positions, page_tables, lengths, true_lens, active,
                    remat: bool = False, start_pos=None, adapter_ids=None,
                    packed=None):
        serve_lora = params.get("serve_lora") if mode != "train" else None
        new_k, new_v, new_ks, new_vs = [], [], [], []
        for g in self.groups:
            stack = params[g.name]
            flags = self._window_flags(g.start, g.count)
            if mode == "train":
                def body(carry, xs, moe=g.moe):
                    h = carry
                    (p, window) = xs if flags is not None else (xs[0], None)
                    h = self._layer_train(h, p, window, moe, positions=positions,
                                          true_lens=true_lens)
                    return h, None

                if remat:
                    body = jax.checkpoint(body, prevent_cse=False)
                xs = (stack,) if flags is None else (stack, flags)
                x, _ = jax.lax.scan(body, x, xs)
                continue

            # The group's page pools ride the scan as a CARRY: writes are
            # in-place scatters at a traced layer index and attention
            # gathers straight from the big buffer.  (Threading them as
            # xs/ys sliced + re-stacked the full pool every step — 14 ms
            # of a 31 ms decode step on a v5e chip.)
            ck_g = cache.k[g.start:g.start + g.count]
            cv_g = cache.v[g.start:g.start + g.count]
            # scale pools (int8 KV mode) ride the same carry; None is a
            # valid empty pytree leaf so the bf16 scan is unchanged
            ks_g = (cache.k_scale[g.start:g.start + g.count]
                    if cache.k_scale is not None else None)
            vs_g = (cache.v_scale[g.start:g.start + g.count]
                    if cache.v_scale is not None else None)
            # per-request adapters ride the scan as an extra [L, n, ...]
            # stack (None for groups without one, e.g. MoE)
            lora_g = serve_lora.get(g.name) if serve_lora else None
            has_lora = bool(lora_g)
            # comm-overlap decode: the next layer's quantized o/down
            # slabs ride the scan as one more xs stream (rolled stack,
            # docs/multichip.md) feeding the kernel's prefetch DMA.
            # Gate off (or non-decode, or bf16): no extra stream — the
            # scan signature and trace are byte-identical to before.
            pf_g = (_prefetch_stack(stack)
                    if self.overlap is not None and mode == "decode"
                    else None)
            has_pf = pf_g is not None

            def body(carry, xs, moe=g.moe, has_lora=has_lora,
                     has_pf=has_pf):
                h, ck_g, cv_g, ks_g, vs_g = carry
                items = list(xs)
                li, p = items[0], items[1]
                k = 2
                lora_l = items[k] if has_lora else None
                k += int(has_lora)
                pf_l = items[k] if has_pf else None
                window = items[-1] if flags is not None else None
                h, ck_g, cv_g, ks_g, vs_g = self._layer(
                    h, p, ck_g, cv_g, li, window, moe, mode,
                    positions=positions, page_tables=page_tables,
                    lengths=lengths, true_lens=true_lens, active=active,
                    start_pos=start_pos, lora=lora_l, lora_ids=adapter_ids,
                    ks=ks_g, vs=vs_g, packed=packed, pf=pf_l)
                return (h, ck_g, cv_g, ks_g, vs_g), None

            # scan length follows the actual stack: pipeline stages pass
            # stage-local views whose leading axis is a fraction of the
            # arch's layer count
            Lg = jax.tree.leaves(stack)[0].shape[0]
            xs = (jnp.arange(Lg, dtype=jnp.int32), stack)
            if has_lora:
                xs = xs + (lora_g,)
            if has_pf:
                xs = xs + (pf_g,)
            if flags is not None:
                pat = self.arch.sliding_window_pattern
                if Lg != g.count and pat and Lg % pat:
                    # flags[:Lg] only equals every stage's own flags when
                    # the global/local pattern tiles the stage evenly
                    raise NotImplementedError(
                        f"pipeline stage of {Lg} layers does not tile the "
                        f"sliding-window pattern ({pat}); per-stage window "
                        f"flags are not implemented")
                xs = xs + (flags[:Lg],)
            (x, ck_new, cv_new, ks_new, vs_new), _ = jax.lax.scan(
                body, (x, ck_g, cv_g, ks_g, vs_g), xs)
            new_k.append(ck_new)
            new_v.append(cv_new)
            new_ks.append(ks_new)
            new_vs.append(vs_new)
        if mode == "train":
            return x, None

        def _cat(parts):
            if parts and parts[0] is None:
                return None
            return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

        cache = KVCache(k=_cat(new_k), v=_cat(new_v),
                        k_scale=_cat(new_ks), v_scale=_cat(new_vs))
        return x, cache

    def _layer_train(self, x, p, window, moe, *, positions, true_lens):
        """Transformer block without KV-cache plumbing (training)."""
        a = self.arch
        B, T, E = x.shape
        h = self._norm(x, p, "attn_norm")
        if self.is_mla:
            attn_out, _, _, _, _ = self._mla_attention(
                h, p, None, None, None, None, None, "train",
                positions=positions, page_tables=None, lengths=None,
                true_lens=true_lens, active=None)
            if a.parallel_residual:
                return x + attn_out + self._mlp(h, p, moe)
            x = x + attn_out
            h2 = self._norm(x, p, "mlp_norm")
            return x + self._mlp(h2, p, moe)
        q, k_new, v_new = self._attn_qkv(h, p, positions, window)
        if self.ring is not None and window is None:
            # sequence-parallel exact attention over the mesh ring;
            # training batches are packed dense (loss masks handle pads)
            from kaito_tpu.parallel.ring_attention import ring_attention

            mesh, axis = self.ring
            out = ring_attention(q, k_new, v_new, mesh, axis,
                                 scale=self._scale, causal=True)
        else:
            out = attn.prefill_attention(
                q, k_new, v_new, scale=self._scale, sliding_window=window,
                logit_softcap=a.attn_logit_softcap, true_len=true_lens)
        o_in = out.reshape(B, T, a.num_heads * a.head_dim)
        attn_out = nn.linear(o_in, p["o"]) + nn.lora_delta(o_in, p, "o", self.lora_scaling)
        if "o_bias" in p:
            attn_out = attn_out + p["o_bias"]
        if a.parallel_residual:
            return x + attn_out + self._mlp(h, p, moe)
        if a.pre_post_norm:
            attn_out = self._norm(attn_out, p, "post_attn_norm")
        x = x + attn_out
        h2 = self._norm(x, p, "mlp_norm")
        mlp_out = self._mlp(h2, p, moe)
        if a.pre_post_norm:
            mlp_out = self._norm(mlp_out, p, "post_mlp_norm")
        return x + mlp_out

    def _embed(self, params, tokens):
        x = params["embed"][tokens].astype(self.dtype)
        if self.arch.embedding_multiplier:
            x = x * jnp.asarray(self.arch.embedding_multiplier, self.dtype)
        return x

    def _logits(self, params, x):
        head = params["embed"] if self.arch.tie_word_embeddings else params["lm_head"]
        # bf16 inputs with fp32 accumulation: upcasting bf16 weights to
        # fp32 inputs adds no information but runs the MXU at fp32 rate
        logits = jax.lax.dot_general(
            x, head, (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        logits = nn.softcap(logits, self.arch.final_logit_softcap)
        return logits[..., : self.arch.vocab_size]

    def prefill(self, params, cache: KVCache, tokens, true_lens, page_tables,
                start_pos=None, adapter_ids=None):
        """Process prompts (or prompt suffixes when ``start_pos`` marks a
        cached/chunked prefix already present in the pages).

        tokens: [B, T] padded chunks; true_lens: [B] valid NEW tokens;
        page_tables: [B, pages_per_seq] pre-allocated.  Returns (cache,
        last_logits [B, vocab], last_hidden [B, E]).
        """
        B, T = tokens.shape
        rel = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        positions = rel if start_pos is None else rel + start_pos[:, None]
        x = self._embed(params, tokens)
        x, cache = self._run_layers(
            params, cache, x, "prefill", positions=positions,
            page_tables=page_tables, lengths=true_lens, true_lens=true_lens,
            active=None, start_pos=start_pos, adapter_ids=adapter_ids)
        x = self._norm(x, params, "final_norm")
        last = jnp.take_along_axis(
            x, (true_lens - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return cache, self._logits(params, last), last

    def prefill_packed(self, params, cache: KVCache, tokens, seg_ids,
                       positions, tok_pages, last_idx, pack_pages=None,
                       tok_pgslot=None, adapter_ids=None):
        """Segment-packed prefill: S fresh prompts concatenated into ONE
        padded row share a single dispatch (docs/prefill.md).

        tokens/seg_ids/positions: [1, T] — per-token segment id (-1 =
        pad) and within-segment position; tok_pages: [T] page per token
        (bf16 KV); pack_pages [n_pg] + tok_pgslot [T] address the int8
        scale fold; last_idx: [S] packed index of each segment's final
        token.  All segments must share one adapter (``adapter_ids`` is
        the usual [B] row vector with B=1).  Returns (cache, last_logits
        [S, vocab], last_hidden [S, E]).
        """
        if self.is_mla:
            raise NotImplementedError(
                "segment-packed prefill is not implemented for MLA "
                "attention; the engine batches fresh MLA prompts on the "
                "batch axis instead")
        x = self._embed(params, tokens)
        x, cache = self._run_layers(
            params, cache, x, "prefill_packed", positions=positions,
            page_tables=None, lengths=None, true_lens=None, active=None,
            adapter_ids=adapter_ids,
            packed=(seg_ids, tok_pages, pack_pages, tok_pgslot))
        x = self._norm(x, params, "final_norm")
        last = x[0, last_idx]                               # [S, E]
        return cache, self._logits(params, last), last

    def prefill_cp(self, params, cache: KVCache, tokens, true_lens,
                   page_tables, adapter_ids=None):
        """Context-parallel single-shot prefill: the WHOLE prompt in one
        call, activations sharded over the ``sequence`` mesh axis and
        attention run as a ring (``parallel/ring_attention.py``).

        The serving-side long-context answer the reference delegates to
        vLLM's KV budget (``pkg/model/interface.go:308-312``): TTFT for
        a T-token prompt scales ~1/seq because every chip holds T/seq
        tokens of activations and attention workspace.  Decode stays TP
        — the KV pool is replicated over the sequence axis, so the
        pages this call writes are immediately readable by the ordinary
        decode step.  Same signature/returns as :meth:`prefill` minus
        ``start_pos`` (prefix-cache hits take the chunked path).
        """
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh, axis_name, _, _ = self.cp
        B, T = tokens.shape
        rel = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        x = self._embed(params, tokens)
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, axis_name)))
        x, cache = self._run_layers(
            params, cache, x, "prefill_cp", positions=rel,
            page_tables=page_tables, lengths=true_lens, true_lens=true_lens,
            active=None, adapter_ids=adapter_ids)
        x = self._norm(x, params, "final_norm")
        last = jnp.take_along_axis(
            x, (true_lens - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return cache, self._logits(params, last), last

    def verify_window_logits(self, params, cache: KVCache, tokens,
                             true_lens, page_tables, start_pos,
                             adapter_ids=None):
        """Speculative-decoding verification forward: run a small window
        of proposed tokens (chunked-prefill machinery — paged history +
        causal window attention, KV written in place) and return the
        full-precision logits at EVERY window position.

        tokens: [B, W] (= [last_emitted, proposal...], -pad);
        true_lens: [B] valid window tokens (0 skips a slot — its writes
        mask to the null page); start_pos: [B] absolute position of the
        window start.  Returns (cache, logits [B, W, V] f32).  Callers
        jit this together with their acceptance rule (greedy argmax or
        ``sampler.spec_verify_sample``) so the [B, W, V] tensor never
        leaves the device.
        """
        B, W = tokens.shape
        rel = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32), (B, W))
        positions = rel + start_pos[:, None]
        x = self._embed(params, tokens)
        x, cache = self._run_layers(
            params, cache, x, "prefill", positions=positions,
            page_tables=page_tables, lengths=true_lens, true_lens=true_lens,
            active=None, start_pos=start_pos, adapter_ids=adapter_ids)
        x = self._norm(x, params, "final_norm")
        logits = self._logits(params, x).astype(jnp.float32)   # [B, W, V]
        return cache, logits

    def verify_window(self, params, cache: KVCache, tokens, true_lens,
                      page_tables, start_pos, adapter_ids=None):
        """Greedy verification (the n-gram speculative path): the
        :meth:`verify_window_logits` forward reduced to the GREEDY next
        token and its model logprob at every window position.

        Returns (cache, targets [B, W] int32, lps [B, W] f32).
        """
        from kaito_tpu.engine.sampler import chosen_logprob

        B, W = tokens.shape
        cache, logits = self.verify_window_logits(
            params, cache, tokens, true_lens, page_tables, start_pos,
            adapter_ids=adapter_ids)
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        flat_lp = chosen_logprob(logits.reshape(B * W, -1),
                                 targets.reshape(B * W))
        return cache, targets, flat_lp.reshape(B, W)

    def decode(self, params, cache: KVCache, tokens, positions, page_tables,
               active=None, adapter_ids=None):
        """One decode step for a batch of slots.

        tokens: [B] last sampled token; positions: [B] their positions;
        lengths after write are positions+1.  Returns (cache, logits).
        """
        B = tokens.shape[0]
        pos2 = positions[:, None].astype(jnp.int32)
        x = self._embed(params, tokens[:, None])
        x, cache = self._run_layers(
            params, cache, x, "decode", positions=pos2,
            page_tables=page_tables, lengths=positions + 1, true_lens=None,
            active=active, adapter_ids=adapter_ids)
        x = self._norm(x, params, "final_norm")
        return cache, self._logits(params, x[:, 0])

    def forward_train(self, params, tokens, mask=None, remat: bool = True):
        """Full-sequence forward for training: [B, T] -> logits [B, T, V].

        Rematerializes each layer (jax.checkpoint) so activation memory
        stays O(sqrt) — the TPU trade the reference never makes because
        HF Trainer owns its training loop.
        """
        B, T = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        true_lens = mask.sum(-1).astype(jnp.int32) if mask is not None else \
            jnp.full((B,), T, jnp.int32)
        x = self._embed(params, tokens)
        x, _ = self._run_layers(
            params, None, x, "train", positions=positions, page_tables=None,
            lengths=None, true_lens=true_lens, active=None, remat=remat)
        x = self._norm(x, params, "final_norm")
        return self._logits(params, x)
