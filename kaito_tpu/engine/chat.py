"""Chat-message formatting.

The reference ships per-model jinja chat templates
(``presets/workspace/inference/chat_templates/*.jinja``, 14 files) fed
to vLLM's ``--chat-template``.  We prefer the HF tokenizer's own
template when locally available; otherwise a model-family template
(llama-3, chatml/qwen, gemma, phi, mistral-inst, deepseek) selected
from the model id, falling back to generic ChatML.
"""

from __future__ import annotations

import json
from typing import Mapping, Optional, Sequence


def normalize_tool_messages(messages: Sequence[Mapping]) -> list:
    """Flatten tool-protocol messages into plain role/content turns.

    The per-family templates only understand ``{"role", "content"}``
    pairs, but multi-turn tool conversations carry two extra shapes the
    OpenAI API defines: assistant messages with a ``tool_calls`` list
    (and often null content), and ``role: "tool"`` result messages.
    Rendering those verbatim would drop the calls and emit an unknown
    role token, so the follow-up generation loses the context of what
    it called and what came back.

    Assistant tool calls are rendered as the same compact JSON envelope
    the constrained decoder emits (``{"name": ..., "arguments": ...}``),
    so the transcript the model sees round-trips its own output format.
    Tool results become ``tool`` turns with the call name folded into
    the content; templates without a native tool role still render them
    as a distinct turn.
    """
    out = []
    for m in messages:
        role = m.get("role", "user")
        if role == "assistant" and m.get("tool_calls"):
            parts = []
            content = m.get("content") or ""
            if content:
                parts.append(content)
            for call in m.get("tool_calls") or ():
                fn = (call or {}).get("function") or {}
                args = fn.get("arguments", "{}")
                if not isinstance(args, str):
                    args = json.dumps(args, separators=(",", ":"))
                parts.append(json.dumps(
                    {"name": fn.get("name", ""), "arguments": args},
                    separators=(",", ":")))
            out.append({"role": "assistant", "content": "\n".join(parts)})
        elif role == "tool":
            content = m.get("content") or ""
            if not isinstance(content, str):
                content = json.dumps(content, separators=(",", ":"))
            name = m.get("name") or ""
            if name:
                content = f"{name}: {content}"
            out.append({"role": "tool", "content": content})
        else:
            out.append(dict(m))
    return out


def _llama3(messages) -> str:
    out = ["<|begin_of_text|>"]
    for m in messages:
        out.append(f"<|start_header_id|>{m.get('role', 'user')}"
                   f"<|end_header_id|>\n\n"
                   f"{m.get('content', '').strip()}<|eot_id|>")
    out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


def _chatml(messages) -> str:
    out = []
    for m in messages:
        out.append(f"<|im_start|>{m.get('role', 'user')}\n"
                   f"{m.get('content', '')}<|im_end|>\n")
    out.append("<|im_start|>assistant\n")
    return "".join(out)


def _gemma(messages) -> str:
    out = ["<bos>"]
    for m in messages:
        role = "model" if m.get("role") == "assistant" else "user"
        out.append(f"<start_of_turn>{role}\n{m.get('content', '')}<end_of_turn>\n")
    out.append("<start_of_turn>model\n")
    return "".join(out)


def _phi3(messages) -> str:
    """phi-3 / phi-3.5 (reference phi-3.jinja): ``<|role|>`` turns, no
    BOS, content trimmed."""
    out = []
    for m in messages:
        out.append(f"<|{m.get('role', 'user')}|>\n"
                   f"{m.get('content', '').strip()}<|end|>\n")
    out.append("<|assistant|>\n")
    return "".join(out)


def _phi3_small(messages) -> str:
    """phi-3-small (reference phi-3-small.jinja): the phi-3 body with a
    leading BOS (phi-3-small's tokenizer BOS is ``<|endoftext|>``)."""
    return "<|endoftext|>" + _phi3(messages)


def _phi4(messages) -> str:
    """phi-4 / phi-4-mini (reference phi-4.jinja +
    tool-chat-phi4-mini.jinja): ChatML-with-``<|im_sep|>`` turns — NOT
    the phi-3 shape; the two families diverged at phi-4."""
    out = []
    for m in messages:
        out.append(f"<|im_start|>{m.get('role', 'user')}<|im_sep|>"
                   f"{m.get('content', '')}<|im_end|>")
    out.append("<|im_start|>assistant<|im_sep|>")
    return "".join(out)


def _mistral(messages) -> str:
    """mistral-instruct (reference mistral-instruct.jinja): a leading
    system message rides after the BOS as plain text (NOT inside the
    first [INST]); content is trimmed.  A NON-leading system message —
    where the reference jinja raise_exception's on the broken
    alternation — folds into the next user turn instead of being
    dropped or failing the request."""
    msgs = list(messages)
    lead = ""
    if msgs and msgs[0].get("role") == "system":
        lead = msgs[0].get("content", "").strip() + "\n\n"
        msgs = msgs[1:]
    out = ["<s>" + lead]
    pending_system = ""
    for m in msgs:
        role, content = m.get("role"), m.get("content", "").strip()
        if role == "system":
            pending_system = content
        elif role == "user":
            body = (f"{pending_system}\n\n{content}" if pending_system
                    else content)
            pending_system = ""
            out.append(f"[INST] {body} [/INST]")
        elif role == "assistant":
            out.append(f" {content}</s>")
        elif role == "tool":
            # mistral wire format carries tool results in their own
            # bracketed block, not inside [INST]
            out.append(f"[TOOL_RESULTS] {content} [/TOOL_RESULTS]")
    if pending_system:
        # a TRAILING system message (no user turn after it) still has
        # to steer the generation — emit it as its own instruction
        # block instead of silently dropping it
        out.append(f"[INST] {pending_system} [/INST]")
    return "".join(out)


def _deepseek(messages, strip_think: bool = False) -> str:
    """DeepSeek V3/R1 and the R1 distills (reference templates
    deepseek-r1-distill-*.jinja, tool-chat-deepseek{r1,v3}.jinja): the
    system prompt — wherever it appears — is COLLECTED and emitted once
    after the BOS, then ``<｜User｜>``/``<｜Assistant｜>`` turns.  The
    reasoning variants (``strip_think``) drop everything before the
    final ``</think>`` from prior assistant turns, exactly like the
    reference distill templates."""
    system = ""
    for m in messages:               # LAST system wins (reference ns.
        if m.get("role") == "system":  # system_prompt overwrite loop)
            system = m.get("content", "")
    out = ["<｜begin▁of▁sentence｜>" + system]
    for m in messages:
        role, content = m.get("role"), m.get("content", "")
        if role == "user":
            out.append(f"<｜User｜>{content}")
        elif role == "tool":
            # no dedicated tool turn in the distill templates — feed
            # the result back as a user turn so it isn't dropped
            out.append(f"<｜User｜>{content}")
        elif role == "assistant":
            if strip_think and "</think>" in content:
                content = content.split("</think>")[-1]
            out.append(f"<｜Assistant｜>{content}<｜end▁of▁sentence｜>")
    out.append("<｜Assistant｜>")
    return "".join(out)


def _deepseek_r1(messages) -> str:
    return _deepseek(messages, strip_think=True)


def _generic(messages) -> str:
    parts = []
    for m in messages:
        parts.append(f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


_FAMILY_TEMPLATES = (
    # ORDER ENCODES PRESET-LEVEL SPECIFICITY (most specific first, the
    # way tool formats key off the preset in engine/parsers.py):
    # - the R1 distills carry llama/qwen in their names but ship
    #   DeepSeek's template, and the reasoning variants strip <think>
    # - phi-3-small adds a BOS to the phi-3 shape; phi-4 switched the
    #   family to ChatML-with-<|im_sep|> (reference templates phi-3,
    #   phi-3-small, phi-4 .jinja all differ)
    (("deepseek-r1", "r1-distill"), _deepseek_r1),
    (("deepseek",), _deepseek),
    (("llama-3", "llama3"), _llama3),
    (("qwen", "chatml", "gpt-oss"), _chatml),
    (("gemma",), _gemma),
    (("phi-3-small",), _phi3_small),
    (("phi-4", "phi4"), _phi4),
    (("phi-", "phi3"), _phi3),
    (("mistral", "ministral", "mixtral"), _mistral),
)


def template_for(model_id: str):
    lowered = (model_id or "").lower()
    for keys, fn in _FAMILY_TEMPLATES:
        if any(k in lowered for k in keys):
            return fn
    return _generic


def render_chat(tokenizer, messages: Sequence[Mapping[str, str]],
                model_id: str = "") -> str:
    messages = normalize_tool_messages(messages)
    apply = getattr(tokenizer, "apply_chat_template", None)
    if apply is not None:
        try:
            return apply(list(messages), tokenize=False,
                         add_generation_prompt=True)
        except Exception:
            pass
    return template_for(model_id)(list(messages))
