"""Chat-message formatting.

The reference ships per-model jinja chat templates
(``presets/workspace/inference/chat_templates/*.jinja``, 14 files) fed
to vLLM's ``--chat-template``.  We use the HF tokenizer's own template
when one is locally available and fall back to a generic ChatML-style
rendering otherwise (serving synthetic checkpoints, tests).
"""

from __future__ import annotations

from typing import Mapping, Sequence


def render_chat(tokenizer, messages: Sequence[Mapping[str, str]]) -> str:
    apply = getattr(tokenizer, "apply_chat_template", None)
    if apply is not None:
        try:
            return apply(list(messages), tokenize=False, add_generation_prompt=True)
        except Exception:
            pass
    parts = []
    for m in messages:
        role = m.get("role", "user")
        content = m.get("content", "")
        parts.append(f"<|{role}|>\n{content}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)
