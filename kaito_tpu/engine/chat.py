"""Chat-message formatting.

The reference ships per-model jinja chat templates
(``presets/workspace/inference/chat_templates/*.jinja``, 14 files) fed
to vLLM's ``--chat-template``.  We prefer the HF tokenizer's own
template when locally available; otherwise a model-family template
(llama-3, chatml/qwen, gemma, phi, mistral-inst, deepseek) selected
from the model id, falling back to generic ChatML.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence


def _llama3(messages) -> str:
    out = ["<|begin_of_text|>"]
    for m in messages:
        out.append(f"<|start_header_id|>{m.get('role', 'user')}"
                   f"<|end_header_id|>\n\n{m.get('content', '')}<|eot_id|>")
    out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


def _chatml(messages) -> str:
    out = []
    for m in messages:
        out.append(f"<|im_start|>{m.get('role', 'user')}\n"
                   f"{m.get('content', '')}<|im_end|>\n")
    out.append("<|im_start|>assistant\n")
    return "".join(out)


def _gemma(messages) -> str:
    out = ["<bos>"]
    for m in messages:
        role = "model" if m.get("role") == "assistant" else "user"
        out.append(f"<start_of_turn>{role}\n{m.get('content', '')}<end_of_turn>\n")
    out.append("<start_of_turn>model\n")
    return "".join(out)


def _phi(messages) -> str:
    out = []
    for m in messages:
        out.append(f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}<|end|>\n")
    out.append("<|assistant|>\n")
    return "".join(out)


def _mistral(messages) -> str:
    out = ["<s>"]
    system = ""
    for m in messages:
        role, content = m.get("role"), m.get("content", "")
        if role == "system":
            system = content
        elif role == "user":
            body = f"{system}\n\n{content}" if system else content
            system = ""
            out.append(f"[INST] {body} [/INST]")
        else:
            out.append(f" {content}</s>")
    return "".join(out)


def _deepseek(messages) -> str:
    """DeepSeek V3/R1 (and the R1 distills, whose tokenizer configs
    carry the same template): ``<｜User｜>``/``<｜Assistant｜>`` turns
    after an optional leading system block (reference templates
    tool-chat-deepseek{r1,v3}.jinja)."""
    out = ["<｜begin▁of▁sentence｜>"]
    for m in messages:
        role, content = m.get("role"), m.get("content", "")
        if role == "system":
            out.append(content)
        elif role == "user":
            out.append(f"<｜User｜>{content}")
        else:
            out.append(f"<｜Assistant｜>{content}<｜end▁of▁sentence｜>")
    out.append("<｜Assistant｜>")
    return "".join(out)


def _generic(messages) -> str:
    parts = []
    for m in messages:
        parts.append(f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


_FAMILY_TEMPLATES = (
    # deepseek FIRST: the R1 distills carry llama/qwen in their names
    # but ship DeepSeek's own chat template
    (("deepseek",), _deepseek),
    (("llama-3", "llama3"), _llama3),
    (("qwen", "chatml", "gpt-oss"), _chatml),
    (("gemma",), _gemma),
    (("phi-", "phi3", "phi4"), _phi),
    (("mistral", "ministral", "mixtral"), _mistral),
)


def template_for(model_id: str):
    lowered = (model_id or "").lower()
    for keys, fn in _FAMILY_TEMPLATES:
        if any(k in lowered for k in keys):
            return fn
    return _generic


def render_chat(tokenizer, messages: Sequence[Mapping[str, str]],
                model_id: str = "") -> str:
    apply = getattr(tokenizer, "apply_chat_template", None)
    if apply is not None:
        try:
            return apply(list(messages), tokenize=False,
                         add_generation_prompt=True)
        except Exception:
            pass
    return template_for(model_id)(list(messages))
