"""Neural-net building blocks for the config-driven transformer.

Functional JAX (no module framework): parameters are plain pytrees so
the engine controls placement/donation precisely and trees map 1:1 onto
logical sharding axes (kaito_tpu.parallel.sharding).  Compute runs in
the params' dtype (bf16 on TPU) with fp32 norms/softmax, which is what
the MXU wants.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from kaito_tpu.models.metadata import ModelArch


def linear(x: jax.Array, w) -> jax.Array:
    """Matmul accepting either a plain weight or an int8 QTensor dict
    ``{"q8": int8[in,out], "scale": f32[out]}`` (per-out-channel
    symmetric quantization).  Under jit the int8 stays in HBM and the
    dequant fuses into the dot — the QLoRA memory model.
    """
    from kaito_tpu.engine.quant import is_qtensor

    if is_qtensor(w):
        return (x @ w["q8"].astype(x.dtype)) * w["scale"].astype(x.dtype)
    return x @ w


def lora_delta(x: jax.Array, p: dict, name: str, scaling: float) -> jax.Array:
    """Low-rank update ``(x @ A) @ B * (alpha/r)`` when the layer stack
    carries lora factors for ``name`` (keys set by kaito_tpu.tuning.lora)."""
    a = p.get(f"{name}_lora_a")
    if a is None:
        return 0.0
    b = p[f"{name}_lora_b"]
    return ((x @ a) @ b) * scaling


def multi_lora_delta(x: jax.Array, lora: Optional[dict], name: str,
                     ids: Optional[jax.Array]):
    """Per-request batched LoRA: each row of the batch applies ITS OWN
    adapter's low-rank update (adapter 0 is the all-zeros base).

    The serving counterpart of the reference's per-request vLLM
    LoRARequest routing (inference_api.py:417-498).  x: [B, T, E];
    lora[f"{name}_a"]: [n_adapters, E, r] (per-layer slice of the scan
    stack); ids: [B] int32.  Scaling is folded into B at load time.
    """
    if lora is None or ids is None:
        return 0.0
    a = lora.get(f"{name}_a")
    if a is None:
        return 0.0
    b = lora[f"{name}_b"]
    ax = jnp.einsum("bte,ber->btr", x, a[ids])
    return jnp.einsum("btr,bro->bto", ax, b[ids])


def rms_norm(x: jax.Array, scale: jax.Array, eps: float, offset: bool) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    w = scale.astype(jnp.float32)
    if offset:
        w = 1.0 + w
    return (y * w).astype(dtype)


def layer_norm(x: jax.Array, scale: jax.Array, bias: Optional[jax.Array], eps: float) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


def apply_norm(x: jax.Array, params: dict, arch: ModelArch) -> jax.Array:
    if arch.norm_type == "layernorm":
        return layer_norm(x, params["scale"], params.get("bias"), arch.rms_norm_eps)
    return rms_norm(x, params["scale"], arch.rms_norm_eps, arch.norm_offset)


# ---------------------------------------------------------------------------
# Rotary position embedding (with llama3 / linear / yarn-style scaling)
# ---------------------------------------------------------------------------

def rope_frequencies(arch: ModelArch) -> jax.Array:
    """Per-pair inverse frequencies, with rope_scaling applied."""
    rot_dim = int(arch.head_dim * arch.partial_rotary_factor)
    rot_dim -= rot_dim % 2
    exponent = jnp.arange(0, rot_dim, 2, dtype=jnp.float32) / rot_dim
    inv_freq = 1.0 / (arch.rope_theta ** exponent)

    scaling = arch.rope_scaling or {}
    rope_type = str(scaling.get("rope_type", scaling.get("type", ""))).lower()
    if rope_type == "linear":
        inv_freq = inv_freq / float(scaling.get("factor", 1.0))
    elif rope_type == "llama3":
        # Llama-3.1 frequency-dependent scaling: low-frequency components
        # are stretched by `factor`, high-frequency kept, mid smoothed.
        factor = float(scaling.get("factor", 8.0))
        low = float(scaling.get("low_freq_factor", 1.0))
        high = float(scaling.get("high_freq_factor", 4.0))
        old_len = float(scaling.get("original_max_position_embeddings", 8192))
        wavelen = 2.0 * math.pi / inv_freq
        low_wl = old_len / low
        high_wl = old_len / high
        smooth = (old_len / wavelen - low) / (high - low)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = jnp.where(
            wavelen > low_wl,
            inv_freq / factor,
            jnp.where(wavelen < high_wl, inv_freq,
                      (1 - smooth) * inv_freq / factor + smooth * inv_freq),
        )
        inv_freq = scaled
    elif rope_type in ("yarn", "longrope"):
        # Serving-grade approximation: plain NTK-by-parts is replaced by
        # uniform interpolation at the trained factor; exact yarn ramps
        # land with the long-context milestone.
        inv_freq = inv_freq / float(scaling.get("factor", 1.0))
    return inv_freq


def apply_rope(x: jax.Array, positions: jax.Array, inv_freq: jax.Array,
               head_dim: int) -> jax.Array:
    """Rotate the first ``2*len(inv_freq)`` dims of each head.

    x: [..., seq, heads, head_dim]; positions: [..., seq].
    """
    rot = 2 * inv_freq.shape[0]
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # [..., seq, rot/2]
    cos = jnp.cos(angles)[..., :, None, :]
    sin = jnp.sin(angles)[..., :, None, :]
    x_rot = x[..., :rot].astype(jnp.float32)
    x_pass = x[..., rot:]
    x1, x2 = jnp.split(x_rot, 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([out.astype(x.dtype), x_pass], axis=-1)


def activation(x: jax.Array, name: str) -> jax.Array:
    if name == "silu":
        return jax.nn.silu(x)
    if name in ("gelu",):
        return jax.nn.gelu(x, approximate=False)
    if name in ("gelu_tanh", "gelu_new"):
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown activation {name!r}")


def mlp(x: jax.Array, p: dict, arch: ModelArch, lora_scaling: float = 0.0,
        serve_lora: Optional[dict] = None,
        lora_ids: Optional[jax.Array] = None) -> jax.Array:
    """Gated (SwiGLU/GeGLU) or classic 2-matrix MLP."""
    if arch.gated_mlp:
        gate = activation(linear(x, p["gate"]) + lora_delta(x, p, "gate", lora_scaling)
                          + multi_lora_delta(x, serve_lora, "gate", lora_ids),
                          arch.hidden_act)
        up = linear(x, p["up"]) + lora_delta(x, p, "up", lora_scaling) \
            + multi_lora_delta(x, serve_lora, "up", lora_ids)
        h = gate * up
    else:
        h = linear(x, p["up"]) + lora_delta(x, p, "up", lora_scaling) \
            + multi_lora_delta(x, serve_lora, "up", lora_ids)
        if "up_bias" in p:
            h = h + p["up_bias"]
        h = activation(h, arch.hidden_act)
    out = linear(h, p["down"]) + lora_delta(h, p, "down", lora_scaling) \
        + multi_lora_delta(h, serve_lora, "down", lora_ids)
    if "down_bias" in p:
        out = out + p["down_bias"]
    return out


def moe_mlp(x: jax.Array, p: dict, arch: ModelArch) -> jax.Array:
    """Token-choice MoE with dense expert compute.

    x: [T, E].  Routing picks top-k experts per token; compute is done
    as dense einsums over all experts with a routing-weight mask —
    static shapes, MXU-friendly, exact (at the cost of FLOPs
    proportional to expert count; a Pallas grouped-matmul replaces this
    on the perf milestone).
    """
    T, E = x.shape
    X = arch.num_experts
    k = arch.num_experts_per_tok
    logits = (x.astype(jnp.float32) @ p["router"].astype(jnp.float32))  # [T, X]
    weights, idx = jax.lax.top_k(logits, k)                             # [T, k]
    weights = jax.nn.softmax(weights, axis=-1)
    # scatter top-k weights back to a dense [T, X] routing matrix
    route = jnp.zeros((T, X), jnp.float32)
    route = route.at[jnp.arange(T)[:, None], idx].set(weights)
    # dense expert compute: h[x] = act(x @ gate_x) * (x @ up_x) @ down_x
    def expert_dot(spec, lhs, w):
        """einsum accepting a plain [X, in, out] stack or an int8
        QTensor {"q8", "scale": [X, out]} (dequant fuses into the dot;
        the per-expert scale rides the output's [x, out] dims)."""
        from kaito_tpu.engine.quant import is_qtensor

        if is_qtensor(w):
            return jnp.einsum(spec, lhs, w["q8"].astype(lhs.dtype)) \
                * w["scale"].astype(lhs.dtype)
        return jnp.einsum(spec, lhs, w)

    gate = expert_dot("te,xei->txi", x, p["experts_gate"])
    up = expert_dot("te,xei->txi", x, p["experts_up"])
    h = activation(gate, arch.hidden_act) * up
    out = expert_dot("txi,xie->txe", h, p["experts_down"])
    y = jnp.einsum("txe,tx->te", out.astype(jnp.float32), route).astype(x.dtype)
    if "shared_gate" in p:
        shared = {"gate": p["shared_gate"], "up": p["shared_up"], "down": p["shared_down"]}
        y = y + mlp(x, shared, arch)
    return y


def moe_mlp_ragged(x: jax.Array, p: dict, arch: ModelArch) -> jax.Array:
    """Token-choice MoE via grouped (ragged) matmuls.

    Tokens sort by assigned expert and each expert runs one matmul over
    its contiguous group (``lax.ragged_dot`` — XLA's grouped-GEMM,
    megablox-style on TPU).  FLOPs scale with top_k instead of the
    expert count, unlike the dense fallback in :func:`moe_mlp`.
    Serving-path implementation; training keeps the dense form.
    """
    T, E = x.shape
    X = arch.num_experts
    k = arch.num_experts_per_tok
    logits = x.astype(jnp.float32) @ p["router"].astype(jnp.float32)
    weights, idx = jax.lax.top_k(logits, k)            # [T, k]
    weights = jax.nn.softmax(weights, axis=-1)

    flat_expert = idx.reshape(-1)                      # [T*k]
    order = jnp.argsort(flat_expert)                   # stable
    token_of = order // k                              # originating token
    x_sorted = x[token_of]                             # [T*k, E]
    group_sizes = jnp.bincount(flat_expert, length=X)
    expert_of_row = flat_expert[order]                 # [T*k]

    def ragged(lhs, w):
        """ragged_dot accepting a plain stack or an int8 QTensor: the
        convert fuses into the grouped GEMM's RHS load, and each row's
        output scales by its expert's per-out-channel scale."""
        from kaito_tpu.engine.quant import is_qtensor

        if is_qtensor(w):
            out = jax.lax.ragged_dot(lhs, w["q8"].astype(lhs.dtype),
                                     group_sizes,
                                     preferred_element_type=jnp.float32)
            return out * w["scale"][expert_of_row].astype(out.dtype)
        return jax.lax.ragged_dot(lhs, w, group_sizes,
                                  preferred_element_type=jnp.float32)

    gate = ragged(x_sorted, p["experts_gate"])
    up = ragged(x_sorted, p["experts_up"])
    h = (activation(gate, arch.hidden_act) * up).astype(x.dtype)
    out_sorted = ragged(h, p["experts_down"])

    w_sorted = weights.reshape(-1)[order]
    y = jnp.zeros((T, E), jnp.float32).at[token_of].add(
        out_sorted * w_sorted[:, None])
    y = y.astype(x.dtype)
    if "shared_gate" in p:
        shared = {"gate": p["shared_gate"], "up": p["shared_up"],
                  "down": p["shared_down"]}
        y = y + mlp(x, shared, arch)
    return y


def softcap(x: jax.Array, cap: Optional[float]) -> jax.Array:
    if not cap:
        return x
    return jnp.tanh(x / cap) * cap
